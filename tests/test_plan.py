"""Logical-plan suite (engine/plan.py — ISSUE 11).

The acceptance bars:

- **per-pass byte-identity**: each rewrite pass toggled alone (and all
  together) must produce byte-identical results vs all-off, across the
  map_rows / map_blocks / mixed / select / filter / reduce / aggregate
  matrix — including under ``jobs.block`` chaos and a REAL subprocess
  kill + cross-process resume of a journaled fused plan;
- **one compiled program**: a 3-op map chain + reduce lowers to exactly
  one jit build (the existing ``engine.jit_cache_builds_total``
  accounting);
- **pruning is provable**: a source column bound only by a dead op
  never crosses the link (``frame.h2d_bytes_total`` delta assert).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.engine import plan as plan_mod
from tensorframes_tpu.engine import resume_job, run_job, run_worker, wait_job
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import chaos, get_config, set_config

pytestmark = pytest.mark.plan

_PLAN_KNOBS = (
    "plan_lazy_ops", "plan_fuse_maps", "plan_prune_columns",
    "plan_hoist_reduce",
)


@pytest.fixture(autouse=True)
def _restore_plan_config():
    prev = {k: getattr(get_config(), k) for k in _PLAN_KNOBS}
    yield
    set_config(**prev)


def _toggles(**on):
    """Config dict with the plan layer on and ONLY the named passes."""
    d = {
        "plan_lazy_ops": True,
        "plan_fuse_maps": False,
        "plan_prune_columns": False,
        "plan_hoist_reduce": False,
    }
    d.update(on)
    return d


#: the per-pass matrix: all-off is the reference the others must match
TOGGLE_SETS = {
    "legacy": {"plan_lazy_ops": False},
    "all_off": _toggles(),
    "fuse_only": _toggles(plan_fuse_maps=True),
    "prune_only": _toggles(plan_prune_columns=True),
    "hoist_only": _toggles(plan_hoist_reduce=True),
    "all_on": _toggles(
        plan_fuse_maps=True, plan_prune_columns=True, plan_hoist_reduce=True
    ),
}


def _counter(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _frame_bytes(df):
    """(schema names, per-column raw bytes) — the byte-identity probe."""
    df.cache()
    out = {}
    for name in df.schema.names:
        cd = df.column_data(name)
        if cd.dense is not None:
            h = np.asarray(cd.host())
            out[name] = (str(h.dtype), h.shape, h.tobytes())
        else:
            out[name] = [
                (c if isinstance(c, bytes) else np.asarray(c).tobytes())
                for c in cd.iter_cells()
            ]
    return df.schema.names, out


def _reduce_bytes(val):
    vals = val if isinstance(val, list) else [val]
    return [(str(np.asarray(v).dtype), np.asarray(v).tobytes()) for v in vals]


# module-level programs: defined once so graph memos hold across runs
def _f1(x):
    return {"h1": x * 2.0 + 1.0}


def _f2(h1):
    return {"h2": h1 @ np.full((4, 4), 0.5, np.float32) + h1}


def _f3(h2):
    return {"h3": h2.sum(axis=-1) if h2.ndim == 1 else h2}


def _fb1(x):
    return {"a": x * 3.0}


def _fb2(a, x):
    return {"b": a + x}


def _fdead(y):
    return {"dead": (y * y).sum(axis=-1)}


def _fred(h1_input):
    return {"h1": h1_input.sum(axis=0)}


def _fred3(h3_input):
    return {"h3": h3_input.sum(axis=0)}


def _ov2(a):
    return {"o2": a + 1.0}


def _ov3(o2):
    return {"o3": o2 * 0.5}


def _ovred(o3_input):
    return {"o3": o3_input.sum(axis=0)}


def _fagg(h1_input):
    return {"h1": h1_input.sum(axis=0)}


def _src(n=96, parts=3, seed=0):
    rng = np.random.default_rng(seed)
    return tft.TensorFrame.from_columns(
        {
            "x": rng.normal(size=(n, 4)).astype(np.float32),
            "y": rng.normal(size=(n, 8)).astype(np.float32),
            "k": (np.arange(n) % 5).astype(np.int32),
        },
        num_partitions=parts,
    ).analyze()


# the pipeline matrix: name -> builder(df) -> lazy frame or eager value
PIPELINES = {
    "map_rows_chain": lambda df: _f3_chain(df),
    "map_blocks_chain": lambda df: _fb_chain(df),
    "mixed_chain": lambda df: _mixed_chain(df),
    "chain_select": lambda df: _f3_chain(df).select(("h3", "z"), "x"),
    "chain_filter": lambda df: _f3_chain(df).filter_rows(
        np.arange(df.num_rows) % 2 == 0
    ),
    "chain_dead_op_select": lambda df: _dead_chain(df).select("h1", "k"),
    "chain_reduce": lambda df: tft.reduce_blocks(_fred, _f1_only(df)),
    "chain_aggregate": lambda df: _f1_only(df)
    .group_by("k")
    .aggregate(_fagg),
}


def _f1_only(df):
    m1 = tft.map_rows(_f1, df)
    return tft.map_rows(_fdead, m1)  # dead for reduce/aggregate demand


def _f3_chain(df):
    m1 = tft.map_rows(_f1, df)
    m2 = tft.map_rows(_f2, m1)
    return tft.map_rows(_f3, m2)


def _fb_chain(df):
    m1 = tft.map_blocks(_fb1, df)
    m2 = tft.map_blocks(_fb2, m1)
    return m2


def _mixed_chain(df):
    m1 = tft.map_rows(_f1, df)
    m2 = tft.map_blocks(lambda h1: {"m": h1 * 0.25}, m1)
    return tft.map_rows(lambda m: {"q": m.sum()}, m2)


def _dead_chain(df):
    m1 = tft.map_rows(_f1, df)
    return tft.map_rows(_fdead, m1)


def _run(pipeline, toggles, seed=0):
    set_config(**toggles)
    try:
        out = PIPELINES[pipeline](_src(seed=seed))
        if isinstance(out, tft.TensorFrame):
            return _frame_bytes(out)
        return _reduce_bytes(out)
    finally:
        set_config(**TOGGLE_SETS["all_on"])


class TestByteIdentityMatrix:
    """Each pass alone (and all together) vs all-off, per pipeline."""

    @pytest.mark.parametrize("pipeline", sorted(PIPELINES))
    @pytest.mark.parametrize(
        "mode", [m for m in TOGGLE_SETS if m != "all_off"]
    )
    def test_pass_matrix_byte_identical(self, pipeline, mode):
        ref = _run(pipeline, TOGGLE_SETS["all_off"])
        got = _run(pipeline, TOGGLE_SETS[mode])
        assert got == ref

    def test_mixed_chain_with_ragged_column_falls_back(self):
        # a ragged source column in a block-lowered group: the group
        # must degrade to op-at-a-time, not miscompute or crash
        cells = [np.arange(k, dtype=np.float32) for k in (3, 5, 3, 7, 5, 3)]
        df = tft.TensorFrame.from_columns({"r": cells})

        def build(d):
            m1 = tft.map_rows(lambda r: {"s": r.sum()}, d)
            return tft.map_blocks(lambda s: {"t": s * 2.0}, m1)

        set_config(**TOGGLE_SETS["all_on"])
        got = _frame_bytes(build(df))
        set_config(plan_lazy_ops=False)
        ref = _frame_bytes(
            build(tft.TensorFrame.from_columns({"r": cells}))
        )
        assert got == ref

    def test_constants_fuse_without_collision(self):
        x = np.arange(16, dtype=np.float32)

        def build(d):
            c1 = tft.map_blocks(
                lambda x, c: {"a": x + c}, d, constants={"c": np.float32(2)}
            )
            return tft.map_blocks(
                lambda a, c: {"b": a * c}, c1,
                constants={"c": np.float32(3)},
            )

        set_config(**TOGGLE_SETS["all_on"])
        got = _frame_bytes(
            build(tft.TensorFrame.from_columns({"x": x}))
        )
        set_config(plan_lazy_ops=False)
        ref = _frame_bytes(
            build(tft.TensorFrame.from_columns({"x": x}))
        )
        assert got == ref


class TestProgramCount:
    def test_three_map_chain_plus_reduce_is_one_program(self):
        """The tentpole acceptance: 3 chained maps + reduce on one
        partition lower to exactly ONE jit build (the fused hoisted
        partial program; no merge program is ever built for a single
        partition). Fresh lambdas guarantee fresh graphs, so the delta
        in the existing program accounting is exactly this chain's."""
        set_config(**TOGGLE_SETS["all_on"])
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        j0 = _counter("engine.jit_cache_builds_total")
        f0 = _counter("plan.fused_ops_total")
        m1 = tft.map_rows(lambda x: {"c1": x * 2.0}, df)
        m2 = tft.map_rows(lambda c1: {"c2": c1 + 1.0}, m1)
        m3 = tft.map_rows(lambda c2: {"c3": c2 * 0.5}, m2)
        out = tft.reduce_blocks(
            lambda c3_input: {"c3": c3_input.sum(axis=0)}, m3
        )
        assert np.asarray(out).shape == (4,)
        assert _counter("engine.jit_cache_builds_total") - j0 == 1
        # 3 maps + the reduce absorbed into the one program
        assert _counter("plan.fused_ops_total") - f0 == 4
        assert _counter("plan.passes_total", **{"pass": "hoist_reduce"}) > 0

    def test_fused_map_chain_is_one_program(self):
        set_config(**TOGGLE_SETS["all_on"])
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        m1 = tft.map_rows(lambda x: {"d1": x * 2.0}, df)
        m2 = tft.map_rows(lambda d1: {"d2": d1 + 1.0}, m1)
        m3 = tft.map_rows(lambda d2: {"d3": d2 * 0.5}, m2)
        j0 = _counter("engine.jit_cache_builds_total")
        m3.cache()
        assert _counter("engine.jit_cache_builds_total") - j0 == 1

    def test_fused_program_reused_across_forces(self):
        """Repeated pipelines over the same functions reuse ONE
        composite (and its jit program) — the compile-once contract."""
        set_config(**TOGGLE_SETS["all_on"])
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        for i in range(3):
            df = tft.TensorFrame.from_columns({"x": x}).analyze()
            out = _f3_chain(df)
            j0 = _counter("engine.jit_cache_builds_total")
            out.cache()
            if i:
                assert (
                    _counter("engine.jit_cache_builds_total") - j0 == 0
                )


class TestColumnPruning:
    def test_pruned_column_never_crosses_the_link(self):
        """The provable h2d delta: `y` is bound only by a dead op, so a
        fused+pruned run uploads exactly `x`'s bytes; the op-at-a-time
        run uploads both."""
        n = 256
        rng = np.random.default_rng(3)
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = rng.normal(size=(n, 16)).astype(np.float32)

        def build(d):
            m1 = tft.map_rows(_f1, d)
            m2 = tft.map_rows(_fdead_y, m1)
            return m2.select("h1", "x")

        set_config(**TOGGLE_SETS["all_on"])
        df = tft.TensorFrame.from_columns({"x": x, "y": y}).analyze()
        p0 = _counter("plan.pruned_columns_total")
        h0 = _counter("frame.h2d_bytes_total")
        got = build(df).cache()
        assert _counter("frame.h2d_bytes_total") - h0 == x.nbytes
        assert _counter("plan.pruned_columns_total") - p0 >= 2  # dead+y
        # the unfused reference uploads BOTH columns
        set_config(plan_lazy_ops=False)
        df2 = tft.TensorFrame.from_columns({"x": x, "y": y}).analyze()
        h1 = _counter("frame.h2d_bytes_total")
        ref = build(df2).cache()
        assert (
            _counter("frame.h2d_bytes_total") - h1 == x.nbytes + y.nbytes
        )
        assert _frame_bytes(got) == _frame_bytes(ref)

    def test_reduce_demand_prunes_dead_op(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        h0 = _counter("frame.h2d_bytes_total")
        out = tft.reduce_blocks(_fred, _f1_only(df))
        uploaded = _counter("frame.h2d_bytes_total") - h0
        # only x (the live op's input) crossed; y (dead op) never did
        assert uploaded == df.column_data("x").host().nbytes
        assert np.asarray(out).shape == (4,)


def _fdead_y(y):
    return {"dead": (y * y).sum(axis=-1)}


class TestLaziness:
    def test_select_and_filter_do_not_force(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        m = _f3_chain(df)
        s = m.select("h3")
        f = m.filter_rows(np.arange(96) % 2 == 0)
        assert m.is_lazy and s.is_lazy and f.is_lazy

    def test_intermediates_stay_lazy_and_force_correctly_later(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        m1 = tft.map_rows(_f1, df)
        m2 = tft.map_rows(_f2, m1)
        m2.cache()
        assert m1.is_lazy
        # forcing the intermediate later re-runs its own prefix,
        # byte-identically to a standalone run
        got = np.asarray(m1.column_data("h1").host())
        set_config(plan_lazy_ops=False)
        ref = np.asarray(
            tft.map_rows(_f1, _src()).column_data("h1").host()
        )
        assert got.tobytes() == ref.tobytes()

    def test_forced_intermediate_acts_as_source(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        m1 = tft.map_rows(_f1, df).cache()  # concrete
        m2 = tft.map_rows(_f2, m1)
        node = m2._plan_node
        src, ops = plan_mod._chain(node)
        assert src is m1 and len(ops) == 1

    def test_errors_still_surface_at_the_call_site(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        m = tft.map_rows(_f1, df)
        with pytest.raises(KeyError):
            m.select("nope")
        from tensorframes_tpu.engine import InputNotFoundError

        with pytest.raises(InputNotFoundError):
            tft.map_rows(lambda missing: {"o": missing}, m)


class TestExplain:
    def test_explain_renders_plan_without_forcing(self):
        set_config(**TOGGLE_SETS["all_on"])
        df = _src()
        out = _dead_chain(df).select("h1", "k")
        txt = tft.explain(out)
        assert out.is_lazy  # rendering must not execute
        assert "== Logical plan ==" in txt
        assert "map_rows" in txt and "select" in txt
        assert "prune_columns" in txt
        assert "dead" in txt  # the dead fetch is named
        assert "y" in txt  # the pruned source column is named
        assert "fused programs: 1" in txt
        assert "== Schema ==" in txt  # schema text still included

    def test_explain_concrete_frame_is_schema_only(self):
        df = _src()
        assert tft.explain(df).startswith("root")


class TestJournaledPipelines:
    def _chain(self, df):
        m1 = tft.map_rows(_f1, df)
        m2 = tft.map_rows(_f2, m1)
        return tft.map_rows(_f3, m2).select("h3", "x")

    def _jsrc(self, n=96):
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        return (
            tft.TensorFrame.from_columns({"x": x}).analyze().repartition(3)
        )

    def _ref(self):
        set_config(plan_lazy_ops=False)
        try:
            return _frame_bytes(self._chain(self._jsrc()))
        finally:
            set_config(**TOGGLE_SETS["all_on"])

    def test_pipeline_job_byte_identical_and_one_fingerprint(
        self, tmp_path
    ):
        set_config(**TOGGLE_SETS["all_on"])
        res = run_job(
            "pipeline", None, self._chain(self._jsrc()),
            job_dir=str(tmp_path), job_id="p1",
        )
        assert res.op == "map_rows" and res.blocks_computed > 0
        assert _frame_bytes(res.completed) == self._ref()
        # resume with a REBUILT plan (fresh lambdas upstream are fine:
        # the fingerprint is structural) restores every block
        res2 = resume_job(
            os.path.join(str(tmp_path), "p1"), None,
            self._chain(self._jsrc()),
        )
        assert res2.blocks_restored == res2.blocks_total
        assert _frame_bytes(res2.completed) == self._ref()

    @pytest.mark.chaos
    def test_fused_plan_under_jobs_block_chaos(self, tmp_path):
        """Transient jobs.block faults inside a journaled fused plan
        retry per block; the output stays byte-identical."""
        set_config(**TOGGLE_SETS["all_on"])
        with chaos.scoped("seed=11;jobs.block=transient:every=2"):
            res = run_job(
                "pipeline", None, self._chain(self._jsrc()),
                job_dir=str(tmp_path), job_id="pc",
            )
        assert not res.quarantined
        assert _frame_bytes(res.completed) == self._ref()

    @pytest.mark.chaos
    def test_kill_and_resume_journaled_fused_plan(self, tmp_path):
        """A REAL process death mid-pipeline: the child journals a
        fused 3-op plan and is killed by a chaos fatal in the journal
        writer; this process rebuilds the plan from scratch and resumes
        — restored + recomputed blocks assemble byte-identically."""
        job_dir = str(tmp_path)
        script = (
            "import numpy as np, tensorframes_tpu as tft\n"
            "from tensorframes_tpu.engine import run_job\n"
            "from tensorframes_tpu.utils import set_config\n"
            "set_config(max_rows_per_device_call=16)\n"
            "x = np.arange(384, dtype=np.float32).reshape(96, 4)\n"
            "df = tft.TensorFrame.from_columns({'x': x}).analyze()"
            ".repartition(3)\n"
            "m1 = tft.map_rows(lambda x: {'h1': x * 2.0 + 1.0}, df)\n"
            "m2 = tft.map_rows(lambda h1: {'h2': h1 @ np.full((4, 4), "
            "0.5, np.float32) + h1}, m1)\n"
            # the EXACT program _f3 traces: the fingerprint is
            # structural, so a different body with the same signature
            # is the caller's contract to avoid (same as resume_job)
            "m3 = tft.map_rows(lambda h2: {'h3': h2.sum(axis=-1) "
            "if h2.ndim == 1 else h2}, m2)\n"
            "run_job('pipeline', None, m3.select('h3', 'x'),\n"
            f"        job_dir={job_dir!r}, job_id='child')\n"
        )
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            TFT_CHAOS="jobs.journal_write=fatal:every=3:times=1",
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert proc.returncode != 0, proc.stdout + proc.stderr
        assert "ChaosFault" in proc.stderr
        path = os.path.join(job_dir, "child")
        assert os.path.exists(os.path.join(path, "manifest.json"))

        set_config(**TOGGLE_SETS["all_on"])
        prev = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            res = resume_job(path, None, self._chain(self._jsrc()))
            assert res.blocks_restored >= 1, "child recorded nothing"
            assert res.blocks_computed >= 1, "kill left a full journal"
            set_config(plan_lazy_ops=False)
            ref = _frame_bytes(self._chain(self._jsrc()))
            set_config(plan_lazy_ops=True)
            assert _frame_bytes(res.completed) == ref
        finally:
            set_config(max_rows_per_device_call=prev)

    def test_distributed_worker_drains_fused_plan(self, tmp_path):
        set_config(**TOGGLE_SETS["all_on"])
        path = os.path.join(str(tmp_path), "dp")
        rep = run_worker(
            "pipeline", None, self._chain(self._jsrc()), path=path
        )
        assert rep.complete and rep.blocks_computed > 0
        res = wait_job(path, None, self._chain(self._jsrc()), timeout_s=60)
        assert _frame_bytes(res.completed) == self._ref()

    def test_journaled_hoisted_reduce_resumes(self, tmp_path):
        set_config(**TOGGLE_SETS["all_on"])
        df = self._jsrc()
        m1 = tft.map_rows(_f1, df)
        res = run_job(
            "reduce_blocks", _fred, m1,
            job_dir=str(tmp_path), job_id="hr",
        )
        ref = _reduce_bytes(res.completed)
        res2 = resume_job(
            os.path.join(str(tmp_path), "hr"), _fred,
            tft.map_rows(_f1, self._jsrc()),
        )
        assert res2.blocks_restored == res2.blocks_total
        assert _reduce_bytes(res2.completed) == ref

    @pytest.mark.chaos
    def test_quarantined_pipeline_skips_trailing_filter(self, tmp_path):
        """A trailing filter_rows mask is recorded against FULL-frame
        row positions; when quarantine drops a block's rows the mask no
        longer lines up, so post-ops must be skipped (partial result
        surfaces untouched) rather than silently selecting wrong rows."""
        set_config(**TOGGLE_SETS["all_on"])
        mask = np.arange(96) % 2 == 0

        def chain():
            m1 = tft.map_rows(_f1, self._jsrc())
            return m1.filter_rows(mask)

        prev = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)  # 6 journal blocks
        try:
            with chaos.scoped("seed=5;jobs.block=fatal:every=3:times=1"):
                res = run_job(
                    "pipeline", None, chain(),
                    job_dir=str(tmp_path), job_id="qf", strict=False,
                )
            assert res.quarantined, "the chaos fatal never quarantined"
            # the partial result keeps the surviving blocks' FULL rows —
            # the misaligned mask was not applied
            dropped = sum(q.rows for q in res.quarantined)
            assert res.completed.num_rows == 96 - dropped
            # a clean run applies the filter normally
            res2 = run_job(
                "pipeline", None, chain(),
                job_dir=str(tmp_path), job_id="qf2",
            )
            assert not res2.quarantined
            assert res2.completed.num_rows == int(mask.sum())
        finally:
            set_config(max_rows_per_device_call=prev)

    def test_pipeline_rejects_concrete_frames(self, tmp_path):
        set_config(**TOGGLE_SETS["all_on"])
        with pytest.raises(ValueError, match="pending lazy planned"):
            run_job("pipeline", None, self._jsrc(), job_dir=str(tmp_path))


class TestOverhead:
    def test_fused_framework_overhead_is_lower(self):
        """The bench (`make bench-pipeline`) publishes the ≥2× number;
        this test pins a conservative floor so a regression that erodes
        the win fails loudly without making CI timing-flaky."""
        import time

        set_config(**TOGGLE_SETS["all_on"])
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = rng.normal(size=(64, 8)).astype(np.float32)
        # one frame, built outside the timed loop: frame construction +
        # analyze cost the same in both modes and would swamp the
        # per-op framework overhead being compared. The pipeline mirrors
        # the bench's: a map_blocks chain + a dead decoy op + a hoisted
        # reduce — 5 logical ops collapsing to one program.
        df = tft.TensorFrame.from_columns({"x": x, "y": y}).analyze()

        def run_once():
            m1 = tft.map_blocks(_fb1, df)
            m2 = tft.map_blocks(_ov2, m1)
            m3 = tft.map_blocks(_ov3, m2)
            m4 = tft.map_blocks(_fdead_y, m3)
            return tft.reduce_blocks(_ovred, m4)

        def best_of(k=25):
            best = float("inf")
            for _ in range(k):
                t0 = time.perf_counter()
                run_once()
                best = min(best, time.perf_counter() - t0)
            return best

        run_once()  # warm compiles
        fused = best_of()
        set_config(plan_lazy_ops=False)
        run_once()  # warm the unfused programs too
        eager = best_of()
        # a deliberately loose floor: min-of-25 wall clocks on shared CI
        # boxes still jitter by tens of µs, and the honest ratio moves
        # with workload shape (the bench's own config measures 2.3×).
        # What must never regress is the *direction*: the fused pipeline
        # strictly beats op-at-a-time on framework overhead.
        assert fused < eager / 1.1, (fused, eager)


class TestObs:
    def test_plan_metrics_and_span(self, tmp_path):
        set_config(**TOGGLE_SETS["all_on"])
        from tensorframes_tpu import obs as obs_pkg

        sink = tmp_path / "spans.jsonl"
        obs_pkg.set_trace_sink(str(sink))
        try:
            p0 = _counter("plan.passes_total", **{"pass": "fuse_maps"})
            _f3_chain(_src()).cache()
            assert (
                _counter("plan.passes_total", **{"pass": "fuse_maps"})
                == p0 + 1
            )
        finally:
            obs_pkg.set_trace_sink(None)
        assert '"plan.optimize"' in sink.read_text()
