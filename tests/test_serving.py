"""The TPU-host scoring service: Arrow over a socket, engine on the host.

The reference ran its engine inside every executor; on TPU the
partitions must come to the chip instead. These tests drive the
server/client pair exactly as Spark's ``mapInArrow`` would — the client
closure writes a whole partition before reading anything — without
needing a cluster (the closure is the same object ``remote_map_in_arrow``
ships to executors).
"""

import threading

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from tensorframes_tpu.interop import (  # noqa: E402
    ScoringServer,
    remote_arrow_mapper,
)


def _batches(xs, batch_rows=None):
    t = pa.table({"x": pa.array(xs, type=pa.float32())})
    return t.to_batches(max_chunksize=batch_rows) if batch_rows else t.to_batches()


def _score(x):
    return {"y": x * 2.0 + 1.0}


def test_round_trip_single_partition():
    with ScoringServer(_score) as addr:
        fn = remote_arrow_mapper(addr)
        out = list(fn(_batches(np.arange(100.0, dtype=np.float32))))
        t = pa.Table.from_batches(out)
        np.testing.assert_allclose(
            t.column("y").to_numpy(), np.arange(100.0) * 2.0 + 1.0
        )
        # input columns carry through (trim=False default)
        assert "x" in t.column_names


def test_partition_is_the_block_not_the_wire_chunking():
    """Cross-row block semantics: all of one connection's batches form
    ONE block, so a block mean sees the whole partition."""

    def demean(x):
        return {"d": x - x.mean()}

    xs = np.arange(64.0, dtype=np.float32)
    with ScoringServer(demean) as addr:
        fn = remote_arrow_mapper(addr)
        out = pa.Table.from_batches(list(fn(_batches(xs, batch_rows=7))))
    np.testing.assert_allclose(
        out.column("d").to_numpy(), xs - xs.mean(), rtol=1e-6
    )


def test_concurrent_partitions_share_the_server():
    xs = [np.arange(50.0, dtype=np.float32) + 100 * i for i in range(6)]
    results = [None] * len(xs)
    with ScoringServer(_score) as addr:
        fn = remote_arrow_mapper(addr)

        def work(i):
            results[i] = pa.Table.from_batches(list(fn(_batches(xs[i]))))

        ts = [threading.Thread(target=work, args=(i,)) for i in range(len(xs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    for i, t in enumerate(results):
        np.testing.assert_allclose(
            t.column("y").to_numpy(), xs[i] * 2.0 + 1.0
        )


def test_trim_and_feed_dict():
    def scorer(v):
        return {"out": v * 3.0}

    with ScoringServer(
        scorer, trim=True, feed_dict={"v": "x"}
    ) as addr:
        fn = remote_arrow_mapper(addr)
        out = pa.Table.from_batches(
            list(fn(_batches(np.arange(10.0, dtype=np.float32))))
        )
    assert out.column_names == ["out"]
    np.testing.assert_allclose(out.column("out").to_numpy(), np.arange(10.0) * 3)


def test_empty_iterator_yields_nothing():
    with ScoringServer(_score) as addr:
        fn = remote_arrow_mapper(addr)
        assert list(fn(iter([]))) == []


def test_streaming_mode_bounds_frame_memory():
    # row-local program per incoming batch; results equal the buffered path
    xs = np.arange(40.0, dtype=np.float32)
    with ScoringServer(_score, streaming=True) as addr:
        fn = remote_arrow_mapper(addr)
        out = pa.Table.from_batches(list(fn(_batches(xs, batch_rows=6))))
    np.testing.assert_allclose(out.column("y").to_numpy(), xs * 2 + 1)


def test_mapper_closure_is_executor_portable(tmp_path):
    """The closure Spark pickles (with cloudpickle, as Spark does) must
    run on an executor that has NEITHER jax NOR this package: unpickle
    and execute it in a subprocess whose import machinery blocks both,
    against a live server."""
    try:
        import cloudpickle
    except ImportError:
        cloudpickle = pytest.importorskip("pyspark.cloudpickle")
    import os
    import subprocess
    import sys

    xs = np.arange(30.0, dtype=np.float32)
    with ScoringServer(_score) as addr:
        payload = tmp_path / "fn.pkl"
        payload.write_bytes(cloudpickle.dumps(remote_arrow_mapper(addr)))
        worker = tmp_path / "worker.py"
        worker.write_text(
            "import pickle, sys\n"
            "import numpy as np\n"
            "import pyarrow as pa\n"
            "sys.modules['jax'] = None; sys.modules['tensorframes_tpu'] = None\n"
            "fn = pickle.load(open(sys.argv[1], 'rb'))\n"
            "t = pa.table({'x': pa.array(np.arange(30.0, dtype=np.float32))})\n"
            "out = pa.Table.from_batches(list(fn(t.to_batches())))\n"
            "got = out.column('y').to_numpy()\n"
            "assert np.allclose(got, np.arange(30.0) * 2.0 + 1.0), got[:5]\n"
            "print('EXECUTOR OK')\n"
        )
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)  # the repo must not be importable
        res = subprocess.run(
            [sys.executable, str(worker), str(payload)],
            capture_output=True, text=True, timeout=120, env=env,
            cwd=str(tmp_path),
        )
    assert res.returncode == 0, res.stderr
    assert "EXECUTOR OK" in res.stdout


def test_server_side_error_propagates_to_client():
    """Engine errors cross the wire as typed failures, not as Arrow
    stream corruption (status-byte protocol)."""

    def broken(nope):  # placeholder matches no column
        return {"y": nope}

    with ScoringServer(broken) as addr:
        fn = remote_arrow_mapper(addr)
        with pytest.raises(RuntimeError, match="remote scoring failed"):
            list(fn(_batches(np.arange(4.0, dtype=np.float32))))


def test_vector_columns_analyze_before_capture():
    """FixedSizeList ingestion must pin cell shapes before capture —
    found broken via the service (the capture probe traced a
    placeholder width)."""
    w = np.linspace(-1, 1, 8).astype(np.float32)

    def score(features):
        return {"s": features @ w}

    feats = np.random.default_rng(0).normal(size=(64, 8)).astype(np.float32)
    t = pa.table({
        "features": pa.FixedSizeListArray.from_arrays(
            pa.array(feats.ravel(), type=pa.float32()), 8
        )
    })
    with ScoringServer(score) as addr:
        fn = remote_arrow_mapper(addr)
        out = pa.Table.from_batches(list(fn(t.to_batches(max_chunksize=16))))
    np.testing.assert_allclose(
        out.column("s").to_numpy(), feats @ w, rtol=1e-5
    )


def test_server_restarts_after_stop():
    srv = ScoringServer(_score)
    for _ in range(2):
        addr = ":".join(map(str, srv.start()))
        fn = remote_arrow_mapper(addr)
        out = pa.Table.from_batches(
            list(fn(_batches(np.arange(5.0, dtype=np.float32))))
        )
        np.testing.assert_allclose(
            out.column("y").to_numpy(), np.arange(5.0) * 2 + 1
        )
        srv.stop()
