"""Tensor-parallel serving (serve/tp.py): mesh-sharded step programs +
the sharded KV PagePool, on the CPU-simulated mesh (conftest provisions
8 virtual devices; `make test-tp` provisions them itself).

The correctness bar, inherited from every serve feature and now pinned
ACROSS TP degrees: decode streams at TP=2 and TP=4 must be
BYTE-IDENTICAL to TP=1 solo decode — greedy and seeded — under chunked
prefill, prefix-cache hits, defragmentation, restart, and a mid-stream
chaos kill with failover onto a replica of a DIFFERENT TP degree. The
capacity contract: aggregate KV pages scale with the degree
(``num_pages`` is the per-chip budget), so a workload that exhausts
TP=1 admission serves preemption-free at TP=2.
"""

import json
import socket
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.parallel import make_mesh
from tensorframes_tpu.serve import Fleet, GenerationEngine

pytestmark = [pytest.mark.serve, pytest.mark.tp]

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    # 8 MHA heads so tp in {1, 2, 4} slices whole KV heads; d_ff = 128
    # divides by 4 for the at-rest weight shards
    return TransformerLM.init(0, VOCAB, d_model=32, n_heads=8, max_len=64)


def _prompts(seed, lens):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist()
        for n in lens
    ]


def _counter_total(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _mesh(tp):
    return make_mesh({"tp": tp}) if tp > 1 else None


# ---------------------------------------------------------------------------
# the byte-identity matrix
# ---------------------------------------------------------------------------


class TestByteIdentityMatrix:
    def test_tp_matrix_greedy_and_seeded(self, lm):
        """TP=1 (no mesh) vs TP=1 (1-chip mesh) vs TP=2 vs TP=4, greedy
        AND seeded sampling, under chunked prefill + prefix-cache hits:
        every stream byte-identical, ≤ 3 compiled step programs per
        engine at every degree."""
        prompts = _prompts(0, (5, 12, 23, 17))
        kw = dict(
            max_slots=4, page_size=8, max_seq_len=64,
            prefill_chunk_tokens=8, prefix_cache=True,
        )
        solo = GenerationEngine(lm, **kw)
        base_g = solo.generate(prompts, 12)
        base_s = solo.generate(prompts, 12, temperature=0.8, seed=11,
                               top_p=0.9)
        assert solo.num_step_programs <= 3
        for tp in (1, 2, 4):
            eng = GenerationEngine(lm, mesh=make_mesh({"tp": tp}), **kw)
            assert eng.tp_degree == tp
            got_g = eng.generate(prompts, 12)
            # a second pass hits the prefix cache (shared pages + COW)
            got_cached = eng.generate(prompts, 12)
            got_s = eng.generate(prompts, 12, temperature=0.8, seed=11,
                                 top_p=0.9)
            for a, b in zip(base_g, got_g):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(base_g, got_cached):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(base_s, got_s):
                np.testing.assert_array_equal(a, b)
            assert eng.num_step_programs <= 3, (
                f"tp={tp} compiled {eng.num_step_programs} step programs"
            )

    def test_tp_matches_models_oracle(self, lm):
        """The chain closes: TP decode == solo engine == the models
        oracle (transformer_generate) for the same request."""
        prompt = _prompts(3, (14,))[0]
        oracle = lm.generate(np.asarray([prompt], np.int32), 10)[0, 14:]
        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64, mesh=_mesh(2)
        )
        np.testing.assert_array_equal(eng.generate([prompt], 10)[0], oracle)

    def test_defragment_and_restart_stay_identical(self, lm):
        prompts = _prompts(5, (9, 21))
        solo = GenerationEngine(lm, max_slots=2, page_size=8,
                                max_seq_len=64)
        base = solo.generate(prompts, 10, temperature=0.5, seed=2)
        eng = GenerationEngine(lm, max_slots=2, page_size=8,
                               max_seq_len=64, mesh=_mesh(4))
        eng.generate(prompts, 10)
        eng.defragment()
        after_defrag = eng.generate(prompts, 10, temperature=0.5, seed=2)
        eng.restart()
        after_restart = eng.generate(prompts, 10, temperature=0.5, seed=2)
        for a, b in zip(base, after_defrag):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(base, after_restart):
            np.testing.assert_array_equal(a, b)


class TestShardedPrefillAttention:
    def test_one_pass_prefill_byte_identical_at_every_degree(self, lm):
        """ISSUE 15 satellite (ROADMAP 1 follow-on): the TP prefill's
        ATTENTION is now sharded along KV heads — each shard computes
        only its heads' causal scores/softmax/weighted-sum and the
        tiled gather reassembles the solo context bit-for-bit. No
        chunking here, so every prompt takes the one-pass prefill
        program; greedy AND seeded first tokens (and the decode that
        follows from the scattered k/v) must match solo at TP=1/2/4."""
        prompts = _prompts(31, (3, 17, 40))
        solo = GenerationEngine(lm, max_slots=4, page_size=8,
                                max_seq_len=64)
        base_g = solo.generate(prompts, 8)
        base_s = solo.generate(prompts, 8, temperature=0.9, seed=17,
                               top_p=0.85)
        for tp in (1, 2, 4):
            eng = GenerationEngine(
                lm, max_slots=4, page_size=8, max_seq_len=64,
                mesh=make_mesh({"tp": tp}),
            )
            for a, b in zip(base_g, eng.generate(prompts, 8)):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(
                base_s,
                eng.generate(prompts, 8, temperature=0.9, seed=17,
                             top_p=0.85),
            ):
                np.testing.assert_array_equal(a, b)
            assert eng.num_step_programs <= 2


class TestSpeculativeUnderTP:
    def test_spec_streams_match_solo_at_tp_degrees(self, lm):
        """ISSUE 15: the verify program shards on KV heads like decode
        (the draft runs replicated); speculative streams at TP=2/4 are
        byte-identical to solo non-speculative decode, within the <= 5
        program budget."""
        prompts = _prompts(41, (7, 19))
        solo = GenerationEngine(lm, max_slots=2, page_size=8,
                                max_seq_len=64)
        base_g = solo.generate(prompts, 10)
        base_s = solo.generate(prompts, 10, temperature=0.7, seed=23)
        for tp in (2, 4):
            eng = GenerationEngine(
                lm, max_slots=2, page_size=8, max_seq_len=64,
                mesh=make_mesh({"tp": tp}),
                draft_params=lm.params, draft_len=3,
            )
            for a, b in zip(base_g, eng.generate(prompts, 10)):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(
                base_s,
                eng.generate(prompts, 10, temperature=0.7, seed=23),
            ):
                np.testing.assert_array_equal(a, b)
            assert eng.num_step_programs <= 5
            assert eng.health()["speculative"]["proposed"] > 0


# ---------------------------------------------------------------------------
# mesh validation + pool semantics
# ---------------------------------------------------------------------------


class TestMeshAndPool:
    def test_mesh_must_be_1d(self, lm):
        with pytest.raises(ValueError, match="1-D"):
            GenerationEngine(lm, mesh=make_mesh({"dp": 2, "tp": 2}))

    def test_heads_must_divide(self, lm):
        bad = TransformerLM.init(0, VOCAB, d_model=24, n_heads=6,
                                 max_len=32)
        with pytest.raises(ValueError, match="divide"):
            GenerationEngine(bad, mesh=make_mesh({"tp": 4}))

    def test_moe_blocks_rejected(self):
        moe = TransformerLM.init(
            0, VOCAB, d_model=16, n_heads=4, max_len=32, moe_experts=2
        )
        with pytest.raises(ValueError, match="[Mm]oe|experts"):
            GenerationEngine(moe, mesh=make_mesh({"tp": 2}))

    def test_num_pages_is_per_chip_budget(self, lm):
        """Same constructor kwargs, higher degree → N× aggregate pages
        (serve.pages_capacity reports the scaled total) at ~flat
        per-chip KV bytes."""
        caps = {}
        for tp in (1, 2, 4):
            eng = GenerationEngine(
                lm, max_slots=4, page_size=8, num_pages=8,
                max_seq_len=64, mesh=_mesh(tp),
            )
            caps[tp] = eng.pool.num_pages
            assert _counter_total("serve.pages_capacity") == float(
                eng.pool.num_pages
            )
            if tp > 1:
                h = eng.health()
                assert h["tp_degree"] == tp
                assert h["tp"]["pages_capacity"] == 8 * tp
        assert caps == {1: 8, 2: 16, 4: 32}

    def test_capacity_scaling_unlocks_admission(self, lm):
        """The acceptance drill: a pool budget that forces TP=1 to
        preempt serves the same workload preemption-free at TP=2 (the
        aggregate pool doubled)."""
        prompts = _prompts(9, (16, 16, 16, 16))
        base = None
        preempts = {}
        for tp in (1, 2):
            before = _counter_total(
                "failures.preemptions_total", op="serve"
            )
            eng = GenerationEngine(
                lm, max_slots=4, page_size=8, num_pages=12,
                max_seq_len=64, mesh=_mesh(tp),
            )
            out = eng.generate(prompts, 16)
            if base is None:
                base = out
            else:
                for a, b in zip(base, out):
                    np.testing.assert_array_equal(a, b)
            preempts[tp] = (
                _counter_total("failures.preemptions_total", op="serve")
                - before
            )
        # TP=1: 4 slots × 4 pages full-length vs 12 pages — must preempt.
        # TP=2: 24 aggregate pages hold all four sequences outright.
        assert preempts[1] > 0, "workload was meant to exhaust TP=1"
        assert preempts[2] == 0, (
            f"TP=2 still preempted {preempts[2]} time(s) with the "
            f"doubled pool"
        )

    def test_tuned_geometry_scales_per_chip_under_tp(self, lm,
                                                     tmp_path,
                                                     monkeypatch):
        """A tuned serve.page_slots budget is a PER-CHIP quantity like
        an explicit num_pages: the defaulted pool scales it by the TP
        degree (floored at one full-length request)."""
        from tensorframes_tpu import tune
        from tensorframes_tpu.utils import get_config, set_config

        monkeypatch.setenv("TFT_TUNE_FILE", str(tmp_path / "t.jsonl"))
        monkeypatch.delenv("TFT_TUNE", raising=False)
        prev = (get_config().autotune, get_config().tune_mode)
        tune.reset()
        try:
            set_config(autotune=True, tune_mode="cached")
            sig = tune.serve_signature(np.float32, 4, 64)
            tune.pin(
                "serve.page_slots", sig,
                {"slots": 4, "pages_per_slot": 3},
            )
            e1 = GenerationEngine(lm, max_seq_len=64, page_size=8)
            e2 = GenerationEngine(
                lm, max_seq_len=64, page_size=8, mesh=_mesh(2)
            )
            assert e1.pool.num_pages == max(e1._max_pages, 4 * 3)
            assert e2.pool.num_pages == max(e2._max_pages, 4 * 3 * 2)
        finally:
            set_config(autotune=prev[0], tune_mode=prev[1])
            tune.reset()

    def test_replica_kwargs_reserved_keys_rejected(self, lm):
        with pytest.raises(ValueError, match="fleet-owned"):
            Fleet(
                lm, replicas=2,
                replica_kwargs=[{"name": "primary"}, {}],
            )

    def test_collective_estimate_and_metric(self, lm):
        before = _counter_total("serve.collective_seconds")
        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64, mesh=_mesh(2)
        )
        assert eng._collective_step_s > 0.0
        assert eng._collective_bytes_per_step > 0
        eng.generate([_prompts(1, (6,))[0]], 4)
        assert _counter_total("serve.collective_seconds") > before
        assert (
            eng.health()["tp"]["collective_seconds_per_step_est"] > 0.0
        )


# ---------------------------------------------------------------------------
# fused ragged kernel under the mesh
# ---------------------------------------------------------------------------


class TestFusedUnderTP:
    def test_fused_read_matches_solo(self, lm):
        """The ragged paged-attention kernel (interpret mode on CPU) is
        head-batched, so its local-head walk shards like the gather:
        streams match the solo FUSED engine byte-for-byte."""
        prompts = _prompts(7, (11, 19))
        solo = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64,
            attention_impl="fused",
        )
        base = solo.generate(prompts, 8)
        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64,
            attention_impl="fused", mesh=_mesh(2),
        )
        for a, b in zip(base, eng.generate(prompts, 8)):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# heterogeneous-TP fleet: chaos kill + failover across degrees
# ---------------------------------------------------------------------------


class TestHeteroFleet:
    def test_failover_across_tp_degrees_mid_stream(self, lm):
        """A TP=2 replica dies mid-stream (chaos kill: fence + injected
        fault + pool scramble); the survivor replays onto the TP=1
        replica and the client stream stays byte-identical to solo —
        greedy and seeded — with ≤ 3 programs per replica."""
        prompt = _prompts(13, (9,))[0]
        solo = GenerationEngine(lm, max_slots=4, page_size=8,
                                max_seq_len=64)
        for temp, seed in ((0.0, 0), (0.6, 5)):
            base = solo.generate([prompt], 24, temperature=temp,
                                 seed=seed)[0]
            fleet = Fleet(
                lm, replicas=2, max_slots=4, page_size=8, max_seq_len=64,
                watchdog_interval_s=0.01,
                replica_kwargs=[{"mesh": make_mesh({"tp": 2})}, {}],
            )
            with fleet:
                assert [
                    r.engine.tp_degree for r in fleet._replicas
                ] == [2, 1]
                h = fleet.submit(prompt, 24, temperature=temp, seed=seed,
                                 session="s")
                got = []
                it = iter(h)
                for _ in range(4):
                    got.append(next(it))
                fleet._kill_replica(
                    fleet._replica("r0"), RuntimeError("chaos kill")
                )
                for tok in it:
                    got.append(tok)
                assert all(
                    n <= 3 for n in fleet.program_counts().values()
                )
            np.testing.assert_array_equal(np.asarray(got, np.int32), base)
        health = fleet.health()
        assert health["replicas"]["r0"]["tp_degree"] == 2
        assert health["replicas"]["r1"]["tp_degree"] == 1

    def test_chunked_prefill_prefix_cache_failover_combo(self, lm):
        """The full satellite matrix in one drill: chunked prefill +
        prefix-cache hits + a chaos kill mid-stream, failing over FROM
        TP=1 ONTO TP=4."""
        sys_prefix = _prompts(21, (16,))[0]
        prompt = sys_prefix + _prompts(22, (7,))[0]
        kw = dict(
            max_slots=4, page_size=8, max_seq_len=64,
            prefill_chunk_tokens=8, prefix_cache=True,
        )
        solo = GenerationEngine(lm, **kw)
        solo.generate([sys_prefix], 2)  # register the shared prefix
        base = solo.generate([prompt], 20, temperature=0.7, seed=9)[0]
        fleet = Fleet(
            lm, replicas=2, watchdog_interval_s=0.01,
            replica_kwargs=[{}, {"mesh": make_mesh({"tp": 4})}], **kw
        )
        with fleet:
            # warm both replicas' prefix caches so the replay path hits
            for eng in fleet.engines:
                eng.submit(sys_prefix, 2, block=False)
            deadline = time.monotonic() + 30
            while (
                any(e.scheduler.has_work() for e in fleet.engines)
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            h = fleet.submit(prompt, 20, temperature=0.7, seed=9,
                             session="u")
            got = []
            it = iter(h)
            for _ in range(3):
                got.append(next(it))
            victim = next(
                r.name for r in fleet._replicas
                if r.engine.scheduler.has_work()
            )
            fleet._kill_replica(
                fleet._replica(victim), RuntimeError("chaos kill")
            )
            for tok in it:
                got.append(tok)
        np.testing.assert_array_equal(np.asarray(got, np.int32), base)


# ---------------------------------------------------------------------------
# healthz / statusz surfaces
# ---------------------------------------------------------------------------


def _http(addr, req: bytes) -> bytes:
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=30) as c:
        c.sendall(req)
        out = b""
        while True:
            b = c.recv(65536)
            if not b:
                break
            out += b
    return out


class TestOperatorSurfaces:
    def test_healthz_and_statusz_report_tp(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64, mesh=_mesh(2)
        )
        with ScoringServer(engine=eng) as addr:
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["tp_degree"] == 2
            tp = body["tp"]
            assert tp["degree"] == 2 and tp["axis"] == "tp"
            assert tp["pages_capacity"] == eng.pool.num_pages
            assert tp["kv_bytes_per_shard"] > 0
            assert "pages_in_use_per_shard" in tp
            resp = _http(addr, b"GET /statusz HTTP/1.1\r\n\r\n")
            sbody = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert sbody["serving"]["tp_degree"] == 2
            assert sbody["serving"]["tp"]["degree"] == 2

    def test_statusz_serving_for_fleet_lists_replicas(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        fleet = Fleet(
            lm, replicas=2, max_slots=2, page_size=8, max_seq_len=64,
            replica_kwargs=[{"mesh": make_mesh({"tp": 2})}, {}],
        )
        with ScoringServer(engine=fleet) as addr:
            resp = _http(addr, b"GET /statusz HTTP/1.1\r\n\r\n")
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            reps = body["serving"]["replicas"]
            assert reps["r0"]["tp_degree"] == 2
            assert reps["r1"]["tp_degree"] == 1
