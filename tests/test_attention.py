"""Attention tests: flash kernel vs dense oracle (CPU interpret mode) and
ring attention over the virtual sp mesh vs the same oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorframes_tpu.ops import (
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from tensorframes_tpu.parallel import make_mesh


def qkv(rng, b=2, h=2, l=32, d=8, dtype=np.float32):
    def mk():
        return jnp.asarray(rng.normal(size=(b, h, l, d)).astype(dtype))

    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def nprng():
    return np.random.default_rng(0)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        q, k, v = qkv(nprng)
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_multiple_kv_blocks(self, nprng):
        q, k, v = qkv(nprng, l=64)
        out = flash_attention(q, k, v, block_q=16, block_k=8)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_cross_attention_lengths(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        out = flash_attention(q, k, v, block_q=16, block_k=16)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_causal_cross_attention_offset(self, nprng):
        # lq != lk: the causal diagonal aligns bottom-right (decoder step
        # batches); kernel must apply the lk - lq offset
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_bad_block_size(self, nprng):
        q, k, v = qkv(nprng, l=30)
        with pytest.raises(ValueError, match="lane-aligned"):
            flash_attention(q, k, v, block_q=16, block_k=16)

    def test_default_tiles_fit_non_multiple_lengths(self, nprng):
        # L=640 is not a multiple of the 512/1024 default tiles but admits
        # a 128 tile; default-argument callers must keep working
        q, k, v = qkv(nprng, l=640)
        out = flash_attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_first_row_causal(self, nprng):
        # the first query attends only to itself: softmax over one key
        q, k, v = qkv(nprng, b=1, h=1, l=16)
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        np.testing.assert_allclose(
            np.asarray(out)[0, 0, 0], np.asarray(v)[0, 0, 0], rtol=1e-5
        )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=32)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_eight_way(self, nprng):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(nprng, l=64, d=4)
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_matches_flash_single_chip(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=32)
        ring = ring_attention(q, k, v, mesh=mesh, causal=True)
        flash = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        np.testing.assert_allclose(ring, flash, rtol=2e-5, atol=2e-5)

    def test_indivisible_length_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=30)
        with pytest.raises(ValueError, match="divide"):
            ring_attention(q, k, v, mesh=mesh)


class TestFullyMaskedRows:
    """Causal attention with lq > lk leaves early query rows with no visible
    key; the convention (everywhere) is zeros for such rows, not a uniform
    average of V."""

    def test_reference_zeros_fully_masked(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        ref = np.asarray(attention_reference(q, k, v, causal=True))
        # offset = lk - lq = -8: rows 0..7 see no key at all
        np.testing.assert_array_equal(ref[:, :, :8], 0.0)
        assert np.abs(ref[:, :, 8:]).min() > 0

    def test_flash_matches_reference_lq_gt_lk(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    """All-to-all sequence parallelism: seq-sharded -> head-sharded ->
    attend full-L -> shard back (ops/ulysses.py)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=32)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_eight_way(self, nprng):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(nprng, h=8, l=64, d=4)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_matches_ring(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=32)
        u = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        r = ring_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(u, r, rtol=2e-5, atol=2e-5)

    def test_indivisible_heads_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=2, l=32)  # 2 heads on a 4-way axis
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, mesh=mesh)

    def test_indivisible_length_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=30)
        with pytest.raises(ValueError, match="divide"):
            ulysses_attention(q, k, v, mesh=mesh)

    def test_transformer_ulysses_impl(self, nprng):
        from tensorframes_tpu.models import init_transformer, transformer_logits

        mesh = make_mesh({"sp": 4})
        params = init_transformer(
            0, vocab=16, d_model=16, n_heads=4, n_layers=1, max_len=32
        )
        toks = nprng.integers(0, 16, size=(2, 32)).astype(np.int32)
        u = transformer_logits(params, toks, attn_impl="ulysses", mesh=mesh)
        d = transformer_logits(params, toks)
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(d), rtol=2e-4, atol=2e-4
        )
