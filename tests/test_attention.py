"""Attention tests: flash kernel vs dense oracle (CPU interpret mode) and
ring attention over the virtual sp mesh vs the same oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorframes_tpu.ops import (
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from tensorframes_tpu.parallel import make_mesh

from _gates import requires_shard_map


def qkv(rng, b=2, h=2, l=32, d=8, dtype=np.float32):
    def mk():
        return jnp.asarray(rng.normal(size=(b, h, l, d)).astype(dtype))

    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def nprng():
    return np.random.default_rng(0)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        q, k, v = qkv(nprng)
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_multiple_kv_blocks(self, nprng):
        q, k, v = qkv(nprng, l=64)
        out = flash_attention(q, k, v, block_q=16, block_k=8)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_cross_attention_lengths(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        out = flash_attention(q, k, v, block_q=16, block_k=16)
        ref = attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_causal_cross_attention_offset(self, nprng):
        # lq != lk: the causal diagonal aligns bottom-right (decoder step
        # batches); kernel must apply the lk - lq offset
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_bad_block_size(self, nprng):
        q, k, v = qkv(nprng, l=30)
        with pytest.raises(ValueError, match="lane-aligned"):
            flash_attention(q, k, v, block_q=16, block_k=16)

    def test_default_tiles_fit_non_multiple_lengths(self, nprng):
        # L=640 is not a multiple of the 512/1024 default tiles but admits
        # a 128 tile; default-argument callers must keep working
        q, k, v = qkv(nprng, l=640)
        out = flash_attention(q, k, v, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_first_row_causal(self, nprng):
        # the first query attends only to itself: softmax over one key
        q, k, v = qkv(nprng, b=1, h=1, l=16)
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        np.testing.assert_allclose(
            np.asarray(out)[0, 0, 0], np.asarray(v)[0, 0, 0], rtol=1e-5
        )


class TestFlashAttentionGrads:
    """The custom VJP (FlashAttention-2 backward in pallas) vs jax.grad
    through the dense oracle."""

    def _grads(self, fn, q, k, v, causal):
        def loss(q, k, v):
            o = fn(q, k, v, causal=causal)
            # weighted sum so every output element carries a distinct
            # cotangent (catches transposition/scale mistakes a plain
            # .sum() cannot)
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return (o.astype(jnp.float32) * jnp.sin(w)).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_oracle(self, nprng, causal):
        q, k, v = qkv(nprng, l=64)
        flash = lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16
        )
        got = self._grads(flash, q, k, v, causal)
        want = self._grads(attention_reference, q, k, v, causal)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name}",
            )

    def test_grads_cross_length_causal(self, nprng):
        # lq != lk: the bottom-right-aligned causal offset must flow
        # through the backward regimes too
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 48, 8)).astype(np.float32))
        flash = lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, block_q=8, block_k=8
        )
        got = self._grads(flash, q, k, v, True)
        want = self._grads(attention_reference, q, k, v, True)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name}",
            )

    def test_grads_empty_rows_are_zero(self, nprng):
        # causal with lq > lk: leading queries see no key; their output is
        # zero and so must every gradient flowing through them be
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 1, 32, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 1, 16, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 1, 16, 8)).astype(np.float32))
        flash = lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, block_q=8, block_k=8
        )
        got = self._grads(flash, q, k, v, True)
        want = self._grads(attention_reference, q, k, v, True)
        # rows 0..15 have offset+i < 0: no visible key
        assert np.all(np.asarray(got[0])[0, 0, :16] == 0.0)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name}",
            )

    def test_bf16_grads_close_to_f32(self, nprng):
        q, k, v = qkv(nprng, l=32)
        flash = lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, block_q=8, block_k=8
        )
        f32 = self._grads(flash, q, k, v, True)
        b16 = self._grads(
            flash,
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            True,
        )
        for g32, g16, name in zip(f32, b16, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g16, dtype=np.float32),
                np.asarray(g32),
                rtol=0.1,
                atol=0.15,
                err_msg=f"d{name}",
            )

    def test_value_and_grad_through_jit(self, nprng):
        # the vjp composes with jit + other ops (the transformer path)
        q, k, v = qkv(nprng, l=32)

        @jax.jit
        def loss(q, k, v):
            return flash_attention(
                q, k, v, causal=True, block_q=8, block_k=8
            ).sum()

        val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert np.isfinite(float(val))
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)


class TestRingAttention:
    @requires_shard_map
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=32)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @requires_shard_map
    def test_eight_way(self, nprng):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(nprng, l=64, d=4)
        out = ring_attention(q, k, v, mesh=mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @requires_shard_map
    def test_matches_flash_single_chip(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=32)
        ring = ring_attention(q, k, v, mesh=mesh, causal=True)
        flash = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        np.testing.assert_allclose(ring, flash, rtol=2e-5, atol=2e-5)

    def test_indivisible_length_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=30)
        with pytest.raises(ValueError, match="divide"):
            ring_attention(q, k, v, mesh=mesh)

    @requires_shard_map
    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_hops_multiple_tiles(self, nprng, causal):
        # chunk (L/n = 32) split into four 8-wide tiles per hop: the carry
        # kernel must stream sub-blocks within a hop, not just whole chunks
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=128)
        out = ring_attention(
            q, k, v, mesh=mesh, causal=causal, block_q=8, block_k=8
        )
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @requires_shard_map
    def test_bf16_matches_f32(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=64)
        f32 = ring_attention(q, k, v, mesh=mesh, causal=True)
        b16 = ring_attention(
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            mesh=mesh,
            causal=True,
        )
        np.testing.assert_allclose(
            np.asarray(b16, dtype=np.float32), np.asarray(f32),
            rtol=0.05, atol=0.05,
        )

    def test_causal_cross_length_rejected(self, nprng):
        # chunk-level causal regimes assume aligned diagonals; the entry
        # point must refuse rather than silently pick an alignment
        mesh = make_mesh({"sp": 4})
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
        with pytest.raises(ValueError, match="equal q/k"):
            ring_attention(q, k, k, mesh=mesh, causal=True)


class TestRingAttentionGrads:
    """The ring-backward custom VJP (dq local, dk/dv rotating home) vs
    jax.grad through the dense oracle."""

    def _grads(self, fn, q, k, v):
        def loss(q, k, v):
            o = fn(q, k, v)
            w = jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)
            return (o.astype(jnp.float32) * jnp.sin(w)).sum()

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @requires_shard_map
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_oracle(self, nprng, causal):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=64)
        ring = lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=causal)
        dense = lambda q, k, v: attention_reference(q, k, v, causal=causal)
        got = self._grads(ring, q, k, v)
        want = self._grads(dense, q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name}",
            )

    @requires_shard_map
    def test_grads_multiple_tiles_per_hop(self, nprng):
        # sub-block streaming in the BACKWARD hops too
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, l=128)
        ring = lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=True, block_q=8, block_k=8
        )
        dense = lambda q, k, v: attention_reference(q, k, v, causal=True)
        got = self._grads(ring, q, k, v)
        want = self._grads(dense, q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name}",
            )


class TestFullyMaskedRows:
    """Causal attention with lq > lk leaves early query rows with no visible
    key; the convention (everywhere) is zeros for such rows, not a uniform
    average of V."""

    def test_reference_zeros_fully_masked(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        ref = np.asarray(attention_reference(q, k, v, causal=True))
        # offset = lk - lq = -8: rows 0..7 see no key at all
        np.testing.assert_array_equal(ref[:, :, :8], 0.0)
        assert np.abs(ref[:, :, 8:]).min() > 0

    def test_flash_matches_reference_lq_gt_lk(self, nprng):
        rng = nprng
        q = jnp.asarray(rng.normal(size=(1, 2, 16, 8)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 2, 8, 8)).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestUlyssesAttention:
    """All-to-all sequence parallelism: seq-sharded -> head-sharded ->
    attend full-L -> shard back (ops/ulysses.py)."""

    @requires_shard_map
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, nprng, causal):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=32)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
        ref = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @requires_shard_map
    def test_eight_way(self, nprng):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(nprng, h=8, l=64, d=4)
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        ref = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    @requires_shard_map
    def test_matches_ring(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=32)
        u = ulysses_attention(q, k, v, mesh=mesh, causal=True)
        r = ring_attention(q, k, v, mesh=mesh, causal=True)
        np.testing.assert_allclose(u, r, rtol=2e-5, atol=2e-5)

    def test_indivisible_heads_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=2, l=32)  # 2 heads on a 4-way axis
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, mesh=mesh)

    def test_indivisible_length_rejected(self, nprng):
        mesh = make_mesh({"sp": 4})
        q, k, v = qkv(nprng, h=4, l=30)
        with pytest.raises(ValueError, match="divide"):
            ulysses_attention(q, k, v, mesh=mesh)

    @requires_shard_map
    def test_transformer_ulysses_impl(self, nprng):
        from tensorframes_tpu.models import init_transformer, transformer_logits

        mesh = make_mesh({"sp": 4})
        params = init_transformer(
            0, vocab=16, d_model=16, n_heads=4, n_layers=1, max_len=32
        )
        toks = nprng.integers(0, 16, size=(2, 32)).astype(np.int32)
        u = transformer_logits(params, toks, attn_impl="ulysses", mesh=mesh)
        d = transformer_logits(params, toks)
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(d), rtol=2e-4, atol=2e-4
        )
