"""Distributed journaled jobs: leasing, heartbeats, reclamation, fencing.

The acceptance bar (ISSUE 8): a K-worker drain of one manifest is
byte-identical to a solo run — including under a kill -9 of one worker
mid-block (lease reclaimed, block recomputed exactly once) and a zombie
worker writing after lease theft (write fence-rejected, zero
duplicate/torn ledger records) — verified by a REAL 3-subprocess soak
with obs counters asserting ≥ 1 reclaim and ≥ 1 fence reject.
Everything else here is CPU-only, seeded, deterministic, and fast;
``make test-distjobs`` selects the suite.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.engine import run_job, resume_job, run_worker, wait_job
from tensorframes_tpu.engine.dist_jobs import (
    LeaseManager,
    journal_status,
)
from tensorframes_tpu.engine.jobs import BlockLedger, jobs_status
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import (
    StaleLeaseError,
    chaos,
    get_config,
    retry_deadline,
    run_with_retries,
    set_config,
)
from tensorframes_tpu.utils.chaos import ChaosFault

pytestmark = pytest.mark.distjobs


@pytest.fixture
def small_chunks():
    old = get_config().max_rows_per_device_call
    set_config(max_rows_per_device_call=16)
    yield
    set_config(max_rows_per_device_call=old)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _counter(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _frame(n=96, width=4, parts=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, width)).astype(np.float32)
    return (
        tft.TensorFrame.from_columns({"x": x}).analyze().repartition(parts)
    )


def _fn(x):
    return {"y": x * 3.0 + 1.0}


def _col(frame, name="y"):
    return np.asarray(frame.column_data(name).host())


def _done_records(path):
    return [
        json.loads(ln)
        for ln in open(os.path.join(path, "ledger.jsonl"))
        if '"done"' in ln
    ]


# ---------------------------------------------------------------------------


class TestLeaseManager:
    def test_claim_is_exclusive_while_live(self, tmp_path):
        a = LeaseManager(str(tmp_path), "a", ttl_s=30.0, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        assert b.try_acquire(0) is None  # live, a's
        assert a.try_acquire(0) == 0  # idempotent for the holder
        assert b.try_acquire(1) == 0  # a different block is free
        a.stop(), b.stop()

    def test_expired_lease_reclaims_with_epoch_bump(self, tmp_path):
        r0 = _counter("jobs.leases_reclaimed_total")
        a = LeaseManager(str(tmp_path), "a", ttl_s=0.2, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        time.sleep(0.35)
        assert b.try_acquire(0) == 1  # epoch bumped — the fencing token
        assert b.reclaimed_total == 1
        assert _counter("jobs.leases_reclaimed_total") == r0 + 1
        # the loser (previous holder) cannot re-enter at its old epoch
        assert a.try_acquire(0) is None
        a.stop(), b.stop()

    def test_done_marker_is_terminal(self, tmp_path):
        a = LeaseManager(str(tmp_path), "a", ttl_s=0.2, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        a.mark_done(0, 0)
        time.sleep(0.3)  # well past the ttl: done markers never expire
        assert b.try_acquire(0) is None
        a.stop(), b.stop()

    def test_release_makes_block_claimable_again(self, tmp_path):
        a = LeaseManager(str(tmp_path), "a", ttl_s=30.0, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        a.release(0)
        assert b.try_acquire(0) == 0  # fresh claim, not a reclaim
        assert b.reclaimed_total == 0
        a.stop(), b.stop()

    def test_heartbeat_extends_the_deadline(self, tmp_path):
        h0 = _counter("jobs.lease_heartbeats_total")
        a = LeaseManager(str(tmp_path), "a", ttl_s=0.6, heartbeat_s=0.1)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        time.sleep(1.2)  # two ttls: only renewals keep it alive
        assert b.try_acquire(0) is None
        assert _counter("jobs.lease_heartbeats_total") > h0
        a.stop()
        # stop() released (unlinked) the lease: claimable immediately
        assert b.try_acquire(0) == 0
        b.stop()

    def test_fence_check_raises_after_steal(self, tmp_path):
        f0 = _counter("jobs.fence_rejects_total")
        a = LeaseManager(str(tmp_path), "a", ttl_s=0.2, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(3) == 0
        a.fence_check(3, 0)  # still ours: passes
        time.sleep(0.35)
        assert b.try_acquire(3) == 1
        with pytest.raises(StaleLeaseError, match="superseded by epoch 1"):
            a.fence_check(3, 0)
        assert _counter("jobs.fence_rejects_total") == f0 + 1
        a.stop(), b.stop()

    def test_heartbeat_does_not_resurrect_a_superseded_lease(
        self, tmp_path
    ):
        """Regression: renew_all's os.replace would re-CREATE a
        superseded epoch file the reclaimer already unlinked, leaving a
        phantom stale lease the old worker renews forever."""
        a = LeaseManager(str(tmp_path), "a", ttl_s=0.2, heartbeat_s=1e6)
        b = LeaseManager(str(tmp_path), "b", ttl_s=30.0, heartbeat_s=1e6)
        assert a.try_acquire(0) == 0
        time.sleep(0.3)
        assert b.try_acquire(0) == 1  # housekeeping unlinked a's e0 file
        a.renew_all()  # a manual sweep on the stale holder
        names = os.listdir(os.path.join(str(tmp_path), "leases"))
        assert "block-00000.e000000.lease" not in names
        assert not a._held  # a dropped the lost lease
        a.stop(), b.stop()

    def test_concurrent_reclaim_has_one_winner(self, tmp_path):
        dead = LeaseManager(str(tmp_path), "dead", ttl_s=0.1,
                            heartbeat_s=1e6)
        assert dead.try_acquire(0) == 0
        time.sleep(0.25)
        managers = [
            LeaseManager(str(tmp_path), f"m{i}", ttl_s=30.0,
                         heartbeat_s=1e6)
            for i in range(4)
        ]
        results = [None] * 4
        barrier = threading.Barrier(4)

        def race(i):
            barrier.wait()
            results[i] = managers[i].try_acquire(0)

        ts = [threading.Thread(target=race, args=(i,)) for i in range(4)]
        [t.start() for t in ts]
        [t.join(10) for t in ts]
        winners = [r for r in results if r is not None]
        assert winners == [1]  # exactly one claims epoch 1
        for m in managers:
            m.stop()
        dead.stop()


# ---------------------------------------------------------------------------


class TestRetryDeadline:
    def test_deadline_stops_the_retry_loop(self, monkeypatch):
        old = (get_config().max_retries, get_config().retry_backoff_s)
        set_config(max_retries=50, retry_backoff_s=0.02)
        calls = []

        def flaky():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: tunnel dropped")

        try:
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                run_with_retries(flaky, what="test", deadline_s=0.15)
            assert time.monotonic() - t0 < 2.0
            assert 1 <= len(calls) < 50
        finally:
            set_config(max_retries=old[0], retry_backoff_s=old[1])

    def test_thread_local_window_applies(self):
        old = (get_config().max_retries, get_config().retry_backoff_s)
        set_config(max_retries=50, retry_backoff_s=0.02)
        calls = []

        def flaky():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: tunnel dropped")

        try:
            with retry_deadline(0.1):
                with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                    run_with_retries(flaky, what="test")
            assert 1 <= len(calls) < 50
        finally:
            set_config(max_retries=old[0], retry_backoff_s=old[1])

    def test_no_deadline_is_unbounded_and_nesting_clips(self):
        # None window is a no-op; an inner window is clipped to the outer
        with retry_deadline(None):
            assert run_with_retries(lambda: 42, what="test") == 42
        from tensorframes_tpu.utils.failures import (
            _effective_retry_deadline,
        )

        with retry_deadline(10.0):
            outer = _effective_retry_deadline(None)
            with retry_deadline(100.0):
                assert _effective_retry_deadline(None) == outer

    def test_stale_lease_error_is_not_transient(self):
        from tensorframes_tpu.utils.failures import is_transient

        assert not is_transient(StaleLeaseError("lease gone"))
        # even when chained from a transient cause
        try:
            try:
                raise RuntimeError("UNAVAILABLE: flaky")
            except RuntimeError as cause:
                raise StaleLeaseError("stale") from cause
        except StaleLeaseError as e:
            assert not is_transient(e)


# ---------------------------------------------------------------------------


class TestMultiWorkerDrain:
    def test_three_workers_drain_byte_identical(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        ref = _col(tft.map_rows(_fn, df))
        path = str(tmp_path / "drain")
        reports = []

        def w(i):
            reports.append(
                run_worker(
                    "map_rows", _fn, df, path=path, worker_id=f"w{i}",
                    lease_ttl_s=15.0, poll_s=0.05,
                )
            )

        ts = [threading.Thread(target=w, args=(i,)) for i in range(3)]
        [t.start() for t in ts]
        [t.join(120) for t in ts]
        assert len(reports) == 3 and all(r.complete for r in reports)
        # all 6 blocks computed exactly once, split across the workers
        assert sum(r.blocks_computed for r in reports) == 6
        recs = _done_records(path)
        assert len(recs) == 6
        assert len({r["block"] for r in recs}) == 6
        assert all("worker" in r and "epoch" in r for r in recs)
        # assembly from ANY process is the ordinary resume path
        res = wait_job(path, _fn, df, timeout_s=30)
        assert res.blocks_restored == 6 and res.blocks_computed == 0
        assert np.array_equal(_col(res.completed), ref)
        status = journal_status(path)
        assert status["terminal"] and status["blocks"]["done"] == 6

    @pytest.mark.chaos
    def test_zombie_late_write_is_fence_rejected(
        self, tmp_path, small_chunks
    ):
        """The zombie-writer drill, full write path: a worker with no
        heartbeats stalls inside its first block past its TTL (chaos
        latency), the block is reclaimed and recomputed by a healthy
        worker, and the zombie's late spool+append is rejected by the
        write fence — no duplicate or torn record lands."""
        df = _frame()
        ref = _col(tft.map_rows(_fn, df))
        path = str(tmp_path / "zombie")
        f0 = _counter("jobs.fence_rejects_total")
        r0 = _counter("jobs.leases_reclaimed_total")
        reports = {}

        def zombie():
            reports["zombie"] = run_worker(
                "map_rows", _fn, df, path=path, worker_id="zombie",
                lease_ttl_s=0.8, heartbeat_s=1e6, poll_s=0.05,
            )

        def healthy():
            time.sleep(1.2)  # let the zombie claim + its lease expire
            reports["healthy"] = run_worker(
                "map_rows", _fn, df, path=path, worker_id="healthy",
                lease_ttl_s=15.0, poll_s=0.05,
            )

        # only the zombie's FIRST block stalls (times=1)
        with chaos.scoped("jobs.block=latency:ms=2500:times=1"):
            tz = threading.Thread(target=zombie)
            th = threading.Thread(target=healthy)
            tz.start(), th.start()
            tz.join(120), th.join(120)
        assert reports["zombie"].fence_rejects >= 1
        assert reports["healthy"].leases_reclaimed >= 1
        assert _counter("jobs.fence_rejects_total") >= f0 + 1
        assert _counter("jobs.leases_reclaimed_total") >= r0 + 1
        recs = _done_records(path)
        assert len(recs) == 6 and len({r["block"] for r in recs}) == 6
        res = wait_job(path, _fn, df, timeout_s=30)
        assert np.array_equal(_col(res.completed), ref)

    def test_replay_ignores_superseded_records(
        self, tmp_path, small_chunks
    ):
        """Belt-and-braces replay arbitration: a stale-epoch done-record
        appended AFTER a higher-epoch one (the fence-slip shape) is
        ignored on open_ and counted as a fence reject."""
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        rel = os.path.join("blocks", "block-00000.npz")
        with open(os.path.join(res.path, "ledger.jsonl"), "ab") as f:
            f.write(
                json.dumps(
                    {"block": 0, "status": "done", "npz": rel,
                     "rows": 16, "worker": "a", "epoch": 2}
                ).encode() + b"\n"
            )
            f.write(
                json.dumps(
                    {"block": 0, "status": "done", "npz": rel,
                     "rows": 16, "worker": "zombie", "epoch": 1}
                ).encode() + b"\n"
            )
        f0 = _counter("jobs.fence_rejects_total")
        led = BlockLedger.open_(res.path)
        assert led._done_epoch[0] == 2
        assert _counter("jobs.fence_rejects_total") == f0 + 1
        res2 = resume_job(res.path, _fn, df)
        assert res2.blocks_restored == 6
        assert np.array_equal(_col(res2.completed), _col(res.completed))

    @pytest.mark.chaos
    def test_quarantine_shared_across_workers(
        self, tmp_path, small_chunks
    ):
        """A poison block quarantined by one worker stays quarantined
        for the whole job: the drain completes around it, wait_job
        returns the partial result, and strict assembly raises."""
        from tensorframes_tpu.utils import QuarantinedBlocksError

        df = _frame()
        path = str(tmp_path / "poison")
        with chaos.scoped("jobs.block=fatal:every=3:times=1"):
            rep = run_worker(
                "map_rows", _fn, df, path=path, worker_id="solo",
                lease_ttl_s=15.0, poll_s=0.05,
            )
        assert rep.complete and rep.blocks_quarantined == 1
        res = wait_job(path, _fn, df, timeout_s=30)
        assert len(res.quarantined) == 1
        assert res.completed.num_rows == 96 - 16
        with pytest.raises(QuarantinedBlocksError):
            wait_job(path, _fn, df, timeout_s=30, strict=True)

    def test_all_ops_drain_through_workers(self, tmp_path):
        """map_blocks / reduce_blocks / aggregate share the leasing
        layer with map_rows: 2 workers each, byte-identical assembly."""
        df = _frame()

        def drain(op, fetches, data, name):
            path = str(tmp_path / name)
            rs = []

            def w(i):
                rs.append(
                    run_worker(
                        op, fetches, data, path=path,
                        worker_id=f"w{i}", lease_ttl_s=15.0, poll_s=0.05,
                    )
                )

            ts = [
                threading.Thread(target=w, args=(i,)) for i in range(2)
            ]
            [t.start() for t in ts]
            [t.join(120) for t in ts]
            assert len(rs) == 2 and all(r.complete for r in rs)
            return wait_job(path, fetches, data, timeout_s=30)

        fnb = lambda x: {"y": x * 2.0}  # noqa: E731
        res = drain("map_blocks", fnb, df, "mb")
        assert np.array_equal(
            _col(res.completed), _col(tft.map_blocks(fnb, df))
        )

        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        res = drain("reduce_blocks", red, df, "rb")
        assert np.allclose(res.completed, tft.reduce_blocks(red, df))

        keys = (np.arange(96) % 5).astype(np.int64)
        adf = tft.TensorFrame.from_columns(
            {"k": keys, "x": np.arange(96, dtype=np.float32)}
        ).analyze()
        agg = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        res = drain("aggregate", agg, adf.group_by("k"), "ag")
        aref = tft.aggregate(agg, adf.group_by("k"))
        assert np.array_equal(
            _col(res.completed, "x"), _col(aref, "x")
        )

    def test_worker_rejects_wrong_op(self, tmp_path, small_chunks):
        df = _frame()
        path = str(tmp_path / "op")
        run_worker(
            "map_rows", _fn, df, path=path, worker_id="a",
            lease_ttl_s=15.0,
        )
        with pytest.raises(ValueError, match="map_rows"):
            run_worker(
                "map_blocks", _fn, df, path=path, worker_id="b",
                lease_ttl_s=15.0,
            )

    def test_wait_job_polls_over_terminal_but_leased_journal(
        self, tmp_path, small_chunks
    ):
        """Regression: a worker that dies between recording its last
        block and settling its lease leaves a TERMINAL journal with a
        live lease. wait_job must keep polling until the lease expires
        — not crash with the resume guard's StaleLeaseError."""
        df = _frame()
        ref = _col(tft.map_rows(_fn, df))
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        lm = LeaseManager(res.path, "dying-worker", ttl_s=1.0,
                          heartbeat_s=1e6)
        assert lm.try_acquire(0) == 0
        lm._stop.set()  # simulate death: lease stays, never renewed
        t0 = time.monotonic()
        out = wait_job(res.path, _fn, df, timeout_s=30, poll_s=0.1)
        assert time.monotonic() - t0 >= 0.5  # it actually waited
        assert np.array_equal(_col(out.completed), ref)

    def test_block_claims_stand_down_under_a_journal_lease(
        self, tmp_path
    ):
        """The guard/worker handshake: while a resume/assembly holds
        the journal lease, block claims return None (both the pre- and
        the post-claim check), and resume after release works."""
        guard = LeaseManager(str(tmp_path), "resume-guard", ttl_s=30.0,
                             heartbeat_s=1e6)
        worker = LeaseManager(str(tmp_path), "worker", ttl_s=30.0,
                              heartbeat_s=1e6)
        assert guard.try_acquire(None) == 0
        assert worker.journal_locked()
        assert worker.try_acquire(0) is None
        # the retreat left no block-lease file behind
        assert not [
            n for n in os.listdir(guard.dir) if n.startswith("block-")
        ]
        guard.release(None)
        assert not worker.journal_locked()
        assert worker.try_acquire(0) == 0
        guard.stop(), worker.stop()

    def test_wait_job_times_out(self, tmp_path):
        with pytest.raises(TimeoutError, match="not terminal"):
            wait_job(
                str(tmp_path / "never"), _fn, _frame(),
                timeout_s=0.3, poll_s=0.05,
            )


# ---------------------------------------------------------------------------


class TestResumeGuard:
    def _crashed_journal(self, tmp_path, df):
        path = str(tmp_path / "crashed")
        with chaos.scoped("jobs.journal_write=fatal:every=3:times=1"):
            with pytest.raises(ChaosFault):
                run_job(
                    "map_rows", _fn, df,
                    job_dir=str(tmp_path), job_id="crashed",
                )
        return path

    @pytest.mark.chaos
    def test_resume_refuses_while_block_leases_live(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        path = self._crashed_journal(tmp_path, df)
        lm = LeaseManager(path, "worker-x", ttl_s=30.0, heartbeat_s=1e6)
        assert lm.try_acquire(4) == 0
        with pytest.raises(StaleLeaseError, match="live block lease"):
            resume_job(path, _fn, df)
        # the retry_quarantined variant refuses identically — clearing
        # quarantine.json under a live drain is the race the guard exists
        # for
        with pytest.raises(StaleLeaseError, match="live block lease"):
            resume_job(path, _fn, df, retry_quarantined=True)
        lm.stop()  # releases the lease
        res = resume_job(path, _fn, df)
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    @pytest.mark.chaos
    def test_expired_leases_do_not_block_resume(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        path = self._crashed_journal(tmp_path, df)
        lm = LeaseManager(path, "dead-worker", ttl_s=0.1, heartbeat_s=1e6)
        assert lm.try_acquire(2) == 0
        lm._stop.set()  # simulate death: no heartbeat, no release
        time.sleep(0.25)
        res = resume_job(path, _fn, df)  # expired lease: no refusal
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    @pytest.mark.chaos
    def test_concurrent_resume_refused_by_journal_lease(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        path = self._crashed_journal(tmp_path, df)
        other = LeaseManager(path, "resume-other", ttl_s=30.0,
                             heartbeat_s=1e6)
        assert other.try_acquire(None) == 0  # the journal-level lease
        with pytest.raises(StaleLeaseError, match="locked"):
            resume_job(path, _fn, df)
        other.stop()
        res = resume_job(path, _fn, df)
        assert res.blocks_restored + res.blocks_computed == 6

    @pytest.mark.chaos
    def test_worker_refused_while_journal_lease_held(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        path = self._crashed_journal(tmp_path, df)
        other = LeaseManager(path, "resume-other", ttl_s=30.0,
                             heartbeat_s=1e6)
        assert other.try_acquire(None) == 0
        with pytest.raises(StaleLeaseError, match="held by"):
            run_worker(
                "map_rows", _fn, df, path=path, worker_id="late",
                lease_ttl_s=15.0,
            )
        other.stop()


# ---------------------------------------------------------------------------


class TestHealthz:
    def test_jobs_status_carries_the_journal_lease_view(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        status = jobs_status()
        j = status["journal"]
        assert j is not None and j["manifest"]
        assert j["blocks"]["total"] == 6 and j["blocks"]["done"] == 6
        assert j["terminal"] and j["workers"] == []
        # a live lease from ANOTHER process's worker shows up: the view
        # is read from the journal, not this process's registry
        lm = LeaseManager(res.path, "other-proc", ttl_s=30.0,
                          heartbeat_s=1e6)
        # (claim a fresh key: all blocks are done, so use the journal
        #  lease to stand in for activity plus a raw block lease file)
        lm._create_excl(
            "block-00099.e000000.lease", lm._payload(0)
        )
        status = jobs_status()
        workers = status["journal"]["workers"]
        assert [w["worker"] for w in workers] == ["other-proc"]
        assert workers[0]["live_leases"] == 1
        lm.stop()

    def test_journal_status_liveness_is_never_cached(
        self, tmp_path, small_chunks
    ):
        """Regression: the mtime-keyed memo must cache only
        time-independent data — a lease EXPIRES without any filesystem
        change (kill -9 the fleet and no mtime moves), so a probe after
        the TTL must reclassify it stale even on a cache hit."""
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        lm = LeaseManager(res.path, "doomed", ttl_s=0.4, heartbeat_s=1e6)
        lm._create_excl("block-00099.e000000.lease", lm._payload(0))
        s1 = journal_status(res.path)
        assert s1["blocks"]["leased_live"] == 1
        assert s1["workers"][0]["live_leases"] == 1
        time.sleep(0.5)  # TTL passes; no file is touched
        s2 = journal_status(res.path)
        assert s2["blocks"]["leased_live"] == 0
        assert s2["workers"][0]["stale_leases"] == 1
        lm.stop()

    def test_healthz_endpoint_embeds_journal_view(
        self, tmp_path, small_chunks
    ):
        import urllib.request

        from tensorframes_tpu.interop.serving import ScoringServer

        df = _frame()
        run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        with ScoringServer(lambda x: {"y": x * 2.0}) as addr:
            with urllib.request.urlopen(
                f"http://{addr}/healthz", timeout=10
            ) as r:
                payload = json.loads(r.read())
        j = payload["jobs"]["journal"]
        assert j["manifest"] and j["blocks"]["done"] == j["blocks"]["total"]


class TestChaosSites:
    def test_new_sites_are_declared(self):
        assert "jobs.lease" in chaos.SITES
        assert "jobs.heartbeat" in chaos.SITES

    @pytest.mark.chaos
    def test_transient_lease_claim_retries(
        self, tmp_path, small_chunks, fast_retries
    ):
        df = _frame()
        path = str(tmp_path / "flaky-lease")
        with chaos.scoped("jobs.lease=transient:every=2"):
            rep = run_worker(
                "map_rows", _fn, df, path=path, worker_id="w",
                lease_ttl_s=15.0, poll_s=0.05,
            )
        assert rep.complete and rep.blocks_computed == 6
        res = wait_job(path, _fn, df, timeout_s=30)
        assert np.array_equal(
            _col(res.completed), _col(tft.map_rows(_fn, df))
        )

    @pytest.mark.chaos
    def test_heartbeat_stall_is_survivable(
        self, tmp_path, small_chunks
    ):
        # a latency injection on the heartbeat sweep delays renewals;
        # with a generous ttl the drain still completes untouched
        df = _frame()
        path = str(tmp_path / "hb-stall")
        with chaos.scoped("jobs.heartbeat=latency:ms=50"):
            rep = run_worker(
                "map_rows", _fn, df, path=path, worker_id="w",
                lease_ttl_s=15.0, heartbeat_s=0.05, poll_s=0.05,
            )
        assert rep.complete
        res = wait_job(path, _fn, df, timeout_s=30)
        assert np.array_equal(
            _col(res.completed), _col(tft.map_rows(_fn, df))
        )


# ---------------------------------------------------------------------------
# the acceptance soak: 3 REAL subprocess workers, kill -9, zombie
# ---------------------------------------------------------------------------

_WORKER_SCRIPT = r"""
import json, sys
import numpy as np
import tensorframes_tpu as tft
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import set_config

path, wid, ttl, hb, report_path = sys.argv[1:6]
set_config(max_rows_per_device_call=16)
x = np.arange(768, dtype=np.float32).reshape(192, 4)
df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(3)
rep = tft.run_worker(
    "map_rows", lambda x: {"y": x * 3.0 + 1.0}, df, path=path,
    worker_id=wid, lease_ttl_s=float(ttl), heartbeat_s=float(hb),
    poll_s=0.2, transient_pass_retries=10,
)
reg = obs_metrics.registry()
out = rep.as_dict()
out["obs"] = {
    "reclaims": reg.get("jobs.leases_reclaimed_total").value(),
    "fence_rejects": reg.get("jobs.fence_rejects_total").value(),
    "claims": reg.get("jobs.leases_claimed_total").value(),
}
with open(report_path, "w") as f:
    json.dump(out, f)
print("WORKER_EXIT", wid)
"""


def _spawn_worker(path, wid, ttl, hb, report_path, chaos_spec):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TFT_CHAOS", None)
    if chaos_spec:
        env["TFT_CHAOS"] = chaos_spec
    return subprocess.Popen(
        [
            sys.executable, "-c", _WORKER_SCRIPT,
            path, wid, str(ttl), str(hb), report_path,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )


def _victim_lease(path, worker_id):
    """The (block, fname) of a live lease held by ``worker_id``, or
    None."""
    lease_dir = os.path.join(path, "leases")
    try:
        names = os.listdir(lease_dir)
    except FileNotFoundError:
        return None
    for n in sorted(names):
        if not (n.startswith("block-") and n.endswith(".lease")):
            continue
        try:
            with open(os.path.join(lease_dir, n)) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get("worker") == worker_id and d.get("state") != "done":
            return int(n.split(".e")[0][len("block-"):]), n
    return None


@pytest.mark.chaos
class TestKillSoak:
    def test_multiprocess_kill_and_zombie_soak(self, tmp_path):
        """The ISSUE 8 acceptance soak. 3 REAL subprocess workers drain
        one 12-block manifest:

        - ``w-healthy`` runs under ``jobs.block`` transients (p=0.25,
          seeded) — absorbed by the worker's transient-pass retry;
        - ``w-victim`` stalls forever inside its first block (chaos
          latency) while heartbeating, and is **kill -9**'d once its
          lease is on disk — a genuine mid-block process death;
        - ``w-zombie`` stalls 5 s inside its first block with
          heartbeats disabled and a 1.2 s TTL — it is presumed dead,
          its block stolen, and its late write must be fence-rejected.

        Asserts: byte-identity with a solo run, ≥ 1 reclaim and ≥ 1
        fence reject on the obs counters, the victim's block reclaimed
        exactly once (surviving record at epoch 1, exactly one done
        record), and zero duplicate/torn ledger records."""
        old_chunk = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            x = np.arange(768, dtype=np.float32).reshape(192, 4)
            df = (
                tft.TensorFrame.from_columns({"x": x})
                .analyze().repartition(3)
            )
            ref = _col(tft.map_rows(_fn, df))
            path = str(tmp_path / "soak")
            reports = {
                w: str(tmp_path / f"report-{w}.json")
                for w in ("w-healthy", "w-victim", "w-zombie")
            }
            healthy = _spawn_worker(
                path, "w-healthy", 20.0, 0.0, reports["w-healthy"],
                "seed=5;jobs.block=transient:p=0.25",
            )
            victim = _spawn_worker(
                path, "w-victim", 2.0, 0.0, reports["w-victim"],
                "jobs.block=latency:ms=120000",
            )
            zombie = _spawn_worker(
                path, "w-zombie", 1.2, 1e6, reports["w-zombie"],
                "jobs.block=latency:ms=5000:times=1",
            )
            try:
                # kill -9 the victim the moment it holds a lease
                deadline = time.monotonic() + 120
                victim_block = None
                while victim_block is None:
                    assert time.monotonic() < deadline, (
                        "victim never claimed a lease"
                    )
                    assert victim.poll() is None, victim.stderr.read()
                    hit = _victim_lease(path, "w-victim")
                    if hit is not None:
                        victim_block = hit[0]
                    else:
                        time.sleep(0.1)
                victim.send_signal(signal.SIGKILL)
                assert victim.wait(timeout=30) == -signal.SIGKILL
                out_h = healthy.communicate(timeout=240)
                out_z = zombie.communicate(timeout=240)
                assert healthy.returncode == 0, out_h[1][-4000:]
                assert zombie.returncode == 0, out_z[1][-4000:]
            finally:
                for p in (healthy, victim, zombie):
                    if p.poll() is None:
                        p.kill()
            rep_h = json.load(open(reports["w-healthy"]))
            rep_z = json.load(open(reports["w-zombie"]))
            assert not os.path.exists(reports["w-victim"])  # it died
            # the acceptance counters, from the workers' own registries
            reclaims = rep_h["obs"]["reclaims"] + rep_z["obs"]["reclaims"]
            fences = (
                rep_h["obs"]["fence_rejects"]
                + rep_z["obs"]["fence_rejects"]
            )
            assert reclaims >= 1, (rep_h, rep_z)
            assert fences >= 1, (rep_h, rep_z)
            assert rep_z["fence_rejects"] >= 1  # the zombie specifically
            # no duplicate or torn records: 12 blocks, 12 unique dones
            recs = _done_records(path)
            assert len(recs) == 12
            assert len({r["block"] for r in recs}) == 12
            # the victim's block was reclaimed EXACTLY once: its
            # surviving record sits at epoch 1, by someone else
            vrec = [r for r in recs if r["block"] == victim_block]
            assert len(vrec) == 1
            assert vrec[0]["epoch"] == 1
            assert vrec[0]["worker"] in ("w-healthy", "w-zombie")
            # byte-identity with the solo run, assembled in THIS process
            # (which computed nothing)
            res = wait_job(path, _fn, df, timeout_s=60)
            assert res.blocks_restored == 12 and res.blocks_computed == 0
            assert np.array_equal(_col(res.completed), ref)
            assert res.completed.num_partitions == df.num_partitions
        finally:
            set_config(max_rows_per_device_call=old_chunk)
