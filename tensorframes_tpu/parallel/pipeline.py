"""Pipeline parallelism: layer stages sharded over a ``pp`` axis.

GPipe-style schedule, TPU-first: every chip holds ONE stage's parameters
(the stage axis of a stacked parameter pytree is sharded over ``pp``), the
batch splits into microbatches, and activations hop chip-to-chip with
``ppermute`` — neighbor traffic on ICI, the same primitive the ring
attention uses. One ``shard_map`` program runs the whole schedule as a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks; at each tick a chip
applies its stage to whatever microbatch is currently resident, then
passes the result downstream. No reference analog exists (SURVEY §2.5:
model parallelism "absent").

The stage function is uniform (same code per stage, per-stage parameters
differ) — the standard homogeneous-transformer-block case.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from .compat import axis_size as _axis_size, shard_map as _shard_map

__all__ = ["pipeline_apply", "pipeline_reference", "pipeline_train_step"]

#: canonical pipeline axis name
PIPE_AXIS = "pp"



def _check_batch_axis(mesh, axis_name, batch_axis, mb):
    """Shared pre-flight for the batch-parallel composition: the batch axis
    must be a real mesh axis distinct from the pipeline axis, and the
    microbatch must shard evenly over it."""
    if batch_axis is None:
        return
    if batch_axis == axis_name:
        raise ValueError(
            f"batch_axis must differ from the pipeline axis "
            f"{axis_name!r}: sharding rows over the stage axis would "
            f"feed only one rank's rows through the schedule"
        )
    if batch_axis not in mesh.shape:
        raise ValueError(
            f"batch_axis {batch_axis!r} is not a mesh axis; mesh has "
            f"{tuple(mesh.shape)}"
        )
    if mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch size {mb} must divide by the {batch_axis!r} "
            f"axis size {mesh.shape[batch_axis]}"
        )


def pipeline_reference(stage_fn, stacked_params, x):
    """Oracle: apply the stages sequentially on one device.
    ``stacked_params``: pytree whose leaves have a leading stage axis."""
    import jax

    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    h = x
    for s in range(n_stages):
        p_s = jax.tree.map(lambda a: a[s], stacked_params)
        h = stage_fn(p_s, h)
    return h


def _pipeline_body(
    stage_fn, n_micro, params_local, x_micro, axis_name, batch_axis=None
):
    """Per-shard schedule. ``params_local``: this chip's stage params (no
    stage axis). ``x_micro``: [n_micro, mb, ...] microbatched input —
    replicated over the pipeline axis (only stage 0 consumes it) and, with
    ``batch_axis``, row-sharded over that axis. Returns [n_micro, mb, ...]
    outputs (valid on the LAST stage; the psum over the PIPELINE axis
    distributes them to every stage; batch shards stay sharded)."""
    import jax
    import jax.numpy as jnp

    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    first = my == 0
    last = my == n - 1
    total_ticks = n_micro + n - 1
    mb_shape = x_micro.shape[1:]

    from ..ops.seq_common import pcast_varying

    def vary(t):
        # carries inherit the microbatch input's variance: pp always, plus
        # the batch axis when microbatch rows are dp-sharded (pp x dp)
        t = pcast_varying(t, axis_name)
        if batch_axis is not None:
            t = pcast_varying(t, batch_axis)
        return t

    perm = [(i, i + 1) for i in range(n - 1)]  # downstream neighbor

    def tick(carry, t):
        held, outs = carry
        # stage 0 loads microbatch t (when one remains); others use the
        # activation received at the end of the previous tick
        mb_idx = jnp.minimum(t, n_micro - 1)
        incoming = jnp.where(
            first, x_micro[mb_idx], held
        )
        y = stage_fn(params_local, incoming)
        # the last stage emits microbatch t - (n - 1) at tick t
        out_idx = t - (n - 1)
        emit = jnp.logical_and(last, out_idx >= 0)
        outs = jax.lax.cond(
            emit,
            lambda o: o.at[jnp.maximum(out_idx, 0)].set(y),
            lambda o: o,
            outs,
        )
        # hand the activation downstream (chip i -> i+1); chip 0 receives
        # garbage it never reads (it always loads fresh microbatches)
        held = jax.lax.ppermute(y, axis_name, perm)
        return (held, outs), None

    held0 = vary(jnp.zeros(mb_shape, x_micro.dtype))
    outs0 = vary(jnp.zeros((n_micro,) + mb_shape, x_micro.dtype))
    (_, outs), _ = jax.lax.scan(
        tick, (held0, outs0), jnp.arange(total_ticks)
    )
    # outputs live on the last stage only; broadcast so every chip (and the
    # replicated out_spec) returns the same array
    keep = jnp.where(last, 1.0, 0.0).astype(outs.dtype)
    return jax.lax.psum(outs * keep, axis_name)


@functools.lru_cache(maxsize=8)
def _pipeline_program(stage_fn, n_micro, mesh, axis_name, batch_axis=None):
    import jax
    from jax.sharding import PartitionSpec as P

    def body(stacked_params, x_micro):
        params_local = jax.tree.map(
            lambda a: a[0], stacked_params
        )  # shard_map gives [1, ...] slabs on the stage axis
        return _pipeline_body(
            stage_fn, n_micro, params_local, x_micro, axis_name, batch_axis
        )

    # microbatch rows ([n_micro, mb, ...] axis 1) shard over batch_axis
    # when given: pp x dp in one program
    x_spec = P(None, batch_axis)
    return jax.jit(
        _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), x_spec),
            out_specs=x_spec,
            # the schedule mixes pp-replicated microbatch input with
            # ppermute-varying activations inside jnp.where; the final
            # psum re-establishes replication over pp (batch shards stay
            # sharded over batch_axis), which the VMA check cannot see
            check_vma=False,
        )
    )


def pipeline_apply(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params,
    x,
    n_micro: int,
    mesh=None,
    axis_name: str = PIPE_AXIS,
    batch_axis=None,
):
    """Run ``x`` through ``n_stages`` pipeline stages sharded over the
    mesh's ``axis_name`` axis.

    ``stage_fn(params, h) -> h``: one stage, shape-preserving. The compiled
    schedule is cached by ``stage_fn``'s IDENTITY — define the stage
    function once and pass the same object every call (an inline lambda
    recreated per call recompiles the whole pipeline each time, the same
    rule as the engine's function frontend).
    ``stacked_params``: pytree with leading stage axis == the axis size.
    ``x``: [B, ...] with ``B % n_micro == 0``.
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != n:
        raise ValueError(
            f"stacked_params has {n_stages} stages; the {axis_name!r} axis "
            f"has {n} devices — they must match (one stage per chip)"
        )
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} must divide by n_micro={n_micro}"
        )
    mb = b // n_micro
    _check_batch_axis(mesh, axis_name, batch_axis, mb)
    x_micro = jnp.reshape(jnp.asarray(x), (n_micro, mb) + x.shape[1:])
    out = _pipeline_program(stage_fn, n_micro, mesh, axis_name, batch_axis)(
        stacked_params, x_micro
    )
    return jnp.reshape(out, x.shape)


# ---------------------------------------------------------------------------
# training through the pipeline
# ---------------------------------------------------------------------------


def _tree_zeros_like(t):
    import jax
    import jax.numpy as jnp

    return jax.tree.map(jnp.zeros_like, t)


def _pipeline_1f1b_body(
    stage_fn,
    loss_fn,
    n_micro,
    params_local,
    extra_params,
    x_micro,
    y_micro,
    axis_name,
    batch_axis=None,
):
    """One-forward-one-backward schedule with recompute-in-backward.

    Per shard: at tick ``t`` chip ``i`` forwards microbatch ``t - i`` (when
    in range) and backwards microbatch ``t - (2(n-1) - i + 1)``. Forward
    activations hop downstream, cotangents hop upstream, both by
    ``ppermute``. Each chip saves only the INPUT activation of in-flight
    microbatches in a ring buffer of depth ``min(n_micro, 2n)`` — the 1F1B
    memory bound — and recomputes the stage forward inside its backward
    (standard rematerialization: ~2 fwd + 1 bwd FLOPs per microbatch).
    GPipe-through-autodiff, by contrast, checkpoints every scan carry:
    O(n_micro) activations per chip.

    The LAST stage fuses ``loss_fn`` into its backward: the cotangent seed
    is d(loss)/d(stage output), so the loss never leaves the device. Chip 0
    collects the input cotangents so embedding-style layers OUTSIDE the
    pipeline can continue the chain (``dx``).

    Returns ``(loss_sum, grads_local, extra_grads, dx)``; every value is a
    SUM over microbatches (callers normalize).
    """
    import jax
    import jax.numpy as jnp

    from ..ops.seq_common import pcast_varying

    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    first = my == 0
    last = my == n - 1
    depth = min(n_micro, 2 * n)
    total_ticks = 2 * (n - 1) + n_micro + 1
    mb_shape = x_micro.shape[1:]

    def vary(t):
        t = pcast_varying(t, axis_name)
        if batch_axis is not None:
            t = pcast_varying(t, batch_axis)
        return t

    perm_down = [(i, i + 1) for i in range(n - 1)]
    perm_up = [(i + 1, i) for i in range(n - 1)]

    def tick(carry, t):
        held_f, held_b, ring, grads, extra_grads, dxs, loss_acc = carry

        # ---- forward slot: chip i forwards microbatch t - i
        f_idx = t - my
        fwd_on = jnp.logical_and(f_idx >= 0, f_idx < n_micro)
        f_clip = jnp.clip(f_idx, 0, n_micro - 1)
        x_in = jnp.where(first, x_micro[f_clip], held_f)
        ring = jax.lax.cond(
            fwd_on,
            lambda r: r.at[f_clip % depth].set(x_in),
            lambda r: r,
            ring,
        )
        y_out = stage_fn(params_local, x_in)

        # ---- backward slot: chip i backwards microbatch
        #      t - (2(n-1) - i + 1); recompute the stage forward from the
        #      saved input, seed the cotangent from the loss on the last
        #      stage, from the downstream ppermute otherwise
        b_idx = t - (2 * (n - 1) - my + 1)
        bwd_on = jnp.logical_and(b_idx >= 0, b_idx < n_micro)
        b_clip = jnp.clip(b_idx, 0, n_micro - 1)
        h_saved = ring[b_clip % depth]
        yb, stage_vjp = jax.vjp(
            lambda p, h: stage_fn(p, h), params_local, h_saved
        )
        lb, loss_vjp = jax.vjp(
            lambda e, yy: loss_fn(e, yy, y_micro[b_clip]), extra_params, yb
        )
        d_extra_b, g_seed = loss_vjp(jnp.ones_like(lb))
        g_use = jnp.where(last, g_seed, held_b)
        dp_b, dh_b = stage_vjp(g_use)

        acc_on = bwd_on
        grads = jax.tree.map(
            lambda a, d: a + jnp.where(acc_on, d, jnp.zeros_like(d)),
            grads,
            dp_b,
        )
        extra_on = jnp.logical_and(acc_on, last)
        extra_grads = jax.tree.map(
            lambda a, d: a + jnp.where(extra_on, d, jnp.zeros_like(d)),
            extra_grads,
            d_extra_b,
        )
        loss_acc = loss_acc + jnp.where(extra_on, lb, 0.0)
        # chip 0's input cotangent continues the chain outside the pipeline
        dxs = jax.lax.cond(
            jnp.logical_and(acc_on, first),
            lambda d: d.at[b_clip].set(dh_b),
            lambda d: d,
            dxs,
        )

        held_f = jax.lax.ppermute(y_out, axis_name, perm_down)
        dh_send = jnp.where(acc_on, dh_b, jnp.zeros_like(dh_b))
        held_b = jax.lax.ppermute(dh_send, axis_name, perm_up)
        return (held_f, held_b, ring, grads, extra_grads, dxs, loss_acc), None

    carry0 = (
        vary(jnp.zeros(mb_shape, x_micro.dtype)),
        vary(jnp.zeros(mb_shape, x_micro.dtype)),
        vary(jnp.zeros((depth,) + mb_shape, x_micro.dtype)),
        vary(_tree_zeros_like(params_local)),
        vary(_tree_zeros_like(extra_params)),
        vary(jnp.zeros((n_micro,) + mb_shape, x_micro.dtype)),
        vary(jnp.zeros((), jnp.float32)),
    )
    (_, _, _, grads, extra_grads, dxs, loss_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(total_ticks)
    )
    # loss/extra grads live on the last stage, dx on the first: psum
    # replicates them over pp (per-stage grads stay per-shard)
    loss_acc = jax.lax.psum(loss_acc, axis_name)
    extra_grads = jax.tree.map(
        lambda a: jax.lax.psum(
            jnp.where(last, a, jnp.zeros_like(a)), axis_name
        ),
        extra_grads,
    )
    keep0 = jnp.where(first, 1.0, 0.0)
    dxs = jax.lax.psum(dxs * keep0.astype(dxs.dtype), axis_name)
    if batch_axis is not None:
        # data-parallel reduction: each batch shard saw its own rows.
        # dx stays per-shard (each shard's cotangent rows are its own) but
        # needs the same 1/nb: the global loss is the mean of shard-local
        # mean losses, so every shard-local derivative carries 1/nb.
        nb = _axis_size(batch_axis)
        loss_acc = jax.lax.psum(loss_acc, batch_axis) / nb
        grads = jax.tree.map(
            lambda a: jax.lax.psum(a, batch_axis) / nb, grads
        )
        extra_grads = jax.tree.map(
            lambda a: jax.lax.psum(a, batch_axis) / nb, extra_grads
        )
        dxs = dxs / nb
    return loss_acc, grads, extra_grads, dxs


@functools.lru_cache(maxsize=8)
def _pipeline_train_program(
    stage_fn, loss_fn, n_micro, mesh, axis_name, batch_axis, schedule
):
    import jax
    from jax.sharding import PartitionSpec as P

    x_spec = P(None, batch_axis)

    if schedule == "1f1b":

        def body(stacked_params, extra_params, x_micro, y_micro):
            params_local = jax.tree.map(lambda a: a[0], stacked_params)
            loss_sum, grads, extra_grads, dxs = _pipeline_1f1b_body(
                stage_fn,
                loss_fn,
                n_micro,
                params_local,
                extra_params,
                x_micro,
                y_micro,
                axis_name,
                batch_axis,
            )
            # normalize: total loss = mean over microbatches
            inv = 1.0 / n_micro
            grads = jax.tree.map(lambda a: (a * inv)[None], grads)
            extra_grads = jax.tree.map(lambda a: a * inv, extra_grads)
            return loss_sum * inv, grads, extra_grads, dxs * inv

        return jax.jit(
            _shard_map(
                body,
                mesh=mesh,
                in_specs=(P(axis_name), P(), x_spec, x_spec),
                out_specs=(P(), P(axis_name), P(), x_spec),
                check_vma=False,
            )
        )

    if schedule != "gpipe":
        raise ValueError(
            f"unknown schedule {schedule!r}; expected 'gpipe' or '1f1b'"
        )

    # GPipe: autodiff straight through the forward schedule (shard_map,
    # ppermute and scan all transpose); simple and the correctness oracle
    # for 1f1b, at O(n_micro) checkpointed activations per chip
    fwd = _shard_map(
        lambda stacked, x_micro: _pipeline_body(
            stage_fn,
            n_micro,
            jax.tree.map(lambda a: a[0], stacked),
            x_micro,
            axis_name,
            batch_axis,
        ),
        mesh=mesh,
        in_specs=(P(axis_name), x_spec),
        out_specs=x_spec,
        check_vma=False,
    )

    def total_loss(stacked, extra, x_micro, y_micro):
        import jax.numpy as jnp

        out = fwd(stacked, x_micro)  # [n_micro, mb, ...]
        losses = jax.vmap(lambda o, t: loss_fn(extra, o, t))(out, y_micro)
        return jnp.mean(losses)

    def step(stacked, extra, x_micro, y_micro):
        loss, (g_stacked, g_extra, dx) = jax.value_and_grad(
            total_loss, argnums=(0, 1, 2)
        )(stacked, extra, x_micro, y_micro)
        return loss, g_stacked, g_extra, dx

    return jax.jit(step)


def pipeline_train_step(
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any, Any], Any],
    stacked_params,
    extra_params,
    x,
    y,
    n_micro: int,
    mesh=None,
    axis_name: str = PIPE_AXIS,
    batch_axis=None,
    schedule: str = "1f1b",
):
    """One training step through the pipeline: loss + grads.

    ``loss_fn(extra_params, y_out_mb, target_mb) -> scalar`` (mean over its
    rows) is fused into the LAST stage's backward. ``extra_params`` are
    replicated parameters consumed by the loss head (unembedding, final
    norm); their grads come back replicated. ``x``/``y``: [B, ...] with
    ``B % n_micro == 0``.

    Returns ``(loss, grads_stacked, grads_extra, dx)`` where ``dx`` (shape
    of ``x``) continues the chain into layers applied BEFORE the pipeline
    (embeddings), so the full model trains even though only the blocks are
    staged. Both schedules produce identical grads; ``'1f1b'`` holds
    ``min(n_micro, 2 * n_stages)`` activations per chip (recompute in
    backward), ``'gpipe'`` autodiffs the forward scan and checkpoints all
    ``n_micro``.

    Like :func:`pipeline_apply`, the compiled program caches on the
    IDENTITY of ``stage_fn``/``loss_fn`` — define them once.
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != n:
        raise ValueError(
            f"stacked_params has {n_stages} stages; the {axis_name!r} axis "
            f"has {n} devices — they must match (one stage per chip)"
        )
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} must divide by n_micro={n_micro}")
    mb = b // n_micro
    _check_batch_axis(mesh, axis_name, batch_axis, mb)
    x_micro = jnp.reshape(jnp.asarray(x), (n_micro, mb) + x.shape[1:])
    y_micro = jnp.reshape(jnp.asarray(y), (n_micro, mb) + y.shape[1:])
    prog = _pipeline_train_program(
        stage_fn, loss_fn, n_micro, mesh, axis_name, batch_axis, schedule
    )
    loss, g_stacked, g_extra, dx = prog(
        stacked_params, extra_params, x_micro, y_micro
    )
    return loss, g_stacked, g_extra, jnp.reshape(dx, x.shape)
