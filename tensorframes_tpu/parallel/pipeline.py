"""Pipeline parallelism: layer stages sharded over a ``pp`` axis.

GPipe-style schedule, TPU-first: every chip holds ONE stage's parameters
(the stage axis of a stacked parameter pytree is sharded over ``pp``), the
batch splits into microbatches, and activations hop chip-to-chip with
``ppermute`` — neighbor traffic on ICI, the same primitive the ring
attention uses. One ``shard_map`` program runs the whole schedule as a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks; at each tick a chip
applies its stage to whatever microbatch is currently resident, then
passes the result downstream. No reference analog exists (SURVEY §2.5:
model parallelism "absent").

The stage function is uniform (same code per stage, per-stage parameters
differ) — the standard homogeneous-transformer-block case.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

__all__ = ["pipeline_apply", "pipeline_reference"]

#: canonical pipeline axis name
PIPE_AXIS = "pp"


def pipeline_reference(stage_fn, stacked_params, x):
    """Oracle: apply the stages sequentially on one device.
    ``stacked_params``: pytree whose leaves have a leading stage axis."""
    import jax

    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    h = x
    for s in range(n_stages):
        p_s = jax.tree.map(lambda a: a[s], stacked_params)
        h = stage_fn(p_s, h)
    return h


def _pipeline_body(
    stage_fn, n_micro, params_local, x_micro, axis_name, batch_axis=None
):
    """Per-shard schedule. ``params_local``: this chip's stage params (no
    stage axis). ``x_micro``: [n_micro, mb, ...] microbatched input —
    replicated over the pipeline axis (only stage 0 consumes it) and, with
    ``batch_axis``, row-sharded over that axis. Returns [n_micro, mb, ...]
    outputs (valid on the LAST stage; the psum over the PIPELINE axis
    distributes them to every stage; batch shards stay sharded)."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    first = my == 0
    last = my == n - 1
    total_ticks = n_micro + n - 1
    mb_shape = x_micro.shape[1:]

    from ..ops.seq_common import pcast_varying

    def vary(t):
        # carries inherit the microbatch input's variance: pp always, plus
        # the batch axis when microbatch rows are dp-sharded (pp x dp)
        t = pcast_varying(t, axis_name)
        if batch_axis is not None:
            t = pcast_varying(t, batch_axis)
        return t

    perm = [(i, i + 1) for i in range(n - 1)]  # downstream neighbor

    def tick(carry, t):
        held, outs = carry
        # stage 0 loads microbatch t (when one remains); others use the
        # activation received at the end of the previous tick
        mb_idx = jnp.minimum(t, n_micro - 1)
        incoming = jnp.where(
            first, x_micro[mb_idx], held
        )
        y = stage_fn(params_local, incoming)
        # the last stage emits microbatch t - (n - 1) at tick t
        out_idx = t - (n - 1)
        emit = jnp.logical_and(last, out_idx >= 0)
        outs = jax.lax.cond(
            emit,
            lambda o: o.at[jnp.maximum(out_idx, 0)].set(y),
            lambda o: o,
            outs,
        )
        # hand the activation downstream (chip i -> i+1); chip 0 receives
        # garbage it never reads (it always loads fresh microbatches)
        held = jax.lax.ppermute(y, axis_name, perm)
        return (held, outs), None

    held0 = vary(jnp.zeros(mb_shape, x_micro.dtype))
    outs0 = vary(jnp.zeros((n_micro,) + mb_shape, x_micro.dtype))
    (_, outs), _ = jax.lax.scan(
        tick, (held0, outs0), jnp.arange(total_ticks)
    )
    # outputs live on the last stage only; broadcast so every chip (and the
    # replicated out_spec) returns the same array
    keep = jnp.where(last, 1.0, 0.0).astype(outs.dtype)
    return jax.lax.psum(outs * keep, axis_name)


@functools.lru_cache(maxsize=8)
def _pipeline_program(stage_fn, n_micro, mesh, axis_name, batch_axis=None):
    import jax
    from jax.sharding import PartitionSpec as P

    def body(stacked_params, x_micro):
        params_local = jax.tree.map(
            lambda a: a[0], stacked_params
        )  # shard_map gives [1, ...] slabs on the stage axis
        return _pipeline_body(
            stage_fn, n_micro, params_local, x_micro, axis_name, batch_axis
        )

    # microbatch rows ([n_micro, mb, ...] axis 1) shard over batch_axis
    # when given: pp x dp in one program
    x_spec = P(None, batch_axis)
    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), x_spec),
            out_specs=x_spec,
            # the schedule mixes pp-replicated microbatch input with
            # ppermute-varying activations inside jnp.where; the final
            # psum re-establishes replication over pp (batch shards stay
            # sharded over batch_axis), which the VMA check cannot see
            check_vma=False,
        )
    )


def pipeline_apply(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params,
    x,
    n_micro: int,
    mesh=None,
    axis_name: str = PIPE_AXIS,
    batch_axis=None,
):
    """Run ``x`` through ``n_stages`` pipeline stages sharded over the
    mesh's ``axis_name`` axis.

    ``stage_fn(params, h) -> h``: one stage, shape-preserving. The compiled
    schedule is cached by ``stage_fn``'s IDENTITY — define the stage
    function once and pass the same object every call (an inline lambda
    recreated per call recompiles the whole pipeline each time, the same
    rule as the engine's function frontend).
    ``stacked_params``: pytree with leading stage axis == the axis size.
    ``x``: [B, ...] with ``B % n_micro == 0``.
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages != n:
        raise ValueError(
            f"stacked_params has {n_stages} stages; the {axis_name!r} axis "
            f"has {n} devices — they must match (one stage per chip)"
        )
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} must divide by n_micro={n_micro}"
        )
    mb = b // n_micro
    if batch_axis is not None:
        if batch_axis == axis_name:
            raise ValueError(
                f"batch_axis must differ from the pipeline axis "
                f"{axis_name!r}: sharding rows over the stage axis would "
                f"feed only one rank's rows through the schedule"
            )
        if batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} is not a mesh axis; mesh has "
                f"{tuple(mesh.shape)}"
            )
        if mb % mesh.shape[batch_axis]:
            raise ValueError(
                f"microbatch size {mb} must divide by the {batch_axis!r} "
                f"axis size {mesh.shape[batch_axis]}"
            )
    x_micro = jnp.reshape(jnp.asarray(x), (n_micro, mb) + x.shape[1:])
    out = _pipeline_program(stage_fn, n_micro, mesh, axis_name, batch_axis)(
        stacked_params, x_micro
    )
    return jnp.reshape(out, x.shape)
