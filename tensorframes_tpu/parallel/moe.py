"""Expert parallelism: a mixture-of-experts FFN sharded over an ``ep`` axis.

The reference has no model parallelism of any kind (SURVEY §2.5: "one graph
replica per partition"); this module and :mod:`.pipeline` complete the mesh
axes the TPU build treats as first-class (dp / tp / sp / ep / pp).

Design, TPU-first: experts are sharded over ``ep`` — each chip holds
``n_experts / n`` expert FFNs. Tokens stay replicated across the axis;
every chip runs its local experts over all tokens with the router's
one-hot mask folded into the expert output, and a single ``psum``
combines the per-chip partials. Static shapes throughout — no
capacity buffers, no token dropping, bit-identical to the dense oracle.
The classic all-to-all token dispatch (:func:`moe_dispatch_apply`) trades
that exactness for lower FLOPs at high expert counts: per-expert capacity
buffers (Switch convention), top-k in k dispatch rounds, fully
differentiable. Both paths train; grads match the dense oracle wherever
no token dropped.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from .compat import axis_size as _axis_size, shard_map as _shard_map

__all__ = [
    "init_moe",
    "moe_ffn",
    "moe_ffn_sharded",
    "moe_apply",
    "moe_dispatch_apply",
    "moe_load_balance_loss",
]

#: canonical expert-parallel axis name
EXPERT_AXIS = "ep"

Params = Dict[str, np.ndarray]


def init_moe(
    seed: int, d_model: int, d_ff: int, n_experts: int, dtype=np.float32
) -> Params:
    """Router + ``n_experts`` two-layer FFNs (stacked on a leading expert
    axis so the expert dim shards cleanly over the mesh)."""
    rng = np.random.default_rng(seed)

    def dense(*shape, fan_in):
        return rng.normal(0, fan_in**-0.5, shape).astype(dtype)

    return {
        "router": dense(d_model, n_experts, fan_in=d_model),
        "w_up": dense(n_experts, d_model, d_ff, fan_in=d_model),
        "b_up": np.zeros((n_experts, d_ff), dtype=dtype),
        "w_down": dense(n_experts, d_ff, d_model, fan_in=d_ff),
        "b_down": np.zeros((n_experts, d_model), dtype=dtype),
    }


def _expert_partials(params, x, expert_offset, gates, expert_ids):
    """Sum of local experts' outputs over tokens routed to them.

    ``x``: [B, L, D]; params hold the LOCAL expert slab (leading axis =
    local expert count); ``expert_ids``/``gates``: [B, L, k] global top-k
    routing (k=1 for switch-style). Masked compute: an expert's output is
    scaled by the sum of the gates of whichever top-k slots chose it."""
    import jax
    import jax.numpy as jnp

    # jnp-ify once: the loop indexes the expert axis with a traced index,
    # which raw numpy arrays cannot do
    w_up_all = jnp.asarray(params["w_up"])
    b_up_all = jnp.asarray(params["b_up"])
    w_down_all = jnp.asarray(params["w_down"])
    b_down_all = jnp.asarray(params["b_down"])

    def one_expert(e_local, acc):
        w_up = w_up_all[e_local]
        b_up = b_up_all[e_local]
        w_down = w_down_all[e_local]
        b_down = b_down_all[e_local]
        h = jax.nn.gelu(x @ w_up + b_up)
        y = h @ w_down + b_down
        mask = (expert_ids == e_local + expert_offset).astype(x.dtype)
        combined_gate = (gates * mask).sum(axis=-1)  # over the k slots
        return acc + y * combined_gate[..., None]

    n_local = w_up_all.shape[0]
    acc0 = jnp.zeros_like(x)
    return jax.lax.fori_loop(
        0, n_local, lambda e, a: one_expert(e, a), acc0
    )


def _route_topk(params, x, k):
    """Top-k routing: ``(gates [B, L, k], expert_ids [B, L, k])``; for
    k > 1 the kept gates renormalize to sum to one (standard top-2
    convention)."""
    import jax
    import jax.numpy as jnp

    # bound by the router's width (the GLOBAL expert count) — inside
    # shard_map params hold only the local expert slab
    n_experts = params["router"].shape[-1]
    if not 1 <= k <= n_experts:
        raise ValueError(f"k={k} must be in [1, {n_experts}]")
    logits = x @ jnp.asarray(params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    gates, expert_ids = jax.lax.top_k(probs, k)
    if k > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return gates, expert_ids


def moe_ffn(params: Params, x, k: int = 1):
    """Dense oracle: top-``k`` routed MoE FFN, all experts local.
    ``x``: [B, L, D] -> [B, L, D]."""
    gates, expert_ids = _route_topk(params, x, k)
    return _expert_partials(params, x, 0, gates, expert_ids)


def moe_ffn_sharded(
    params: Params, x, axis_name: str = EXPERT_AXIS, k: int = 1
):
    """Per-shard body (call inside ``shard_map``): params hold this chip's
    expert slab (leading expert axis sharded over ``axis_name``), ``x`` is
    replicated. Router runs replicated; local experts compute masked
    partials; one ``psum`` combines. Top-k composes for free here: a
    token's k experts may live on different chips, each contributing its
    gate-scaled partial to the same psum."""
    import jax

    my = jax.lax.axis_index(axis_name)
    n_local = params["w_up"].shape[0]
    gates, expert_ids = _route_topk(params, x, k)
    partial = _expert_partials(
        params, x, my * n_local, gates, expert_ids
    )
    return jax.lax.psum(partial, axis_name)


@functools.lru_cache(maxsize=32)
def _moe_program(mesh, axis_name: str, k: int = 1):
    import jax
    from jax.sharding import PartitionSpec as P

    expert_sharded = {
        "router": P(),  # replicated
        "w_up": P(axis_name),
        "b_up": P(axis_name),
        "w_down": P(axis_name),
        "b_down": P(axis_name),
    }
    return jax.jit(
        _shard_map(
            functools.partial(moe_ffn_sharded, axis_name=axis_name, k=k),
            mesh=mesh,
            in_specs=(expert_sharded, P()),
            out_specs=P(),
            # the masked-partial accumulator mixes replicated tokens with
            # ep-varying expert slabs; the closing psum re-establishes
            # replication, which is what the VMA checker cannot see
            check_vma=False,
        )
    )


def moe_apply(
    params: Params, x, mesh=None, axis_name: str = EXPERT_AXIS, k: int = 1
):
    """Full-array entry point: shards the expert slabs over the mesh's
    ``axis_name`` axis and applies the top-``k`` routed MoE FFN.
    ``n_experts`` must divide by the axis size."""
    import jax

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_experts = params["w_up"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"n_experts={n_experts} must divide by the {axis_name!r} axis "
            f"size {n}"
        )
    if not 1 <= k <= n_experts:  # fail fast, before tracing
        raise ValueError(f"k={k} must be in [1, {n_experts}]")
    return _moe_program(mesh, axis_name, k)(params, x)


# ---------------------------------------------------------------------------
# all-to-all (capacity-based) dispatch — the Switch-Transformer data path
# ---------------------------------------------------------------------------


def _dispatch_body(params, x, capacity, axis_name, k):
    """Per-shard body: ``x`` [T_local, D] tokens sharded over ``axis_name``;
    params hold the local expert slab. Tokens are ROUTED: for each of the
    ``k`` routing slots, every chip packs its tokens into a PER-EXPERT
    send buffer of ``capacity`` slots (the Switch convention: capacity
    counts tokens per (source shard, expert), so one expert hogging a
    chip cannot evict its neighbors' traffic), one ``all_to_all``
    exchanges the buffers, local experts run on what arrived, and a
    second ``all_to_all`` returns results to the owning chips. Overflow
    beyond an expert's capacity is dropped (contributes zero) — the
    standard Switch trade; communication is O(E*C*D) per slot instead of
    replicating T. Expert identity travels POSITIONALLY (buffer row =
    local expert), with a validity mask so empty slots contribute nothing
    (an expert's bias would otherwise leak into unused slots)."""
    import jax
    import jax.numpy as jnp

    n = _axis_size(axis_name)
    t_local, d = x.shape
    n_local = params["w_up"].shape[0]
    n_experts = n * n_local

    w_up = jnp.asarray(params["w_up"])
    b_up = jnp.asarray(params["b_up"])
    w_down = jnp.asarray(params["w_down"])
    b_down = jnp.asarray(params["b_down"])

    gates, ids = _route_topk(params, x, k)
    out = jnp.zeros_like(x)
    for j in range(k):  # k static dispatch rounds, one per routing slot
        expert = ids[..., j]                      # global expert id [T]
        gate = gates[..., j]                      # [T]
        # position of each token within ITS EXPERT's send buffer: running
        # count of earlier tokens routed to the same expert (stable
        # priority by position, the Switch convention); >= capacity drops
        onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
        pos = (jnp.cumsum(onehot, axis=0) - onehot)[
            jnp.arange(t_local), expert
        ]
        keep = pos < capacity
        # dropped tokens target the out-of-bounds slot `capacity` so
        # mode="drop" discards them (a clipped in-bounds index would
        # clobber a kept token's slot)
        safe = jnp.where(keep, pos, capacity)
        send = jnp.zeros((n_experts, capacity, d), x.dtype)
        send = send.at[expert, safe].set(x, mode="drop")
        valid = jnp.zeros((n_experts, capacity), x.dtype)
        valid = valid.at[expert, safe].set(
            jnp.ones_like(gate), mode="drop"
        )

        # exchange: destination chip = expert // n_local, positional
        recv = jax.lax.all_to_all(
            send.reshape(n, n_local * capacity, d),
            axis_name, 0, 0, tiled=False,
        ).reshape(n, n_local, capacity, d)
        recv_v = jax.lax.all_to_all(
            valid.reshape(n, n_local * capacity),
            axis_name, 0, 0, tiled=False,
        ).reshape(n, n_local, capacity)

        # local experts over their received slabs ([n_src * C, D] each)
        def one_expert(e, acc):
            te = recv[:, e].reshape(n * capacity, d)
            h = jax.nn.gelu(te @ w_up[e] + b_up[e])
            y = (h @ w_down[e] + b_down[e]).reshape(n, capacity, d)
            y = y * recv_v[:, e][..., None]  # empty slots: no bias leak
            return acc.at[:, e].set(y)

        out_buf = jax.lax.fori_loop(
            0, n_local, one_expert, jnp.zeros_like(recv)
        )

        # return trip, then gather each token's result from its
        # (expert, pos) slot
        back = jax.lax.all_to_all(
            out_buf.reshape(n, n_local * capacity, d),
            axis_name, 0, 0, tiled=False,
        ).reshape(n_experts, capacity, d)
        res = back[expert, jnp.where(keep, pos, 0)]
        out = out + jnp.where(keep[:, None], res * gate[:, None], 0.0)
    return out


@functools.lru_cache(maxsize=32)
def _dispatch_program(mesh, capacity: int, axis_name: str, k: int):
    import jax
    from jax.sharding import PartitionSpec as P

    expert_sharded = {
        "router": P(),
        "w_up": P(axis_name),
        "b_up": P(axis_name),
        "w_down": P(axis_name),
        "b_down": P(axis_name),
    }
    return jax.jit(
        _shard_map(
            functools.partial(
                _dispatch_body, capacity=capacity, axis_name=axis_name, k=k
            ),
            mesh=mesh,
            in_specs=(expert_sharded, P(axis_name)),
            out_specs=P(axis_name),
            check_vma=False,
        )
    )


def moe_dispatch_apply(
    params: Params,
    x,
    mesh=None,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    k: int = 1,
):
    """All-to-all routed MoE over ``[B, L, D]`` (Switch-Transformer data
    path): tokens sharded over ``axis_name``, routed to their experts'
    chips with ``capacity = ceil(cf * T_local / E)`` slots PER
    (source shard, expert) per round, processed, and returned; ``k``
    routing slots dispatch in ``k`` rounds whose gate-scaled results
    sum. Tokens beyond
    an expert's capacity are DROPPED (contribute zero) — choose
    ``capacity_factor`` >= E/k for exactness under any routing, or keep
    the default and accept the standard Switch behavior. Fully
    differentiable (grads match the dense oracle wherever no token
    dropped). Use :func:`moe_apply` for the exact masked-compute variant.
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_experts = params["w_up"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"n_experts={n_experts} must divide by the {axis_name!r} axis "
            f"size {n}"
        )
    if not 1 <= k <= n_experts:
        raise ValueError(f"k={k} must be in [1, {n_experts}]")
    b, l, d = x.shape
    t = b * l
    if t % n:
        raise ValueError(
            f"token count {t} (= {b}x{l}) must divide by the {axis_name!r} "
            f"axis size {n}"
        )
    t_local = t // n
    # capacity is PER ROUND (each of the k rounds dispatches every token
    # exactly once, so expected per-expert load per round is T_local / E
    # regardless of k); total slots across rounds stay at the Switch
    # convention cf * k * T_local / E
    capacity = int(np.ceil(capacity_factor * t_local / n_experts))
    flat = jnp.reshape(jnp.asarray(x), (t, d))
    out = _dispatch_program(mesh, capacity, axis_name, k)(params, flat)
    return jnp.reshape(out, (b, l, d))


def moe_load_balance_loss(params: Params, x, k: int = 1):
    """Switch-Transformer auxiliary load-balancing loss:
    ``E * sum_e f_e * p_e`` where ``f_e`` is the fraction of ROUTING SLOTS
    assigned to expert ``e`` (mean one-hot over all ``k`` top-k slots, so
    the loss reflects actual assignment under top-k routing) and ``p_e``
    the mean router probability. Equals 1.0 under perfectly uniform
    routing; add a small multiple to the task loss to keep experts
    utilized (dropped-token rates down under the capacity dispatch).
    Differentiable through ``p_e`` (the ``f_e`` factor carries no
    gradient, per the standard formulation). Recomputes the router
    projection — one [T, D] x [D, E] matmul, negligible next to the
    expert FFNs — so it composes with any apply path without changing
    their signatures."""
    import jax
    import jax.numpy as jnp

    n_experts = params["w_up"].shape[0]
    logits = x @ jnp.asarray(params["router"])
    probs = jax.nn.softmax(logits, axis=-1).reshape(-1, n_experts)
    _, ids = jax.lax.top_k(probs, k)              # [T, k]
    f = jnp.mean(
        jax.nn.one_hot(ids, n_experts, dtype=probs.dtype), axis=(0, 1)
    )
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(jax.lax.stop_gradient(f) * p)
