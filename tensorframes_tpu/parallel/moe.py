"""Expert parallelism: a mixture-of-experts FFN sharded over an ``ep`` axis.

The reference has no model parallelism of any kind (SURVEY §2.5: "one graph
replica per partition"); this module and :mod:`.pipeline` complete the mesh
axes the TPU build treats as first-class (dp / tp / sp / ep / pp).

Design, TPU-first: experts are sharded over ``ep`` — each chip holds
``n_experts / n`` expert FFNs. Tokens stay replicated across the axis;
every chip runs its local experts over all tokens with the router's
one-hot mask folded into the expert output, and a single ``psum``
combines the per-chip partials. Static shapes throughout — no
capacity buffers, no token dropping, bit-identical to the dense oracle
(the classic all-to-all token dispatch trades that exactness for lower
FLOPs at high expert counts; with top-1 routing the masked compute is the
robust default and the communication is one psum of ``[B, L, D]``).
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

__all__ = [
    "init_moe",
    "moe_ffn",
    "moe_ffn_sharded",
    "moe_apply",
    "moe_dispatch_apply",
    "moe_load_balance_loss",
]

#: canonical expert-parallel axis name
EXPERT_AXIS = "ep"

Params = Dict[str, np.ndarray]


def init_moe(
    seed: int, d_model: int, d_ff: int, n_experts: int, dtype=np.float32
) -> Params:
    """Router + ``n_experts`` two-layer FFNs (stacked on a leading expert
    axis so the expert dim shards cleanly over the mesh)."""
    rng = np.random.default_rng(seed)

    def dense(*shape, fan_in):
        return rng.normal(0, fan_in**-0.5, shape).astype(dtype)

    return {
        "router": dense(d_model, n_experts, fan_in=d_model),
        "w_up": dense(n_experts, d_model, d_ff, fan_in=d_model),
        "b_up": np.zeros((n_experts, d_ff), dtype=dtype),
        "w_down": dense(n_experts, d_ff, d_model, fan_in=d_ff),
        "b_down": np.zeros((n_experts, d_model), dtype=dtype),
    }


def _expert_partials(params, x, expert_offset, gates, expert_ids):
    """Sum of local experts' outputs over tokens routed to them.

    ``x``: [B, L, D]; params hold the LOCAL expert slab (leading axis =
    local expert count); ``expert_ids``/``gates``: [B, L, k] global top-k
    routing (k=1 for switch-style). Masked compute: an expert's output is
    scaled by the sum of the gates of whichever top-k slots chose it."""
    import jax
    import jax.numpy as jnp

    # jnp-ify once: the loop indexes the expert axis with a traced index,
    # which raw numpy arrays cannot do
    w_up_all = jnp.asarray(params["w_up"])
    b_up_all = jnp.asarray(params["b_up"])
    w_down_all = jnp.asarray(params["w_down"])
    b_down_all = jnp.asarray(params["b_down"])

    def one_expert(e_local, acc):
        w_up = w_up_all[e_local]
        b_up = b_up_all[e_local]
        w_down = w_down_all[e_local]
        b_down = b_down_all[e_local]
        h = jax.nn.gelu(x @ w_up + b_up)
        y = h @ w_down + b_down
        mask = (expert_ids == e_local + expert_offset).astype(x.dtype)
        combined_gate = (gates * mask).sum(axis=-1)  # over the k slots
        return acc + y * combined_gate[..., None]

    n_local = w_up_all.shape[0]
    acc0 = jnp.zeros_like(x)
    return jax.lax.fori_loop(
        0, n_local, lambda e, a: one_expert(e, a), acc0
    )


def _route_topk(params, x, k):
    """Top-k routing: ``(gates [B, L, k], expert_ids [B, L, k])``; for
    k > 1 the kept gates renormalize to sum to one (standard top-2
    convention)."""
    import jax
    import jax.numpy as jnp

    # bound by the router's width (the GLOBAL expert count) — inside
    # shard_map params hold only the local expert slab
    n_experts = params["router"].shape[-1]
    if not 1 <= k <= n_experts:
        raise ValueError(f"k={k} must be in [1, {n_experts}]")
    logits = x @ jnp.asarray(params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    gates, expert_ids = jax.lax.top_k(probs, k)
    if k > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return gates, expert_ids


def moe_ffn(params: Params, x, k: int = 1):
    """Dense oracle: top-``k`` routed MoE FFN, all experts local.
    ``x``: [B, L, D] -> [B, L, D]."""
    gates, expert_ids = _route_topk(params, x, k)
    return _expert_partials(params, x, 0, gates, expert_ids)


def moe_ffn_sharded(
    params: Params, x, axis_name: str = EXPERT_AXIS, k: int = 1
):
    """Per-shard body (call inside ``shard_map``): params hold this chip's
    expert slab (leading expert axis sharded over ``axis_name``), ``x`` is
    replicated. Router runs replicated; local experts compute masked
    partials; one ``psum`` combines. Top-k composes for free here: a
    token's k experts may live on different chips, each contributing its
    gate-scaled partial to the same psum."""
    import jax

    my = jax.lax.axis_index(axis_name)
    n_local = params["w_up"].shape[0]
    gates, expert_ids = _route_topk(params, x, k)
    partial = _expert_partials(
        params, x, my * n_local, gates, expert_ids
    )
    return jax.lax.psum(partial, axis_name)


@functools.lru_cache(maxsize=32)
def _moe_program(mesh, axis_name: str, k: int = 1):
    import jax
    from jax.sharding import PartitionSpec as P

    expert_sharded = {
        "router": P(),  # replicated
        "w_up": P(axis_name),
        "b_up": P(axis_name),
        "w_down": P(axis_name),
        "b_down": P(axis_name),
    }
    return jax.jit(
        jax.shard_map(
            functools.partial(moe_ffn_sharded, axis_name=axis_name, k=k),
            mesh=mesh,
            in_specs=(expert_sharded, P()),
            out_specs=P(),
            # the masked-partial accumulator mixes replicated tokens with
            # ep-varying expert slabs; the closing psum re-establishes
            # replication, which is what the VMA checker cannot see
            check_vma=False,
        )
    )


def moe_apply(
    params: Params, x, mesh=None, axis_name: str = EXPERT_AXIS, k: int = 1
):
    """Full-array entry point: shards the expert slabs over the mesh's
    ``axis_name`` axis and applies the top-``k`` routed MoE FFN.
    ``n_experts`` must divide by the axis size."""
    import jax

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_experts = params["w_up"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"n_experts={n_experts} must divide by the {axis_name!r} axis "
            f"size {n}"
        )
    if not 1 <= k <= n_experts:  # fail fast, before tracing
        raise ValueError(f"k={k} must be in [1, {n_experts}]")
    return _moe_program(mesh, axis_name, k)(params, x)


# ---------------------------------------------------------------------------
# all-to-all (capacity-based) dispatch — the Switch-Transformer data path
# ---------------------------------------------------------------------------


def _dispatch_body(params, x, capacity, axis_name):
    """Per-shard body: ``x`` [T_local, D] tokens sharded over ``axis_name``;
    params hold the local expert slab. Tokens are ROUTED: each chip packs
    up to ``capacity`` tokens per destination chip into a [n, C, D] buffer,
    one ``all_to_all`` exchanges them, local experts run on what arrived,
    and a second ``all_to_all`` returns results to the owning chips.
    Overflow beyond capacity is dropped (contributes zero) — the standard
    Switch trade; communication is O(n*C*D) instead of replicating T."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.axis_size(axis_name)
    t_local, d = x.shape
    n_local = params["w_up"].shape[0]

    gates1, ids1 = _route_topk(params, x, 1)     # dispatch is top-1
    expert = ids1[..., 0]                        # global expert id [T]
    gate = gates1[..., 0]                        # [T]
    dst = expert // n_local                      # destination chip [T]
    local_e = expert % n_local                   # expert id on that chip

    # position of each token within its destination's send buffer: running
    # count of earlier tokens with the same destination (stable priority by
    # position, the Switch convention); >= capacity drops
    onehot = jax.nn.one_hot(dst, n, dtype=jnp.int32)        # [T, n]
    pos = (jnp.cumsum(onehot, axis=0) - onehot)[jnp.arange(t_local), dst]
    keep = pos < capacity

    # scatter tokens into the [n, C, D] send buffer; dropped tokens target
    # the out-of-bounds slot `capacity` so mode="drop" discards them (a
    # clipped in-bounds index would clobber a kept token's slot)
    safe_pos = jnp.where(keep, pos, capacity)
    send = jnp.zeros((n, capacity, d), x.dtype)
    send = send.at[dst, safe_pos].set(x, mode="drop")
    # empty slots carry expert id -1, which matches no local expert — no
    # separate validity buffer (and no third all_to_all) needed
    send_e = jnp.full((n, capacity), -1, jnp.int32)
    send_e = send_e.at[dst, safe_pos].set(local_e, mode="drop")

    # exchange: recv[s] = what chip s sent to me
    recv = jax.lax.all_to_all(send, axis_name, 0, 0, tiled=False)
    recv_e = jax.lax.all_to_all(send_e, axis_name, 0, 0, tiled=False)

    toks = recv.reshape(n * capacity, d)
    te = recv_e.reshape(n * capacity)

    # local experts over the received tokens (masked accumulate, same
    # pattern as the replicated path but over n*C tokens, not T)
    w_up = jnp.asarray(params["w_up"])
    b_up = jnp.asarray(params["b_up"])
    w_down = jnp.asarray(params["w_down"])
    b_down = jnp.asarray(params["b_down"])

    def one_expert(e, acc):
        h = jax.nn.gelu(toks @ w_up[e] + b_up[e])
        y = h @ w_down[e] + b_down[e]
        m = (te == e).astype(toks.dtype)[:, None]
        return acc + y * m

    out_toks = jax.lax.fori_loop(
        0, n_local, one_expert, jnp.zeros_like(toks)
    )

    # return trip: results back to the owning chips, then gather each
    # token's result from its (dst, pos) slot
    back = jax.lax.all_to_all(
        out_toks.reshape(n, capacity, d), axis_name, 0, 0, tiled=False
    )
    result = back[dst, jnp.where(keep, pos, 0)]
    return jnp.where(keep[:, None], result * gate[:, None], 0.0)


@functools.lru_cache(maxsize=32)
def _dispatch_program(mesh, capacity: int, axis_name: str):
    import jax
    from jax.sharding import PartitionSpec as P

    expert_sharded = {
        "router": P(),
        "w_up": P(axis_name),
        "b_up": P(axis_name),
        "w_down": P(axis_name),
        "b_down": P(axis_name),
    }
    return jax.jit(
        jax.shard_map(
            functools.partial(
                _dispatch_body, capacity=capacity, axis_name=axis_name
            ),
            mesh=mesh,
            in_specs=(expert_sharded, P(axis_name)),
            out_specs=P(axis_name),
            check_vma=False,
        )
    )


def moe_dispatch_apply(
    params: Params,
    x,
    mesh=None,
    axis_name: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
):
    """All-to-all routed MoE over ``[B, L, D]`` (Switch-Transformer data
    path): tokens sharded over ``axis_name``, routed to their expert's chip
    with ``capacity = ceil(cf * T_local / n)`` slots per (src, dst) pair,
    processed, and returned. Tokens beyond a destination's capacity are
    DROPPED (output zero) — choose ``capacity_factor`` >= n for exactness
    under any routing, or keep the default and accept the standard Switch
    behavior. Use :func:`moe_apply` for the exact masked-compute variant.
    """
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_experts = params["w_up"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"n_experts={n_experts} must divide by the {axis_name!r} axis "
            f"size {n}"
        )
    b, l, d = x.shape
    t = b * l
    if t % n:
        raise ValueError(
            f"token count {t} (= {b}x{l}) must divide by the {axis_name!r} "
            f"axis size {n}"
        )
    t_local = t // n
    capacity = int(np.ceil(capacity_factor * t_local / n))
    flat = jnp.reshape(jnp.asarray(x), (t, d))
    out = _dispatch_program(mesh, capacity, axis_name)(params, flat)
    return jnp.reshape(out, (b, l, d))


def moe_load_balance_loss(params: Params, x):
    """Switch-Transformer auxiliary load-balancing loss:
    ``E * sum_e f_e * p_e`` where ``f_e`` is the fraction of tokens routed
    to expert ``e`` (top-1) and ``p_e`` the mean router probability. Equals
    1.0 under perfectly uniform routing; add a small multiple to the task
    loss to keep experts utilized (dropped-token rates down under the
    capacity dispatch). Differentiable through ``p_e`` (the ``f_e`` factor
    carries no gradient, per the standard formulation). Recomputes the
    router projection — one [T, D] x [D, E] matmul, negligible next to the
    expert FFNs — so it composes with any apply path without changing
    their signatures."""
    import jax
    import jax.numpy as jnp

    n_experts = params["w_up"].shape[0]
    logits = x @ jnp.asarray(params["router"])
    probs = jax.nn.softmax(logits, axis=-1).reshape(-1, n_experts)
    chosen = jnp.argmax(probs, axis=-1)
    f = jnp.mean(
        jax.nn.one_hot(chosen, n_experts, dtype=probs.dtype), axis=0
    )
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(jax.lax.stop_gradient(f) * p)
