"""Expert parallelism: a mixture-of-experts FFN sharded over an ``ep`` axis.

The reference has no model parallelism of any kind (SURVEY §2.5: "one graph
replica per partition"); this module and :mod:`.pipeline` complete the mesh
axes the TPU build treats as first-class (dp / tp / sp / ep / pp).

Design, TPU-first: experts are sharded over ``ep`` — each chip holds
``n_experts / n`` expert FFNs. Tokens stay replicated across the axis;
every chip runs its local experts over all tokens with the router's
one-hot mask folded into the expert output, and a single ``psum``
combines the per-chip partials. Static shapes throughout — no
capacity buffers, no token dropping, bit-identical to the dense oracle
(the classic all-to-all token dispatch trades that exactness for lower
FLOPs at high expert counts; with top-1 routing the masked compute is the
robust default and the communication is one psum of ``[B, L, D]``).
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

__all__ = ["init_moe", "moe_ffn", "moe_ffn_sharded", "moe_apply"]

#: canonical expert-parallel axis name
EXPERT_AXIS = "ep"

Params = Dict[str, np.ndarray]


def init_moe(
    seed: int, d_model: int, d_ff: int, n_experts: int, dtype=np.float32
) -> Params:
    """Router + ``n_experts`` two-layer FFNs (stacked on a leading expert
    axis so the expert dim shards cleanly over the mesh)."""
    rng = np.random.default_rng(seed)

    def dense(*shape, fan_in):
        return rng.normal(0, fan_in**-0.5, shape).astype(dtype)

    return {
        "router": dense(d_model, n_experts, fan_in=d_model),
        "w_up": dense(n_experts, d_model, d_ff, fan_in=d_model),
        "b_up": np.zeros((n_experts, d_ff), dtype=dtype),
        "w_down": dense(n_experts, d_ff, d_model, fan_in=d_ff),
        "b_down": np.zeros((n_experts, d_model), dtype=dtype),
    }


def _expert_partials(params, x, expert_offset, gates, expert_ids):
    """Sum of local experts' outputs over tokens routed to them.

    ``x``: [B, L, D]; params hold the LOCAL expert slab (leading axis =
    local expert count); ``expert_ids``/``gates``: [B, L] global top-1
    routing. Masked compute: experts not chosen contribute zero."""
    import jax
    import jax.numpy as jnp

    # jnp-ify once: the loop indexes the expert axis with a traced index,
    # which raw numpy arrays cannot do
    w_up_all = jnp.asarray(params["w_up"])
    b_up_all = jnp.asarray(params["b_up"])
    w_down_all = jnp.asarray(params["w_down"])
    b_down_all = jnp.asarray(params["b_down"])

    def one_expert(e_local, acc):
        w_up = w_up_all[e_local]
        b_up = b_up_all[e_local]
        w_down = w_down_all[e_local]
        b_down = b_down_all[e_local]
        h = jax.nn.gelu(x @ w_up + b_up)
        y = h @ w_down + b_down
        mask = (expert_ids == e_local + expert_offset).astype(x.dtype)
        return acc + y * (gates * mask)[..., None]

    n_local = w_up_all.shape[0]
    acc0 = jnp.zeros_like(x)
    return jax.lax.fori_loop(
        0, n_local, lambda e, a: one_expert(e, a), acc0
    )


def moe_ffn(params: Params, x):
    """Dense oracle: top-1 routed MoE FFN, all experts local.
    ``x``: [B, L, D] -> [B, L, D]."""
    import jax
    import jax.numpy as jnp

    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_ids = jnp.argmax(probs, axis=-1)  # [B, L]
    gates = jnp.max(probs, axis=-1)  # [B, L]
    return _expert_partials(params, x, 0, gates, expert_ids)


def moe_ffn_sharded(params: Params, x, axis_name: str = EXPERT_AXIS):
    """Per-shard body (call inside ``shard_map``): params hold this chip's
    expert slab (leading expert axis sharded over ``axis_name``), ``x`` is
    replicated. Router runs replicated; local experts compute masked
    partials; one ``psum`` combines."""
    import jax
    import jax.numpy as jnp

    my = jax.lax.axis_index(axis_name)
    n_local = params["w_up"].shape[0]

    logits = x @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_ids = jnp.argmax(probs, axis=-1)
    gates = jnp.max(probs, axis=-1)
    partial = _expert_partials(
        params, x, my * n_local, gates, expert_ids
    )
    return jax.lax.psum(partial, axis_name)


@functools.lru_cache(maxsize=32)
def _moe_program(mesh, axis_name: str):
    import jax
    from jax.sharding import PartitionSpec as P

    expert_sharded = {
        "router": P(),  # replicated
        "w_up": P(axis_name),
        "b_up": P(axis_name),
        "w_down": P(axis_name),
        "b_down": P(axis_name),
    }
    return jax.jit(
        jax.shard_map(
            functools.partial(moe_ffn_sharded, axis_name=axis_name),
            mesh=mesh,
            in_specs=(expert_sharded, P()),
            out_specs=P(),
            # the masked-partial accumulator mixes replicated tokens with
            # ep-varying expert slabs; the closing psum re-establishes
            # replication, which is what the VMA checker cannot see
            check_vma=False,
        )
    )


def moe_apply(params: Params, x, mesh=None, axis_name: str = EXPERT_AXIS):
    """Full-array entry point: shards the expert slabs over the mesh's
    ``axis_name`` axis and applies the MoE FFN. ``n_experts`` must divide
    by the axis size."""
    import jax

    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    n = mesh.shape[axis_name]
    n_experts = params["w_up"].shape[0]
    if n_experts % n:
        raise ValueError(
            f"n_experts={n_experts} must divide by the {axis_name!r} axis "
            f"size {n}"
        )
    return _moe_program(mesh, axis_name)(params, x)
