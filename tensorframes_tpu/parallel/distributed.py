"""Distributed dataframe ops over a device mesh.

TPU-native replacement for the reference's Spark execution plane
(SURVEY §2.5). Mapping of mechanisms:

==========================  =================================================
reference (Spark)           this module (JAX/XLA over a Mesh)
==========================  =================================================
partition -> executor task  row shard -> chip along the ``dp`` mesh axis
broadcast of graph bytes    jit-compiled program, resident per device
``rdd.mapPartitions``       one ``shard_map`` program: each chip maps its
 (``DebugRowOps:377-391``)  shard in place
``RDD.reduce`` driver       ``lax.all_gather`` of per-shard partials over ICI
 funnel (``:524``,          + an on-device fold of the user's merge program —
 ``reducePair:732-750``)    no host round-trip, executed inside the same XLA
                            program as the local reduction
Spark shuffle + UDAF        global key sort + sharded segmented associative
 (``:547-592``)             scan + small boundary-group merge (partial/final
                            aggregation)
==========================  =================================================

Row counts not divisible by the mesh size are handled with a main+tail
split: the bulk runs in the sharded program, the remainder runs as one extra
block, and reduces merge the tail partial through the same pair-merge
program. Partition boundaries are not semantically observable (same contract
as Spark partitions in the reference), so this is behavior-preserving.

Compilation and transfer are both amortized: every jitted program (sharded
main, tail fold, pair merge) is memoized on the CapturedGraph, and
device-sharded copies of immutable columns are memoized per (mesh, split) —
iterative algorithms pay tracing and host->device movement once.

Multi-host: this module only speaks ``jax.devices()`` — under
``jax.distributed.initialize`` the same compiled programs span all hosts'
devices with collectives over DCN. Host-side feeds, however, must come from
each process's addressable rows: :mod:`tensorframes_tpu.parallel.multihost`
provides the per-host input pipeline (``global_batch``/``local_rows``),
exercised for real by the two-process suite in ``tests/test_multihost.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..engine.ops import (
    _as_graph,
    _empty_output,
    _ensure_precision,
    _fetch_column_info,
    _jitted,
    _jitted_vmap,
    _map_rows_thunk,
    _unpack_reduce_result,
)
from ..engine import aggregate as _local_aggregate
from ..engine.validation import (
    InvalidDimensionError,
    check_output_collisions,
    validate_map_inputs,
    validate_reduce_block_graph,
    validate_reduce_row_graph,
)
from ..frame import GroupedFrame, TensorFrame
from ..schema import FrameInfo, Shape, Unknown
from ..utils import get_config, get_logger
from .compat import shard_map as _shard_map
from .mesh import DATA_AXIS, default_mesh

__all__ = ["map_blocks", "map_rows", "reduce_blocks", "reduce_rows", "aggregate"]

logger = get_logger("parallel")


def _mesh_or_default(mesh):
    return mesh if mesh is not None else default_mesh()


def _dp_size(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def _dp_spec():
    from jax.sharding import PartitionSpec as P

    return P(DATA_AXIS)


def _split(n: int, ndev: int):
    main = (n // ndev) * ndev
    return main, n - main


# ---------------------------------------------------------------------------
# per-graph program + feed caches
# ---------------------------------------------------------------------------


def _cached_program(g, key, build: Callable[[], Any]):
    """Memoize a compiled program on the CapturedGraph (the distributed
    analog of the local engine's ``g._jit_cache``). Dispatches retry on
    transient runtime failures, same policy as the local engine
    (``utils.failures``; the reference leans on Spark task retry here)."""
    from ..utils import run_with_retries

    cache = getattr(g, "_shard_cache", None)
    if cache is None:
        cache = {}
        g._shard_cache = cache
    if key not in cache:
        prog = build()

        def dispatch(*a, _prog=prog, _key=key, **k):
            import jax

            def _run():
                # sync inside the retry window — async failures would
                # otherwise surface later, past the handler; distributed
                # results are materialized promptly by their callers
                return jax.block_until_ready(_prog(*a, **k))

            if jax.process_count() > 1:
                # no local retries in a multi-process run: a transient
                # error seen by ONE process would re-enter the collective
                # program alone while peers that succeeded do not, leaving
                # the retried collectives without matching participants
                # (a silent hang at the Gloo/DCN barrier). Fail fast and
                # let the job-level restart (checkpoint/resume) recover —
                # the same contract as a lost Spark executor taking down
                # the stage in the reference.
                return _run()
            return run_with_retries(_run, what=f"distributed program {_key}")

        cache[key] = dispatch
    return cache[key]


def _shard_mapped(g, mesh, body, kind: str, const_names=()):
    """jit(shard_map(body)) with column inputs/outputs row-sharded over
    ``dp`` and ``const_names`` replicated; memoized per (mesh, kind)."""
    import jax
    from jax.sharding import PartitionSpec as P

    const_names = tuple(sorted(const_names))

    def build():
        return jax.jit(
            _shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    {
                        ph: (P() if ph in const_names else _dp_spec())
                        for ph in g.placeholders
                    },
                ),
                out_specs=_dp_spec(),
            )
        )

    return _cached_program(g, (mesh, kind, const_names), build)


def _sharded_main_feed(
    df: TensorFrame, binding: Dict[str, str], mesh, main: int, key_fmt=str
) -> Dict[str, Any]:
    """Feed dict for the sharded main region.

    Columns within the device-cache budget are device_put once with the
    row-sharded NamedSharding and memoized per (mesh, main) on the column;
    larger columns stream as host slices (re-transferred per call, HBM
    bounded)."""
    import jax
    from jax.sharding import NamedSharding

    thr = get_config().device_cache_bytes
    out: Dict[str, Any] = {}
    for ph, col in binding.items():
        cd = df.column_data(col)
        arr = cd.dense
        if arr.nbytes <= thr:
            cache = cd._sharded_cache
            if cache is None:
                cache = {}
                cd._sharded_cache = cache
            ckey = (mesh, main)
            if ckey not in cache:
                cache[ckey] = jax.device_put(
                    arr[:main], NamedSharding(mesh, _dp_spec())
                )
            out[key_fmt(ph)] = cache[ckey]
        else:
            out[key_fmt(ph)] = arr[:main]
    return out


def _tail_feed(
    df: TensorFrame, binding: Dict[str, str], main: int, key_fmt=str
) -> Dict[str, Any]:
    return {
        key_fmt(ph): df.column_data(col).dense[main:]
        for ph, col in binding.items()
    }


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------


def map_blocks(
    fetches,
    dframe: TensorFrame,
    mesh=None,
    trim: bool = False,
    feed_dict: Optional[Dict[str, str]] = None,
    constants: Optional[Dict[str, Any]] = None,
) -> TensorFrame:
    """``map_blocks`` with one row shard per chip: a single ``shard_map``
    program executes the captured graph on every chip's shard concurrently
    (the distributed analog of the reference's per-partition tasks,
    ``DebugRowOps.scala:377-391``). ``constants`` are replicated per-call
    inputs (see the local engine docstring)."""
    mesh = _mesh_or_default(mesh)
    g = _as_graph(
        fetches, dframe, cell_inputs=False, feed_dict=feed_dict,
        constants=constants,
    )
    binding = validate_map_inputs(
        g, dframe.schema, block=True, constants=set(constants or ())
    )
    _ensure_precision(g, dframe.schema)
    input_shapes = {
        ph: dframe.schema[col].block_shape.with_lead(Unknown)
        for ph, col in binding.items()
    }
    out_specs = g.analyze(input_shapes)
    for name, spec in out_specs.items():
        if spec.shape.num_dims == 0:
            raise InvalidDimensionError(
                f"map_blocks output {name!r} is a scalar; map outputs must "
                f"keep the leading row dimension (use reduce_blocks to "
                f"reduce a frame to one row)"
            )
    if not trim:
        check_output_collisions(out_specs, dframe.schema)
    fetch_names = sorted(out_specs)
    fetch_infos = [
        _fetch_column_info(n, out_specs[n], block_output=True)
        for n in fetch_names
    ]
    result_info = FrameInfo(
        fetch_infos if trim else fetch_infos + list(dframe.schema)
    )
    ndev = _dp_size(mesh)
    parent = dframe
    const_feed = {ph: np.asarray(v) for ph, v in (constants or {}).items()}

    def thunk() -> TensorFrame:
        from ..frame.table import _ColumnData

        for col in binding.values():
            parent.column_block(col, None)  # rejects ragged/binary
        n = parent.num_rows
        main, tail = _split(n, ndev)
        pieces: Dict[str, List[np.ndarray]] = {f: [] for f in fetch_names}

        def check_rows(arr, expect, f):
            if not trim and arr.shape[0] != expect:
                raise ValueError(
                    f"map_blocks output {f!r} changed the row count; "
                    f"only trimmed maps may do that"
                )

        if main:
            prog = _shard_mapped(
                g, mesh, g.fn, kind="map", const_names=const_feed
            )
            res = prog(
                _sharded_main_feed(parent, binding, mesh, main) | const_feed
            )
            for f in fetch_names:
                arr = np.asarray(res[f])
                check_rows(arr, main, f)
                pieces[f].append(arr)
        if tail:
            res = _jitted(g)(
                _tail_feed(parent, binding, main) | const_feed
            )
            for f in fetch_names:
                arr = np.asarray(res[f])
                check_rows(arr, tail, f)
                pieces[f].append(arr)
        cols: Dict[str, _ColumnData] = {}
        for f in fetch_names:
            dense = (
                np.concatenate(pieces[f], axis=0)
                if pieces[f]
                else _empty_output(out_specs[f], block_output=True)
            )
            cols[f] = _ColumnData(dense=np.ascontiguousarray(dense))
        if trim:
            return TensorFrame(cols, result_info, num_partitions=ndev)
        for c in parent.schema:
            cols[c.name] = parent.column_data(c.name)
        return TensorFrame(cols, result_info, num_partitions=ndev)

    return TensorFrame({}, result_info, num_partitions=ndev, _thunk=thunk)


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------


def map_rows(
    fetches,
    dframe: TensorFrame,
    mesh=None,
    feed_dict: Optional[Dict[str, str]] = None,
    decoders: Optional[Dict[str, Callable]] = None,
) -> TensorFrame:
    """Distributed row-wise map: rows are bucketed by input cell shape (as in
    the local engine), and each bucket runs as one ``shard_map``-of-``vmap``
    program with rows sharded over the ``dp`` axis — every chip maps its
    slice of the bucket concurrently. Ragged 1-D columns pack into
    (flat, offsets) buffers and feed buckets via a native gather-pad. The
    distributed analog of the reference's per-task row loop
    (``performMapRows``, ``DebugRowOps.scala:396-477,819-857``).

    Binary (host-path) programs have no device program to shard; they
    delegate to the local engine, same as the reference runs them inside an
    ordinary task."""
    import jax

    mesh = _mesh_or_default(mesh)
    if decoders:
        from ..engine.ops import apply_decoders

        dframe = apply_decoders(dframe, decoders, feed_dict)
    g = _as_graph(fetches, dframe, cell_inputs=True, feed_dict=feed_dict)
    binding = validate_map_inputs(g, dframe.schema, block=False)
    host_mode = any(
        dframe.schema[col].scalar_type.name == "binary"
        for col in binding.values()
    )
    if host_mode:
        from ..engine import map_rows as local_map_rows

        return local_map_rows(g, dframe)  # feed_dict already merged into g
    _ensure_precision(g, dframe.schema)
    input_shapes = {
        ph: dframe.schema[col].cell_shape for ph, col in binding.items()
    }
    out_specs = g.analyze(input_shapes, share_lead=False)
    check_output_collisions(out_specs, dframe.schema)
    fetch_names = sorted(out_specs)
    fetch_infos = [
        _fetch_column_info(n, out_specs[n], block_output=False)
        for n in fetch_names
    ]
    result_info = FrameInfo(fetch_infos + list(dframe.schema))
    ndev = _dp_size(mesh)
    parent = dframe

    def run_bucket(feed: Dict[str, Any], m: int) -> Dict[str, Any]:
        """Sharded main region + local tail, concatenated per fetch."""
        main, tail = _split(m, ndev)
        parts = []
        if main:
            vprog = _shard_mapped(g, mesh, jax.vmap(g.fn), kind="map_rows")
            parts.append(vprog({ph: feed[ph][:main] for ph in binding}))
        if tail:
            parts.append(
                _jitted_vmap(g)({ph: feed[ph][main:] for ph in binding})
            )
        if len(parts) == 1:
            return parts[0]
        return {
            f: np.concatenate([np.asarray(r[f]) for r in parts])
            for f in fetch_names
        }

    thunk = _map_rows_thunk(
        parent,
        binding,
        fetch_names,
        out_specs,
        result_info,
        run_bucket=run_bucket,
        result_partitions=ndev,
        # the sharded run_bucket feeds jit(shard_map) programs that expect
        # dp-sharded rows; the local engine's _block_feeder whole-column
        # device copy is the wrong residency for that path, so the
        # device-resident dense fast path is disabled here
        device_resident=False,
    )
    return TensorFrame({}, result_info, num_partitions=ndev, _thunk=thunk)


# ---------------------------------------------------------------------------
# reduce_blocks / reduce_rows
# ---------------------------------------------------------------------------


def reduce_blocks(fetches, dframe: TensorFrame, mesh=None):
    """Distributed block reduce: each chip reduces its shard, partials are
    ``all_gather``-ed over the ``dp`` axis (ICI), and the user's own merge
    program folds them — all in one compiled program. This replaces the
    reference's executors→driver funnel (``DebugRowOps.scala:503-526``)
    with a collective."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    mesh = _mesh_or_default(mesh)
    g = _as_graph(fetches, dframe, cell_inputs=False)
    binding = validate_reduce_block_graph(g, dframe.schema)
    for col in binding.values():
        dframe.column_block(col, None)
    _ensure_precision(g, dframe.schema)
    fetch_names = list(g.fetch_names)

    def prog(feed: Dict[str, Any]) -> Dict[str, Any]:
        local = g.fn(feed)  # per-shard partial
        gathered = {
            f: lax.all_gather(local[f], DATA_AXIS) for f in fetch_names
        }

        def body(carry, xs):
            merged = g.fn(
                {
                    f"{f}_input": jnp.stack([carry[f], xs[f]])
                    for f in fetch_names
                }
            )
            return merged, None

        init = {f: gathered[f][0] for f in fetch_names}
        rest = {f: gathered[f][1:] for f in fetch_names}
        out, _ = lax.scan(body, init, rest)
        # emit as a sharded [1, ...] row per shard; identical on every shard
        return {f: out[f][None] for f in fetch_names}

    n = dframe.num_rows
    if n == 0:
        raise ValueError("reduce_blocks on an empty frame")
    ndev = _dp_size(mesh)
    main, tail = _split(n, ndev)
    fmt = "{}_input".format
    acc = None
    if main:
        sharded = _shard_mapped(g, mesh, prog, kind="reduce_blocks")
        res = sharded(_sharded_main_feed(dframe, binding, mesh, main, fmt))
        acc = {f: res[f][0] for f in fetch_names}
    if tail:
        part = _jitted(g)(_tail_feed(dframe, binding, main, fmt))
        if acc is None:
            acc = part
        else:
            merge = _cached_program(
                g,
                "pair_merge",
                lambda: jax.jit(
                    lambda a, b: g.fn(
                        {
                            f"{f}_input": jnp.stack([a[f], b[f]])
                            for f in fetch_names
                        }
                    )
                ),
            )
            acc = merge(acc, part)
    return _unpack_reduce_result(acc, fetch_names)


def reduce_rows(fetches, dframe: TensorFrame, mesh=None):
    """Distributed pairwise row reduce: per-shard ``lax.scan`` fold, then the
    same all_gather + on-device merge fold as :func:`reduce_blocks`
    (reference ``DebugRowOps.scala:479-501``)."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_or_default(mesh)
    g = _as_graph(fetches, dframe, cell_inputs=True)
    binding = validate_reduce_row_graph(g, dframe.schema)
    for col in binding.values():
        dframe.column_block(col, None)
    _ensure_precision(g, dframe.schema)
    fetch_names = list(g.fetch_names)

    def merge(a, b):
        feed = {}
        for f in fetch_names:
            feed[f"{f}_1"] = a[f]
            feed[f"{f}_2"] = b[f]
        return g.fn(feed)

    def local_fold(feed: Dict[str, Any]) -> Dict[str, Any]:
        init = {f: feed[f][0] for f in fetch_names}
        rest = {f: feed[f][1:] for f in fetch_names}

        def body(c, x):
            return merge(c, x), None

        out, _ = lax.scan(body, init, rest)
        return out

    def prog(feed: Dict[str, Any]) -> Dict[str, Any]:
        local = local_fold(feed)
        gathered = {
            f: lax.all_gather(local[f], DATA_AXIS) for f in fetch_names
        }

        def body(c, x):
            return merge(c, x), None

        init = {f: gathered[f][0] for f in fetch_names}
        rest = {f: gathered[f][1:] for f in fetch_names}
        out, _ = lax.scan(body, init, rest)
        return {f: out[f][None] for f in fetch_names}

    n = dframe.num_rows
    if n == 0:
        raise ValueError("reduce_rows on an empty frame")
    ndev = _dp_size(mesh)
    main, tail = _split(n, ndev)
    acc = None
    if main:
        # the sharded program is fed whole columns keyed by fetch name
        sm = _cached_program(
            g,
            (mesh, "reduce_rows"),
            lambda: jax.jit(
                _shard_map(
                    prog,
                    mesh=mesh,
                    in_specs=({f: P(DATA_AXIS) for f in fetch_names},),
                    out_specs=P(DATA_AXIS),
                )
            ),
        )
        res = sm(_sharded_main_feed(dframe, binding, mesh, main))
        acc = {f: res[f][0] for f in fetch_names}
    if tail:
        fold = _cached_program(
            g, "tail_fold", lambda: jax.jit(local_fold)
        )
        part = fold(_tail_feed(dframe, binding, main))
        if acc is None:
            acc = part
        else:
            pm = _cached_program(g, "pair_merge", lambda: jax.jit(merge))
            acc = pm(acc, part)
    return _unpack_reduce_result(acc, fetch_names)


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------


def aggregate(
    fetches, grouped_data: GroupedFrame, mesh=None
) -> TensorFrame:
    """Distributed keyed aggregation, two-phase (classic partial/final):

    1. rows are globally key-sorted on the host, then one ``shard_map``
       program runs the heavy phase on every chip in parallel: per-row
       partials (the reduce graph on blocks of 1 via ``vmap``) combined by a
       *segmented associative scan*, with segment starts forced at shard
       boundaries so each shard's scan is self-contained;
    2. each shard contributes one partial per locally-seen group (last scan
       element of each segment); a key split across a shard boundary yields
       at most one extra partial, and the small (key, partial) table is
       merged with a final local aggregate.

    This parallelizes the pattern the reference's optimized k-means builds
    *by hand* (in-graph pre-aggregation + global merge,
    ``kmeans_demo.py:101-171``) and its UDAF approximates with bounded
    buffers (``DebugRowOps.scala:644-676``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_or_default(mesh)
    df = grouped_data.frame
    keys = grouped_data.keys
    ndev = _dp_size(mesh)
    n = df.num_rows
    if n == 0:
        raise ValueError("aggregate on an empty frame")
    if n < 2 * ndev:
        return _local_aggregate(fetches, grouped_data)

    g = _as_graph(fetches, df, cell_inputs=False)
    binding = validate_reduce_block_graph(g, df.schema)
    _ensure_precision(g, df.schema)
    fetch_names = list(g.fetch_names)

    # global key sort on device (binary/mixed keys dict-code on host first);
    # main/tail split for non-divisible row counts
    from ..engine.ops import _group_sort

    order, flags, emit_keys = _group_sort(df, keys, binding)
    main, tail = _split(n, ndev)
    # each shard's scan restarts: force a segment start at shard boundaries.
    # _group_sort memoizes its result on the frame, so mutate a copy
    flags = flags.copy()
    shard_rows = main // ndev
    flags[np.arange(1, ndev) * shard_rows] = True
    if tail:
        flags[main] = True

    def scan_body(feed: Dict[str, Any], flags_: Any) -> Dict[str, Any]:
        per_row = jax.vmap(
            lambda cells: g.fn(
                {f"{f}_input": cells[f][None] for f in fetch_names}
            )
        )({f: feed[f] for f in fetch_names})

        def merge_pair(a, b):
            return g.fn(
                {f"{f}_input": jnp.stack([a[f], b[f]]) for f in fetch_names}
            )

        vmerge = jax.vmap(merge_pair)

        def combine(x, y):
            vx, fx = x
            vy, fy = y
            merged = vmerge(vx, vy)
            out = {}
            for f in fetch_names:
                fy_b = fy.reshape(fy.shape + (1,) * (merged[f].ndim - 1))
                out[f] = jnp.where(fy_b, vy[f], merged[f])
            return out, fx | fy

        scanned, _ = lax.associative_scan(combine, (per_row, flags_), axis=0)
        return scanned

    import jax.numpy as jnp

    # feed gather on device: memoized HBM column + device gather by order
    order_dev = jnp.asarray(order)
    sorted_feed = {
        f: df.column_data(col).device()[order_dev]
        for f, col in binding.items()
    }
    # segment ends (known before the scan runs — flags are host bools):
    # last row before each segment start, plus the final row. Gathering the
    # per-group rows ON DEVICE means only #groups rows cross to the host,
    # not the full n-row scan output.
    starts = np.nonzero(flags)[0]
    ends = np.append(starts[1:] - 1, n - 1)
    ends_main = ends[ends < main]
    ends_tail = ends[ends >= main] - main
    pieces: Dict[str, List[np.ndarray]] = {f: [] for f in fetch_names}
    if main:
        sharded_scan = _cached_program(
            g,
            (mesh, "aggregate"),
            lambda: jax.jit(
                _shard_map(
                    scan_body,
                    mesh=mesh,
                    in_specs=(
                        {f: P(DATA_AXIS) for f in fetch_names},
                        P(DATA_AXIS),
                    ),
                    out_specs=P(DATA_AXIS),
                )
            ),
        )
        scanned = sharded_scan(
            {f: a[:main] for f, a in sorted_feed.items()}, flags[:main]
        )
        em = jnp.asarray(ends_main)
        for f in fetch_names:
            pieces[f].append(np.asarray(scanned[f][em]))
    if tail:
        tail_scan = _cached_program(
            g, "aggregate_tail", lambda: jax.jit(scan_body)
        )
        scanned = tail_scan(
            {f: a[main:] for f, a in sorted_feed.items()}, flags[main:]
        )
        et = jnp.asarray(ends_tail)
        for f in fetch_names:
            pieces[f].append(np.asarray(scanned[f][et]))

    partial_cols: Dict[str, Any] = dict(emit_keys(ends))
    for f in fetch_names:
        ps = pieces[f]
        partial_cols[f] = (
            ps[0] if len(ps) == 1 else np.concatenate(ps, axis=0)
        )
    partials = TensorFrame.from_columns(partial_cols).analyze()
    # partial value columns are named after the fetches; rebind the merge
    # graph's f_input placeholders to them and fold boundary duplicates
    g2 = g.with_inputs({f"{f}_input": f for f in fetch_names})
    return _local_aggregate(g2, GroupedFrame(partials, keys))
