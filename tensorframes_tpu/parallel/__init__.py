"""Distributed execution over TPU device meshes.

Replaces the reference's Spark plane (partitions/broadcast/shuffle/driver
funnel, SURVEY §2.5) with ``shard_map`` programs and XLA collectives.
"""

from .compat import has_shard_map, shard_map
from .mesh import make_mesh, default_mesh, data_axis
from .distributed import map_blocks, map_rows, reduce_blocks, reduce_rows, aggregate
from .training import ShardedSGDTrainer
from .moe import (
    init_moe,
    moe_apply,
    moe_dispatch_apply,
    moe_ffn,
    moe_load_balance_loss,
)
from .pipeline import pipeline_apply, pipeline_reference
from . import multihost

__all__ = [
    "has_shard_map",
    "shard_map",
    "multihost",
    "init_moe",
    "moe_apply",
    "moe_dispatch_apply",
    "moe_ffn",
    "moe_load_balance_loss",
    "pipeline_apply",
    "pipeline_reference",
    "make_mesh",
    "default_mesh",
    "data_axis",
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "ShardedSGDTrainer",
]
