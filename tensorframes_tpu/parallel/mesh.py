"""Device meshes: the cluster substrate.

The reference's "cluster" is Spark: partitions scheduled onto executors,
results funneled to the driver (``DebugRowOps.scala:377-391,524``). The
TPU-native substrate is a ``jax.sharding.Mesh``: a named, possibly
multi-dimensional arrangement of chips; collectives ride ICI inside a pod
and DCN across hosts (SURVEY §2.5). One table shard maps to one chip along
the ``dp`` (data/rows) axis; other axes (``tp``...) are reserved for model
sharding in :mod:`tensorframes_tpu.parallel.training`.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

__all__ = ["make_mesh", "default_mesh", "data_axis"]

#: canonical name of the row/data-parallel mesh axis
DATA_AXIS = "dp"


def data_axis() -> str:
    return DATA_AXIS


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a Mesh.

    ``shape``: ordered axis-name -> size dict (e.g. ``{"dp": 4, "tp": 2}``);
    defaults to a 1-D ``{"dp": <all devices>}`` mesh. ``devices`` defaults to
    ``jax.devices()``."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = {DATA_AXIS: len(devs)}
    sizes = tuple(shape.values())
    n = int(np.prod(sizes))
    if n > len(devs):
        raise ValueError(
            f"Mesh shape {shape} needs {n} devices; only {len(devs)} available"
        )
    grid = np.array(devs[:n]).reshape(sizes)
    return Mesh(grid, tuple(shape.keys()))


_default_mesh = None


def default_mesh():
    """Process-wide 1-D data mesh over all devices (cached)."""
    global _default_mesh
    import jax

    if _default_mesh is None or _default_mesh.devices.size != len(jax.devices()):
        _default_mesh = make_mesh()
    return _default_mesh
