"""Mesh-sharded training steps (distributed SGD).

BASELINE config 5 ("map_blocks(grad) + reduce_blocks(sum) on synthetic
rows") is the reference's composition for distributed SGD: gradients per
partition, summed through a driver funnel. The TPU-native form is a single
jitted train step over a ``Mesh`` with named axes:

- ``dp``: batch rows sharded across chips; XLA inserts the gradient
  all-reduce (psum) over ICI where the loss mean crosses the axis;
- ``tp``: weight matrices alternately column-/row-sharded (Megatron-style);
  the row-sharded matmul's partial sums are reduced over ``tp`` by XLA.

Shardings are declared with ``NamedSharding`` on params and batch, and the
compiler (GSPMD) places the collectives — the "pick a mesh, annotate,
let XLA insert collectives" recipe. No NCCL/MPI analog is needed: the same
program spans hosts once ``jax.distributed.initialize`` has run.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.mlp import Params, init_mlp, mlp_loss
from .mesh import make_mesh

__all__ = ["ShardedSGDTrainer"]


class ShardedSGDTrainer:
    """SGD over an MLP with dp x tp sharding.

    ``mesh`` must have axes ``("dp", "tp")`` (either may be size 1).
    """

    def __init__(
        self,
        layer_sizes: Sequence[int],
        mesh=None,
        lr: float = 0.1,
        dtype=np.float32,
    ):
        import jax

        self.layer_sizes = list(layer_sizes)
        if mesh is None:
            n = len(jax.devices())
            tp = 2 if n % 2 == 0 and n >= 2 else 1
            mesh = make_mesh({"dp": n // tp, "tp": tp})
        if set(mesh.axis_names) != {"dp", "tp"}:
            raise ValueError(
                f"ShardedSGDTrainer needs a ('dp','tp') mesh; got "
                f"{mesh.axis_names}"
            )
        self.mesh = mesh
        self.lr = float(lr)
        self.dtype = dtype
        self._step = None

    # -- sharding plan -----------------------------------------------------

    def param_shardings(self):
        """Alternate column-/row-sharding of weight matrices over ``tp``:
        layer 0 splits the output features, layer 1 splits the input
        features (partial-sum reduced by XLA), and so on. Dims not divisible
        by the ``tp`` size stay replicated (e.g. a small logits layer)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = self.mesh.shape["tp"]
        shardings = []
        for i, (fan_in, fan_out) in enumerate(
            zip(self.layer_sizes[:-1], self.layer_sizes[1:])
        ):
            if i % 2 == 0 and fan_out % tp == 0:
                w_spec = P(None, "tp")
                b_spec = P("tp")
            elif i % 2 == 1 and fan_in % tp == 0:
                w_spec = P("tp", None)
                b_spec = P()
            else:
                w_spec = P()
                b_spec = P()
            shardings.append(
                {
                    "w": NamedSharding(self.mesh, w_spec),
                    "b": NamedSharding(self.mesh, b_spec),
                }
            )
        return shardings

    def batch_shardings(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return (
            NamedSharding(self.mesh, P("dp", None)),  # x
            NamedSharding(self.mesh, P("dp")),  # y
        )

    # -- params ------------------------------------------------------------

    def init_params(self, seed: int = 0) -> Params:
        import jax

        host = init_mlp(seed, self.layer_sizes, self.dtype)
        return jax.device_put(host, self.param_shardings())

    def place_batch(self, x: np.ndarray, y: np.ndarray):
        """Place a batch with the dp/tp shardings. Single-process: a plain
        sharded transfer. Multi-host: ``x``/``y`` are this process's local
        rows and each host contributes its addressable shard — no host
        holds the global batch (see :mod:`.multihost`)."""
        import jax

        xs, ys = self.batch_shardings()
        if jax.process_count() > 1:
            return (
                jax.make_array_from_process_local_data(xs, np.asarray(x)),
                jax.make_array_from_process_local_data(ys, np.asarray(y)),
            )
        return jax.device_put(x, xs), jax.device_put(y, ys)

    # -- the step ----------------------------------------------------------

    def train_step(self):
        """The jitted ``(params, x, y) -> (params, loss)`` step; built once.
        Donating params buys in-place updates on device."""
        if self._step is not None:
            return self._step
        import jax

        lr = self.lr

        def step(params, x, y):
            loss, grads = jax.value_and_grad(mlp_loss)(params, x, y)
            new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return new_params, loss

        # no buffer donation: fit() may be handed caller-owned params that
        # must stay alive after the step
        self._step = jax.jit(
            step, out_shardings=(self.param_shardings(), None)
        )
        return self._step

    def fit(
        self,
        x: np.ndarray,
        y: np.ndarray,
        steps: int = 10,
        params: Optional[Params] = None,
        seed: int = 0,
        resume: Optional[str] = None,
        checkpoint_every: Optional[int] = None,
        on_step: Optional[Callable[[int, float], None]] = None,
    ) -> Tuple[Params, List[float]]:
        """Run SGD for ``steps`` steps.

        ``resume``: a checkpoint directory. The latest step-numbered
        checkpoint there (if any) is restored — with the dp×tp shardings of
        this trainer's param plan — and training continues from that step;
        new checkpoints are written every ``checkpoint_every`` steps and at
        the end, so a killed run picks up where it left off (the reference
        rode Spark's task retry instead, SURVEY §5; the process-death drill
        in ``tests/test_multihost.py`` exercises exactly this path).

        ``on_step(step_number, loss)`` fires after every completed step —
        metrics hooks, and the failure-injection point for the drill."""
        from ..utils.checkpoint import run_checkpointed_loop

        params = params if params is not None else self.init_params(seed)
        xd, yd = self.place_batch(x, y)
        step = self.train_step()
        return run_checkpointed_loop(
            lambda p: step(p, xd, yd),
            params,
            steps,
            resume=resume,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
        )
