"""jax API compatibility: one ``shard_map`` for every supported jax.

The parallel layer targets the TOP-LEVEL ``jax.shard_map`` API (jax >=
0.5, keyword ``check_vma`` from 0.6); older releases expose the same
machinery as ``jax.experimental.shard_map.shard_map`` with the
replication check spelled ``check_rep``. Every mesh-crossing program in
the tree builds through this module's :func:`shard_map` so the whole
suite — ring/ulysses attention, the distributed engine, expert-parallel
MoE, pipeline training, and the tensor-parallel serving programs — runs
on either API instead of skipping 36 tier-1 tests on older jax
(ISSUE 14 satellite; ``tests/_gates.py`` keys its gate off
:func:`has_shard_map`).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

__all__ = [
    "axis_size",
    "has_shard_map",
    "process_allgather_stacked",
    "shard_map",
]


@functools.lru_cache(maxsize=1)
def _resolve():
    """(callable, name of its replication-check kwarg or None) — the
    best shard_map this jax offers, probed once."""
    import inspect

    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        try:
            from jax.experimental.shard_map import shard_map as fn
        except ImportError:
            return None, None
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # C-level or wrapped: assume newest
        return fn, "check_vma"
    for kw in ("check_vma", "check_rep"):
        if kw in params:
            return fn, kw
    return fn, None


def has_shard_map() -> bool:
    """Whether this jax offers ANY shard_map (top-level or
    experimental) — what the test gate and the TP serving path probe."""
    return _resolve()[0] is not None


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: Optional[bool] = None,
) -> Any:
    """``jax.shard_map`` on jax >= 0.5, else
    ``jax.experimental.shard_map.shard_map`` — with ``check_vma``
    translated to the resolved API's replication-check spelling
    (``check_rep`` on older releases; dropped where unsupported)."""
    fn, check_kw = _resolve()
    if fn is None:
        import jax

        raise AttributeError(
            f"jax {jax.__version__} offers neither jax.shard_map nor "
            f"jax.experimental.shard_map — the parallel layer cannot "
            f"build mesh programs on this version"
        )
    kw = {}
    if check_vma is not None and check_kw is not None:
        kw[check_kw] = check_vma
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def process_allgather_stacked(x):
    """``multihost_utils.process_allgather(tiled=False)`` with the
    ``[n_processes, ...]`` leading axis GUARANTEED. jax releases before
    ~0.5 short-circuit the single-process case to the unstacked input
    (no leading axis), which breaks every caller that indexes
    ``out[p]`` — exactly the shape-contract drift this module exists to
    absorb. Detected by shape, so multi-process behavior (which stacks
    correctly on every version) passes through untouched."""
    import numpy as np
    from jax.experimental import multihost_utils

    out = np.asarray(multihost_utils.process_allgather(x))
    if out.shape == np.shape(x):
        out = out[None]
    return out


def axis_size(axis_name: str) -> int:
    """The named mesh axis's size from inside a shard_map body:
    ``jax.lax.axis_size`` where this jax has it, else the classic
    ``psum(1, axis)`` constant-fold (pre-0.5 spelling — the sum of one
    over a static named axis folds to a Python int at trace time)."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)
