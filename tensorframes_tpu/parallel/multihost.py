"""Multi-host (multi-process) execution: the DCN story.

The reference's cross-host communication *is* Spark: Py4J control plane,
torrent broadcast of the graph, shuffle for groupBy, and an
executors-to-driver funnel for reduces
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:376,524,576``).
The TPU-native replacement has no driver funnel: every host runs the SAME
program, ``jax.distributed.initialize`` wires the processes into one
runtime, meshes span every host's devices, and XLA routes collectives over
ICI within a pod and DCN across pods/hosts (SURVEY §2.5). Each host feeds
only its addressable shard (per-host input pipelines — the part the
reference never solved, SURVEY §7 hard-part 6).

On CPU this is exercised for real: multiple processes with virtual
devices, cross-process collectives over Gloo — the same code path
``jax.distributed`` uses across TPU hosts over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = [
    "initialize",
    "is_multihost",
    "process_count",
    "process_index",
    "global_batch",
    "local_rows",
    "sync_global",
]


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_count: Optional[int] = None,
) -> None:
    """Join this process into a multi-host runtime.

    Thin wrapper over ``jax.distributed.initialize`` that can also size the
    CPU backend at ``local_device_count`` virtual devices per process —
    the testing topology (N processes x M virtual devices) that stands in
    for N hosts x M chips. Must run before any jax computation initializes
    the backends."""
    import jax

    if local_device_count is not None:
        try:
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except Exception as e:  # backends already initialized, or old jax
            from ..utils import get_logger

            get_logger("multihost").warning(
                "could not size the CPU backend at %d devices (%s); "
                "device count will be whatever the backend reports",
                local_device_count,
                e,
            )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def global_batch(local: np.ndarray, mesh, spec=None):
    """Assemble a globally-sharded array from each process's local rows.

    ``local`` is THIS process's slice along the leading (row) axis; every
    process contributes its own. ``spec`` defaults to rows-over-``dp``,
    trailing dims replicated. The result is addressable-shard-backed: no
    host ever materializes the global array (the reference, by contrast,
    funnels global state through the driver)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import DATA_AXIS

    if spec is None:
        spec = P(DATA_AXIS, *([None] * (np.ndim(local) - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def local_rows(n_rows: int) -> slice:
    """The contiguous row range this process should load, under the even
    row split ``global_batch`` expects: process i of p takes rows
    ``[i*n/p, (i+1)*n/p)``."""
    import jax

    p, i = jax.process_count(), jax.process_index()
    if n_rows % p != 0:
        raise ValueError(
            f"{n_rows} rows do not split evenly over {p} processes; pad or "
            f"trim the dataset so every host feeds the same shard size"
        )
    per = n_rows // p
    return slice(i * per, (i + 1) * per)


def sync_global(x):
    """Fetch a (replicated or sharded) global array to every host, via an
    all-gather across processes when needed. For small results only —
    this is the one deliberate host materialization point."""
    import jax

    arr = x
    if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)
