"""Multi-host (multi-process) execution: the DCN story.

The reference's cross-host communication *is* Spark: Py4J control plane,
torrent broadcast of the graph, shuffle for groupBy, and an
executors-to-driver funnel for reduces
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:376,524,576``).
The TPU-native replacement has no driver funnel: every host runs the SAME
program, ``jax.distributed.initialize`` wires the processes into one
runtime, meshes span every host's devices, and XLA routes collectives over
ICI within a pod and DCN across pods/hosts (SURVEY §2.5). Each host feeds
only its addressable shard (per-host input pipelines — the part the
reference never solved, SURVEY §7 hard-part 6).

On CPU this is exercised for real: multiple processes with virtual
devices, cross-process collectives over Gloo — the same code path
``jax.distributed`` uses across TPU hosts over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .compat import shard_map as _shard_map

__all__ = [
    "initialize",
    "is_multihost",
    "process_count",
    "process_index",
    "global_batch",
    "local_rows",
    "sync_global",
    "map_blocks",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
]


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_count: Optional[int] = None,
) -> None:
    """Join this process into a multi-host runtime.

    Thin wrapper over ``jax.distributed.initialize`` that can also size the
    CPU backend at ``local_device_count`` virtual devices per process —
    the testing topology (N processes x M virtual devices) that stands in
    for N hosts x M chips. Must run before any jax computation initializes
    the backends."""
    import jax

    if local_device_count is not None:
        try:
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except Exception as e:  # backends already initialized, or old jax
            from ..utils import get_logger

            get_logger("multihost").warning(
                "could not size the CPU backend at %d devices (%s); "
                "device count will be whatever the backend reports",
                local_device_count,
                e,
            )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def global_batch(local: np.ndarray, mesh, spec=None):
    """Assemble a globally-sharded array from each process's local rows.

    ``local`` is THIS process's slice along the leading (row) axis; every
    process contributes its own. ``spec`` defaults to rows-over-``dp``,
    trailing dims replicated. The result is addressable-shard-backed: no
    host ever materializes the global array (the reference, by contrast,
    funnels global state through the driver)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import DATA_AXIS

    if spec is None:
        spec = P(DATA_AXIS, *([None] * (np.ndim(local) - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def local_rows(n_rows: int) -> slice:
    """The contiguous row range this process should load, under the even
    row split ``global_batch`` expects: process i of p takes rows
    ``[i*n/p, (i+1)*n/p)``."""
    import jax

    p, i = jax.process_count(), jax.process_index()
    if n_rows % p != 0:
        raise ValueError(
            f"{n_rows} rows do not split evenly over {p} processes; pad or "
            f"trim the dataset so every host feeds the same shard size"
        )
    per = n_rows // p
    return slice(i * per, (i + 1) * per)


def sync_global(x):
    """Fetch a (replicated or sharded) global array to every host, via an
    all-gather across processes when needed. For small results only —
    this is the one deliberate host materialization point."""
    import jax

    arr = x
    if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)


# ---------------------------------------------------------------------------
# dataframe ops over a multi-process mesh: each host feeds its local rows
# ---------------------------------------------------------------------------


def _mh_registry(df) -> dict:
    """The frame's registry of globally-sharded device arrays, one per
    column: ``{col: (mesh, jax.Array)}``. Frames are immutable, so a cached
    global assembly of a column stays valid for the frame's lifetime."""
    reg = getattr(df, "_mh_global", None)
    if reg is None:
        reg = {}
        df._mh_global = reg
    return reg


def _global_feed_col(local_df, col, mesh):
    """The globally-sharded device feed for one column, memoized so that
    chained multihost ops (and repeated passes over the same frame) reuse
    the sharded array instead of re-assembling it from host rows — the
    multi-process analog of the local engine's device residency
    (single-chip results chain in HBM without host round-trips; here the
    global result chains in the fleet's HBM without ever touching a host).
    The reference re-marshals rows through the JVM on every Session.run
    (``TFDataOps.scala:27-59``); neither plane here does.

    Two cache levels: the frame-level ``_mh_global`` registry (a lazy
    multihost result's own fetch arrays — their column storage doesn't
    exist until the thunk runs), then the column-level ``_sharded_cache``
    on ``_ColumnData`` — shared with every frame aliasing the column and
    released by ``unpersist_device`` on any of them. Caching honors the
    same ``device_cache_bytes`` budget as the single-process sharded feed
    (``distributed.py``): a column over budget is assembled transiently
    and freed after the op, so HBM use stays bounded."""
    from ..utils import get_config

    reg = getattr(local_df, "_mh_global", None)
    if reg:
        hit = reg.get(col)
        if hit is not None and hit[0] == mesh:
            return hit[1]
    cd = local_df.column_data(col)
    local_df.column_block(col)  # dense check (raises for ragged/binary)
    host = cd.host()
    if host.nbytes > get_config().device_cache_bytes:
        return global_batch(host, mesh)  # transient: over budget
    cache = cd._sharded_cache
    if cache is None:
        cache = cd._sharded_cache = {}
    key = ("mh_global", mesh)
    arr = cache.get(key)
    if arr is None:
        arr = global_batch(host, mesh)
        cache[key] = arr
    return arr


def _lazy_mh_result(res, g, local_df, mesh, out_specs, block_output, feed, binding):
    """Build the lazy local result frame for a multihost map: the global
    result arrays stay sharded over the mesh (registered for reuse by the
    next multihost op); this process's host rows materialize only if the
    frame is actually read. Input columns alias the parent's storage, same
    as the single-process engine."""
    from ..engine.ops import _fetch_column_info
    from ..frame import TensorFrame
    from ..frame.table import _ColumnData
    from ..schema import FrameInfo
    from ..utils import get_config

    fetch_names = list(g.fetch_names)
    result_info = FrameInfo(
        [
            _fetch_column_info(n, out_specs[n], block_output=block_output)
            for n in fetch_names
        ]
        + list(local_df.schema)
    )

    def thunk():
        cols = {
            n: _ColumnData(dense=_local_rows_of(res[n])) for n in fetch_names
        }
        for c in local_df.schema:
            cols[c.name] = local_df.column_data(c.name)
        return TensorFrame(
            cols, result_info, num_partitions=local_df.num_partitions
        )

    out = TensorFrame(
        {}, result_info, num_partitions=local_df.num_partitions, _thunk=thunk
    )
    reg = _mh_registry(out)
    for n in fetch_names:
        reg[n] = (mesh, res[n])
    # every parent column passes through, so keep a chained op on ANY of
    # them lazy: propagate the parent's registry (its fetch arrays), and
    # reference this pass's input feeds when they fit the cache budget
    # (over-budget feeds were transient — pinning them here would defeat
    # the HBM bound). These are refs to arrays the _ColumnData cache
    # already holds, not extra copies; release is per-frame, see
    # ``unpersist_device``.
    budget = get_config().device_cache_bytes
    for ph, col in binding.items():
        # same byte basis as _global_feed_col's cache decision (per-process
        # host bytes, not the global array): a column cached there must be
        # registered here, or a chained op on a pass-through column would
        # force the lazy frame and re-materialize every fetch column
        if feed[ph].nbytes // process_count() <= budget:
            reg.setdefault(col, (mesh, feed[ph]))
    parent_reg = getattr(local_df, "_mh_global", None)
    if parent_reg:
        for col, entry in parent_reg.items():
            reg.setdefault(col, entry)
    return out


def map_blocks(fetches, local_df, mesh, feed_dict=None):
    """Multi-host ``map_blocks``: ``local_df`` holds THIS process's rows;
    all processes call with the same program and their own shard. Returns
    a lazy local frame of this process's result rows (fetch columns +
    inputs). The collective program dispatches NOW (multi-host programs
    are SPMD — every process must reach the rendezvous), but the result
    stays sharded over the fleet's devices: chained multihost ops feed it
    straight back without any host round-trip, and this process's host
    rows materialize only if the frame is actually read."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.ops import _as_graph, _ensure_precision
    from ..engine.validation import (
        InvalidDimensionError,
        check_output_collisions,
        validate_map_inputs,
    )
    from ..schema import Unknown
    from .distributed import _cached_program
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=False, feed_dict=feed_dict)
    binding = validate_map_inputs(g, local_df.schema, block=True)
    _ensure_precision(g, local_df.schema)
    # same pre-flight contract as the single-process engine: no scalar
    # outputs, no collisions with existing columns
    out_specs = g.analyze(
        {
            ph: local_df.schema[col].block_shape.with_lead(Unknown)
            for ph, col in binding.items()
        }
    )
    for name, spec in out_specs.items():
        if spec.shape.num_dims == 0:
            raise InvalidDimensionError(
                f"map_blocks output {name!r} is a scalar; map outputs must "
                f"keep the leading row dimension (use reduce_blocks)"
            )
    check_output_collisions(out_specs, local_df.schema)
    feed = {
        ph: _global_feed_col(local_df, col, mesh)
        for ph, col in binding.items()
    }
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    prog = _cached_program(
        g,
        (mesh, "mh_map"),
        lambda: jax.jit(
            g.fn, out_shardings={f: sharding for f in g.fetch_names}
        ),
    )
    res = prog(feed)
    return _lazy_mh_result(
        res, g, local_df, mesh, out_specs, True, feed, binding
    )


def _local_rows_of(arr) -> np.ndarray:
    """This process's rows of a dp-sharded global array, in row order,
    deduplicated: on a multi-axis mesh the row shard is replicated over the
    other axes and ``addressable_shards`` yields every replica."""
    seen = set()
    parts = []
    for s in sorted(
        arr.addressable_shards, key=lambda s: s.index[0].start or 0
    ):
        key = (s.index[0].start, s.index[0].stop)
        if key in seen:
            continue
        seen.add(key)
        parts.append(np.asarray(s.data))
    return np.concatenate(parts)


def reduce_blocks(fetches, local_df, mesh):
    """Multi-host ``reduce_blocks``: block-reduce over the GLOBAL rows with
    each process feeding its shard; the result is replicated, so every
    process returns the same numpy value(s) — no driver funnel."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.ops import (
        _as_graph,
        _ensure_precision,
        _unpack_reduce_result,
    )
    from ..engine.validation import validate_reduce_block_graph
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=False)
    binding = validate_reduce_block_graph(g, local_df.schema)
    _ensure_precision(g, local_df.schema)
    feed = {
        f"{f}_input": _global_feed_col(local_df, col, mesh)
        for f, col in binding.items()
    }
    from .distributed import _cached_program

    rep = NamedSharding(mesh, P())
    prog = _cached_program(
        g,
        (mesh, "mh_reduce"),
        lambda: jax.jit(
            g.fn, out_shardings={f: rep for f in g.fetch_names}
        ),
    )
    res = prog(feed)
    host = {f: sync_global(res[f]) for f in g.fetch_names}
    return _unpack_reduce_result(host, g.fetch_names)


# ---------------------------------------------------------------------------
# map_rows / reduce_rows / aggregate: the rest of the op surface
# ---------------------------------------------------------------------------


def map_rows(fetches, local_df, mesh, feed_dict=None):
    """Multi-host row-wise map. All five frame ops run through the
    distributed plane, matching the reference where every op executes
    inside the cluster (row maps run inside Spark tasks,
    ``DebugRowOps.scala:396-477``).

    Execution picks the shape that fits the data:

    - **dense frames** (every bound column has one cell shape): one global
      program — each process contributes its rows via ``global_batch`` and
      a ``vmap`` of the row graph runs over the globally row-sharded
      array; results come back as this process's rows.
    - **ragged / binary frames**: rows with differing cell shapes compile
      per shape bucket, and bucket membership is a property of *local*
      data — so each process maps its own rows with the local engine, the
      exact analog of the reference's partition-local row loop (a Spark
      row map never leaves its executor either). No cross-process
      rendezvous is needed because a row map carries no cross-row
      dataflow.

    Returns a lazy local frame of this process's result rows (fetch
    columns followed by the input columns), like :func:`map_blocks`: the
    global result stays sharded over the mesh for chained multihost ops,
    host rows materialize only on access.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.ops import _as_graph, _ensure_precision
    from ..engine.validation import (
        check_output_collisions,
        validate_map_inputs,
    )
    from .distributed import _cached_program
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=True, feed_dict=feed_dict)
    binding = validate_map_inputs(g, local_df.schema, block=False)
    reg = getattr(local_df, "_mh_global", None) or {}

    def _col_is_dense(col):
        # a column whose global sharded assembly is already registered is
        # dense by construction — answering from the registry keeps a lazy
        # chained frame lazy (no thunk force just to inspect storage)
        hit = reg.get(col)
        if hit is not None and hit[0] == mesh:
            return True
        return (
            local_df.schema[col].scalar_type.name != "binary"
            and local_df.column_data(col).dense is not None
        )

    dense = all(_col_is_dense(col) for col in binding.values())
    if not dense:
        from ..engine import map_rows as local_map_rows

        return local_map_rows(g, local_df)  # feed_dict already merged
    _ensure_precision(g, local_df.schema)
    input_shapes = {
        ph: local_df.schema[col].cell_shape for ph, col in binding.items()
    }
    out_specs = g.analyze(input_shapes, share_lead=False)
    check_output_collisions(out_specs, local_df.schema)
    feed = {
        ph: _global_feed_col(local_df, col, mesh)
        for ph, col in binding.items()
    }
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    prog = _cached_program(
        g,
        (mesh, "mh_map_rows"),
        lambda: jax.jit(
            jax.vmap(g.fn),
            out_shardings={f: sharding for f in g.fetch_names},
        ),
    )
    res = prog(feed)
    return _lazy_mh_result(
        res, g, local_df, mesh, out_specs, False, feed, binding
    )


def reduce_rows(fetches, local_df, mesh):
    """Multi-host pairwise row reduce: one ``shard_map`` program over the
    global mesh — per-shard ``lax.scan`` fold, ``all_gather`` of the
    per-shard partials (ICI within a host, DCN across hosts), and an
    on-device fold of the user's merge graph. Every process returns the
    same value; no driver funnel (reference:
    ``DebugRowOps.scala:479-501``, executors→driver).

    The global row count must divide the mesh size (each process's rows
    already split evenly by ``local_rows``; pad or trim to a multiple of
    the device count).
    """
    import jax
    from jax import lax

    from ..engine.ops import (
        _as_graph,
        _ensure_precision,
        _unpack_reduce_result,
    )
    from ..engine.validation import validate_reduce_row_graph
    from .distributed import _cached_program, _dp_spec
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=True)
    binding = validate_reduce_row_graph(g, local_df.schema)
    _ensure_precision(g, local_df.schema)
    fetch_names = list(g.fetch_names)
    # pre-flight the row count BEFORE assembling the feed, so a bad count
    # raises the actionable error (global_batch would die on an opaque
    # sharding mismatch first). The count comes from the frame registry
    # when the input is a lazy chained result — no host force — else from
    # the local frame.
    ndev = int(np.prod(list(mesh.shape.values())))
    reg = getattr(local_df, "_mh_global", None) or {}
    hit = next(
        (
            reg[c][1]
            for c in binding.values()
            if c in reg and reg[c][0] == mesh
        ),
        None,
    )
    if hit is not None:
        n_global = int(hit.shape[0])
    else:
        n_local = local_df.num_rows
        if n_local == 0:
            raise ValueError("reduce_rows on an empty frame")
        n_global = n_local * process_count()
    if n_global % ndev != 0:
        raise ValueError(
            f"{n_global} global rows do not shard evenly over {ndev} "
            f"devices; pad or trim to a multiple of the device count"
        )
    feed = {
        f: _global_feed_col(local_df, col, mesh)
        for f, col in binding.items()
    }

    def merge(a, b):
        feed = {}
        for f in fetch_names:
            feed[f"{f}_1"] = a[f]
            feed[f"{f}_2"] = b[f]
        return g.fn(feed)

    def prog_body(feed):
        init = {f: feed[f][0] for f in fetch_names}
        rest = {f: feed[f][1:] for f in fetch_names}

        def body(c, x):
            return merge(c, x), None

        local, _ = lax.scan(body, init, rest)
        gathered = {
            f: lax.all_gather(local[f], DATA_AXIS) for f in fetch_names
        }
        init = {f: gathered[f][0] for f in fetch_names}
        rest = {f: gathered[f][1:] for f in fetch_names}
        out, _ = lax.scan(body, init, rest)
        # one identical [1, ...] row per shard; any addressable shard
        # holds the final value
        return {f: out[f][None] for f in fetch_names}

    prog = _cached_program(
        g,
        (mesh, "mh_reduce_rows"),
        lambda: jax.jit(
            _shard_map(
                prog_body,
                mesh=mesh,
                in_specs=({f: _dp_spec() for f in fetch_names},),
                out_specs=_dp_spec(),
            )
        ),
    )
    res = prog(feed)
    acc = {
        f: np.asarray(res[f].addressable_shards[0].data)[0]
        for f in fetch_names
    }
    return _unpack_reduce_result(acc, fetch_names)


def _allgather_partials(partials_df):
    """Exchange each process's (small) partial-aggregate table so every
    process holds the global partial set.

    Group counts differ per process, and ``process_allgather`` requires
    identical shapes — so counts are gathered first, every column is
    padded to the max count, gathered, then trimmed per process and
    concatenated. Binary key columns ride as (lengths, fixed-width uint8)
    pairs sized by the gathered max key length. Partial tables are one row
    per locally-seen group — the only data that crosses hosts, same as the
    reference's partial-aggregation shuffle (``DebugRowOps.scala:547-592``).
    """
    from ..frame import TensorFrame
    from .compat import process_allgather_stacked as ag

    nproc = process_count()
    local_n = partials_df.num_rows
    counts = np.asarray(
        ag(np.asarray([local_n], dtype=np.int64))
    ).reshape(nproc)
    maxc = int(counts.max())

    def gather_numeric(arr):
        pad_shape = (maxc - local_n,) + arr.shape[1:]
        padded = np.concatenate(
            [arr, np.zeros(pad_shape, dtype=arr.dtype)], axis=0
        )
        stacked = np.asarray(ag(padded))  # [P, maxc, ...]
        return np.concatenate(
            [stacked[p, : counts[p]] for p in range(nproc)], axis=0
        )

    cols = {}
    for ci in partials_df.schema:
        cd = partials_df.column_data(ci.name)
        if ci.scalar_type.name == "binary":
            cells = [bytes(c) for c in cd.cells]
            lens = np.asarray(
                [len(c) for c in cells] + [0] * (maxc - local_n),
                dtype=np.int64,
            )
            maxlen = int(np.asarray(ag(lens.max(initial=0))).max())
            buf = np.zeros((maxc, maxlen), dtype=np.uint8)
            for i, c in enumerate(cells):
                buf[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
            all_lens = np.asarray(ag(lens))  # [P, maxc]
            all_buf = np.asarray(ag(buf))  # [P, maxc, maxlen]
            out = []
            for p in range(nproc):
                for i in range(int(counts[p])):
                    out.append(
                        all_buf[p, i, : all_lens[p, i]].tobytes()
                    )
            cols[ci.name] = out
        else:
            cols[ci.name] = gather_numeric(cd.host())
    return TensorFrame.from_columns(cols)


def aggregate(fetches, grouped_data, mesh):
    """Multi-host keyed aggregation, two-phase partial/final:

    1. each process aggregates its LOCAL rows with the full local engine
       (device sort + segmented associative scan over this host's chips),
       yielding one partial row per locally-seen group;
    2. the small partial tables are all-gathered across processes and a
       replicated final aggregate merges same-key partials — every
       process returns the identical global result.

    The shuffle the reference leans on (``DebugRowOps.scala:547-592``)
    moves raw rows between executors; here only per-group partials cross
    hosts. Keys may be numeric, binary, or multi-column mixes, same as
    the local engine.
    """
    from ..engine import aggregate as local_aggregate
    from ..engine.ops import _as_graph
    from ..frame import GroupedFrame

    local_df = grouped_data.frame
    keys = grouped_data.keys
    g = _as_graph(fetches, local_df, cell_inputs=False)
    partials = local_aggregate(g, grouped_data)._force()
    global_partials = _allgather_partials(partials).analyze()
    g2 = g.with_inputs({f"{f}_input": f for f in g.fetch_names})
    return local_aggregate(g2, GroupedFrame(global_partials, keys))
