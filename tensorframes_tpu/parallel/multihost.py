"""Multi-host (multi-process) execution: the DCN story.

The reference's cross-host communication *is* Spark: Py4J control plane,
torrent broadcast of the graph, shuffle for groupBy, and an
executors-to-driver funnel for reduces
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala:376,524,576``).
The TPU-native replacement has no driver funnel: every host runs the SAME
program, ``jax.distributed.initialize`` wires the processes into one
runtime, meshes span every host's devices, and XLA routes collectives over
ICI within a pod and DCN across pods/hosts (SURVEY §2.5). Each host feeds
only its addressable shard (per-host input pipelines — the part the
reference never solved, SURVEY §7 hard-part 6).

On CPU this is exercised for real: multiple processes with virtual
devices, cross-process collectives over Gloo — the same code path
``jax.distributed`` uses across TPU hosts over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = [
    "initialize",
    "is_multihost",
    "process_count",
    "process_index",
    "global_batch",
    "local_rows",
    "sync_global",
    "map_blocks",
    "reduce_blocks",
]


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_count: Optional[int] = None,
) -> None:
    """Join this process into a multi-host runtime.

    Thin wrapper over ``jax.distributed.initialize`` that can also size the
    CPU backend at ``local_device_count`` virtual devices per process —
    the testing topology (N processes x M virtual devices) that stands in
    for N hosts x M chips. Must run before any jax computation initializes
    the backends."""
    import jax

    if local_device_count is not None:
        try:
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except Exception as e:  # backends already initialized, or old jax
            from ..utils import get_logger

            get_logger("multihost").warning(
                "could not size the CPU backend at %d devices (%s); "
                "device count will be whatever the backend reports",
                local_device_count,
                e,
            )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def global_batch(local: np.ndarray, mesh, spec=None):
    """Assemble a globally-sharded array from each process's local rows.

    ``local`` is THIS process's slice along the leading (row) axis; every
    process contributes its own. ``spec`` defaults to rows-over-``dp``,
    trailing dims replicated. The result is addressable-shard-backed: no
    host ever materializes the global array (the reference, by contrast,
    funnels global state through the driver)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import DATA_AXIS

    if spec is None:
        spec = P(DATA_AXIS, *([None] * (np.ndim(local) - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def local_rows(n_rows: int) -> slice:
    """The contiguous row range this process should load, under the even
    row split ``global_batch`` expects: process i of p takes rows
    ``[i*n/p, (i+1)*n/p)``."""
    import jax

    p, i = jax.process_count(), jax.process_index()
    if n_rows % p != 0:
        raise ValueError(
            f"{n_rows} rows do not split evenly over {p} processes; pad or "
            f"trim the dataset so every host feeds the same shard size"
        )
    per = n_rows // p
    return slice(i * per, (i + 1) * per)


def sync_global(x):
    """Fetch a (replicated or sharded) global array to every host, via an
    all-gather across processes when needed. For small results only —
    this is the one deliberate host materialization point."""
    import jax

    arr = x
    if hasattr(arr, "is_fully_addressable") and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(arr)


# ---------------------------------------------------------------------------
# dataframe ops over a multi-process mesh: each host feeds its local rows
# ---------------------------------------------------------------------------


def _global_block_feed(local_df, binding, mesh):
    """Assemble the globally-sharded feed from this process's local frame:
    every process contributes its rows via ``global_batch`` — the analog of
    the reference's per-executor partitions, except no driver ever sees the
    whole table."""
    feed = {}
    for ph, col in binding.items():
        feed[ph] = global_batch(local_df.column_block(col), mesh)
    return feed


def map_blocks(fetches, local_df, mesh, feed_dict=None):
    """Multi-host ``map_blocks``: ``local_df`` holds THIS process's rows;
    all processes call with the same program and their own shard. Returns
    a local frame of this process's result rows (fetch columns + inputs).
    Eager (the cross-process collective assembly happens now), unlike the
    single-process lazy engine — multi-host programs are SPMD, so laziness
    would only defer a rendezvous every process must reach anyway."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.ops import _as_graph, _ensure_precision
    from ..engine.validation import (
        InvalidDimensionError,
        check_output_collisions,
        validate_map_inputs,
    )
    from ..frame import TensorFrame
    from ..schema import Unknown
    from .distributed import _cached_program
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=False, feed_dict=feed_dict)
    binding = validate_map_inputs(g, local_df.schema, block=True)
    _ensure_precision(g, local_df.schema)
    # same pre-flight contract as the single-process engine: no scalar
    # outputs, no collisions with existing columns
    out_specs = g.analyze(
        {
            ph: local_df.schema[col].block_shape.with_lead(Unknown)
            for ph, col in binding.items()
        }
    )
    for name, spec in out_specs.items():
        if spec.shape.num_dims == 0:
            raise InvalidDimensionError(
                f"map_blocks output {name!r} is a scalar; map outputs must "
                f"keep the leading row dimension (use reduce_blocks)"
            )
    check_output_collisions(out_specs, local_df.schema)
    feed = _global_block_feed(local_df, binding, mesh)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    prog = _cached_program(
        g,
        (mesh, "mh_map"),
        lambda: jax.jit(
            g.fn, out_shardings={f: sharding for f in g.fetch_names}
        ),
    )
    res = prog(feed)
    cols = {}
    for name in g.fetch_names:
        cols[name] = _local_rows_of(res[name])
    out = dict(cols)
    for c in local_df.schema:
        out[c.name] = local_df.column_data(c.name).host()
    return TensorFrame.from_columns(out)


def _local_rows_of(arr) -> np.ndarray:
    """This process's rows of a dp-sharded global array, in row order,
    deduplicated: on a multi-axis mesh the row shard is replicated over the
    other axes and ``addressable_shards`` yields every replica."""
    seen = set()
    parts = []
    for s in sorted(
        arr.addressable_shards, key=lambda s: s.index[0].start or 0
    ):
        key = (s.index[0].start, s.index[0].stop)
        if key in seen:
            continue
        seen.add(key)
        parts.append(np.asarray(s.data))
    return np.concatenate(parts)


def reduce_blocks(fetches, local_df, mesh):
    """Multi-host ``reduce_blocks``: block-reduce over the GLOBAL rows with
    each process feeding its shard; the result is replicated, so every
    process returns the same numpy value(s) — no driver funnel."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..engine.ops import (
        _as_graph,
        _ensure_precision,
        _unpack_reduce_result,
    )
    from ..engine.validation import validate_reduce_block_graph
    from .mesh import DATA_AXIS

    g = _as_graph(fetches, local_df, cell_inputs=False)
    binding = validate_reduce_block_graph(g, local_df.schema)
    _ensure_precision(g, local_df.schema)
    feed = {
        f"{f}_input": global_batch(local_df.column_block(col), mesh)
        for f, col in binding.items()
    }
    from .distributed import _cached_program

    rep = NamedSharding(mesh, P())
    prog = _cached_program(
        g,
        (mesh, "mh_reduce"),
        lambda: jax.jit(
            g.fn, out_shardings={f: rep for f in g.fetch_names}
        ),
    )
    res = prog(feed)
    host = {f: sync_global(res[f]) for f in g.fetch_names}
    return _unpack_reduce_result(host, g.fetch_names)
