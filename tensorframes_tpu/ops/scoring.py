"""Fused dense-scoring kernel: ``argmax(x @ w + b)`` at HBM line rate.

The headline scoring workload (reference BASELINE config 3: MNIST
logistic regression over 1M rows) is HBM-bound — 3.1 GB of features read
against 15.7 GFLOP — but its matmul is MXU-PADDED: ``[N, 784] x [784,
10]`` pads the 10 output classes to the MXU's 128 lanes, costing ~1 ms
per pass regardless of dtype. Measured r05 headline passes fit
``t = bytes / 809 GB/s + 1.0 ms`` almost exactly: XLA's emitted matmul
SERIALIZES the feature streaming against that padded MXU work, and the
fixed millisecond is why the bf16 mode (half the bytes) sat at 62-69%
bandwidth utilization while f32 reached 78% (VERDICT r4 weakness 4).

This kernel runs the scoring as a Pallas grid over row tiles with the
weights resident in VMEM: the pipeline ships tile ``i+1`` from HBM while
the MXU scores tile ``i``, hiding the padded matmul entirely behind the
streaming. The argmax epilogue runs on tile-local scores (classes padded
with a ``-inf`` bias so pad lanes never win).

Used by :class:`~tensorframes_tpu.models.mlp.MLPClassifier` for
single-layer models; deeper MLPs keep the XLA path (their matmuls are
large enough to pipeline well).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["dense_argmax"]

_NEG_BIAS = -1e30  # pad-class bias: never the argmax


def _kernel(x_ref, w_ref, b_ref, o_ref):
    s = jax.lax.dot_general(
        x_ref[...],
        w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[...]
    o_ref[...] = jnp.argmax(s, axis=-1, keepdims=True).astype(jnp.int32)


def _pick_block_rows(n: int, cap: int = 2048) -> Optional[int]:
    """Largest divisor of ``n`` that is <= cap and a multiple of 8 (the
    sublane count): whole tiles, no remainder handling in the kernel."""
    best = None
    for b in range(8, min(n, cap) + 1, 8):
        if n % b == 0:
            best = b
    return best


def dense_argmax(
    x,
    w,
    b=None,
    interpret: Optional[bool] = None,
):
    """``argmax(x @ w + b, axis=-1)`` as an int32 vector, streamed at HBM
    rate. ``x``: [N, K] (any float dtype — bf16 streams half the bytes
    and scores identically thanks to f32 accumulation); ``w``: [K, C];
    ``b``: [C] or None. Falls back to the plain XLA expression when no
    whole-tile row split exists (tiny or prime N), so shapes/dtypes are
    identical either way."""
    n, k = x.shape
    c = w.shape[1]
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    # VMEM budget: the x tile is double-buffered and the padded weights
    # stay resident; cap the row tile so ~2*bn*k*itemsize + k*cp stays
    # well under the 16 MB scoped-VMEM limit (wide single-layer models
    # would otherwise fail TPU compile — invisible in interpret mode)
    itemsize = np.dtype(x.dtype).itemsize
    cp_est = max(128, -(-c // 128) * 128)
    w_bytes = k * cp_est * itemsize
    row_cap = int((6 << 20) // max(1, k * itemsize))
    bn = (
        _pick_block_rows(n, cap=min(2048, max(8, row_cap - row_cap % 8)))
        if w_bytes <= (4 << 20)
        else None
    )
    if bn is None or n < 64:
        s = jnp.dot(x, w, preferred_element_type=jnp.float32)
        if b is not None:
            s = s + b
        return jnp.argmax(s, axis=-1).astype(jnp.int32)

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cp = max(128, -(-c // 128) * 128)
    wp = jnp.zeros((k, cp), jnp.float32).at[:, :c].set(
        w.astype(jnp.float32)
    )
    bp = jnp.full((1, cp), _NEG_BIAS, jnp.float32)
    bias = b.astype(jnp.float32) if b is not None else jnp.zeros(
        c, jnp.float32
    )
    bp = bp.at[0, :c].set(bias)
    # the weights ride the MXU in the INPUT's dtype (bf16 features score
    # in the native bf16 pass, like the XLA path)
    wp = wp.astype(x.dtype)

    out = pl.pallas_call(
        _kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, cp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cp), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.int32),
        compiler_params=(
            None
            if interpret
            else pltpu.CompilerParams(dimension_semantics=("arbitrary",))
        ),
        interpret=interpret,
    )(x, wp, bp)
    return out[:, 0]
