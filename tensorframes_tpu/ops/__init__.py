"""Hot-op kernels: Pallas flash attention + ring/Ulysses sequence
parallelism + fused dense scoring."""

from .attention import flash_attention, attention_reference, online_block_update
from .ring import ring_attention, ring_attention_sharded
from .scoring import dense_argmax
from .ulysses import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "dense_argmax",
    "flash_attention",
    "attention_reference",
    "online_block_update",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
