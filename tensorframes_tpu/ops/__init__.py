"""Hot-op kernels: Pallas flash attention + ring/Ulysses sequence
parallelism."""

from .attention import (
    attention_reference,
    flash_attention,
    online_block_update,
    paged_attention,
    paged_page_size_hint,
    ragged_paged_attention,
)
from .ring import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention, ulysses_attention_sharded

__all__ = [
    "flash_attention",
    "attention_reference",
    "paged_attention",
    "ragged_paged_attention",
    "paged_page_size_hint",
    "online_block_update",
    "ring_attention",
    "ring_attention_sharded",
    "ulysses_attention",
    "ulysses_attention_sharded",
]
