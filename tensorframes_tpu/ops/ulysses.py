"""Ulysses (all-to-all) sequence parallelism.

The second of the two standard long-context shardings (the other is the
ring, :mod:`tensorframes_tpu.ops.ring`; the reference has neither — its
only scalable axis is rows, SURVEY §5 "long context: absent"):

- **ring**: K/V chunks rotate around the ``sp`` axis via ``ppermute``
  (neighbor hops on ICI); communication overlaps compute, memory per chip
  stays O(L/n), and any head count works.
- **ulysses**: two ``all_to_all`` exchanges re-shard the activations from
  sequence-sharded ``[B, H, L/n, D]`` to head-sharded ``[B, H/n, L, D]``,
  run ordinary (flash) attention on the FULL sequence for a subset of
  heads, and shard back. Communication is two collective transposes total
  (vs n ppermute hops), and the attention itself is the plain kernel —
  but it needs ``H % n == 0`` and O(L) sequence memory per chip.

Use ulysses when heads are plentiful and the sequence fits per-chip after
the exchange; use the ring when the sequence itself must stay sharded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import flash_attention
from .seq_common import (
    SEQ_AXIS,
    axis_size as _axis_size,
    check_divisible,
    resolve_sp_mesh,
)

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    interpret=None,
):
    """Per-shard body: call inside ``shard_map`` with q/k/v sequence chunks
    ``[B, H, L/n, D]`` sharded over ``axis_name``; returns the local output
    chunk. Heads must divide by the axis size."""
    n = _axis_size(axis_name)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the {axis_name!r} "
            f"axis size ({n}); use ring attention otherwise"
        )

    def seq_to_heads(t):
        # [B, H, L/n, D] -> [B, H/n, L, D]: split the head axis n ways,
        # exchange, concatenate the received pieces along the sequence
        return jax.lax.all_to_all(
            t, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(t):
        return jax.lax.all_to_all(
            t, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # full sequence per chip for H/n heads: plain flash attention, and the
    # causal mask needs no offset bookkeeping (unlike the ring)
    oh = flash_attention(qh, kh, vh, causal=causal, interpret=interpret)
    return heads_to_seq(oh)


@functools.lru_cache(maxsize=64)
def _ulysses_program(mesh, causal: bool, axis_name: str, batch_axis=None):
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map as _shard_map

    # interpret must follow the MESH's devices, not the default backend:
    # the multichip dryrun runs this over virtual CPU devices on a box
    # whose default platform is a TPU
    interpret = mesh.devices.flat[0].platform != "tpu"
    spec = P(batch_axis, None, axis_name, None)
    return jax.jit(
        _shard_map(
            functools.partial(
                ulysses_attention_sharded,
                causal=causal,
                axis_name=axis_name,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # the pallas flash kernel does not annotate varying-mesh-axes
            # on its out_shape; every input/output here is uniformly
            # sharded by construction, so the check adds nothing
            check_vma=False,
        )
    )


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh=None,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    batch_axis=None,
):
    """Full-array entry point: shards ``[B, H, L, D]`` over the mesh's
    ``axis_name`` axis, re-shards to heads with one collective transpose,
    attends, and shards back. ``L`` and ``H`` must divide by the axis
    size. ``batch_axis`` additionally shards the batch dim over another
    mesh axis (dp x sp composition in one program, like the ring — the
    all_to_all exchanges ride the sp axis only, so the body is
    batch-agnostic)."""
    mesh = resolve_sp_mesh(mesh, axis_name)
    n = mesh.shape[axis_name]
    check_divisible(
        n, axis_name, q_seq_len=q.shape[2], k_seq_len=k.shape[2]
    )
    if q.shape[1] % n:
        raise ValueError(
            f"head count {q.shape[1]} must divide by the {axis_name} axis "
            f"size {n}; use ring_attention for head counts < the axis size"
        )
    if batch_axis is not None:
        if batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} is not a mesh axis; mesh has "
                f"{tuple(mesh.shape)}"
            )
        check_divisible(
            mesh.shape[batch_axis], batch_axis, batch=q.shape[0]
        )
    return _ulysses_program(mesh, causal, axis_name, batch_axis)(q, k, v)
