"""Shared plumbing for the sequence-parallel entry points (ring, ulysses)."""

from __future__ import annotations

__all__ = [
    "SEQ_AXIS",
    "axis_size",
    "resolve_sp_mesh",
    "check_divisible",
    "pcast_varying",
]

#: canonical sequence-parallel axis name
SEQ_AXIS = "sp"


def resolve_sp_mesh(mesh, axis_name: str):
    """Default to a 1-D mesh over all devices when none is given."""
    if mesh is None:
        import jax

        from ..parallel.mesh import make_mesh

        mesh = make_mesh({axis_name: len(jax.devices())})
    return mesh


def axis_size(axis_name: str) -> int:
    """Named-axis size from inside a shard_map body — the ops-side door
    to ``parallel.compat.axis_size`` (lazy import: ops loads before the
    parallel package in some import orders), shared by the ring and
    ulysses bodies so a jax API drift is fixed in one place."""
    from ..parallel.compat import axis_size as _axis_size

    return _axis_size(axis_name)


def pcast_varying(t, axis_name: str):
    """Mark a shard_map-internal constant as varying over ``axis_name``.

    Constants born inside ``shard_map`` are device-invariant; a loop carry
    that later passes through ``ppermute`` becomes varying, so the initial
    carry must be marked too (jax >= 0.8 VMA checking). Older jax versions
    lack ``pcast`` — there the check does not exist either, so pass-through
    is correct."""
    import jax

    try:
        return jax.lax.pcast(t, (axis_name,), to="varying")
    except (AttributeError, TypeError):
        return t


def check_divisible(n: int, axis_name: str, **named_lengths: int) -> None:
    """Require every named length to divide by the axis size; the error
    names the offending operand (not just whichever was checked first)."""
    bad = {name: l for name, l in named_lengths.items() if l % n}
    if bad:
        detail = ", ".join(f"{name}={l}" for name, l in bad.items())
        raise ValueError(
            f"{detail} must divide by the {axis_name!r} axis size {n}"
        )
