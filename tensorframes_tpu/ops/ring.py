"""Ring attention: sequence/context parallelism over the device mesh.

Long sequences are sharded along the sequence axis, one chunk per chip on
the ``sp`` mesh axis. Each chip keeps its query chunk resident and the
key/value chunks rotate around the ring with ``lax.ppermute`` (ICI
neighbor exchange), one hop per step. Every hop streams the visiting
chunk through the flash kernel in carry mode
(:func:`tensorframes_tpu.ops.attention.flash_carry`): the online-softmax
state (m, l, acc) enters the kernel, the chunk passes through VMEM one
[block_k, d] tile at a time, and the updated state comes back. Per-chip
memory is O(chunk + block) — no [L/n, L/n] score matrix ever exists, so
the path scales to the chunk sizes ring attention is for (32k+ per chip).

Causality is resolved per hop at trace level: a visiting chunk is either
entirely in the past (full unmasked kernel), entirely in the future
(skipped — no FLOPs, which is where causal ring wins its 2x), or the
diagonal (causal kernel at offset 0). ``lax.switch`` picks the regime
from the ring-rotated source index, so the math matches a dense causal
mask exactly.

Differentiation is a custom VJP implementing the ring backward: the
forward saves only the output and the per-row log-sum-exp; the backward
re-rotates k/v around the ring, accumulating dq locally while dk/dv ride
the ring with their chunks (n hops return them to their home chip), each
hop running the same two FlashAttention-2 backward kernels the
single-chip VJP uses (:func:`tensorframes_tpu.ops.attention.flash_bwd_pair`).

This is the blockwise/ring formulation (cf. Ring Attention; see PAPERS.md)
— the reference has nothing comparable (no attention, no sequence axis,
SURVEY §5); its closest mechanism, the rows-axis pairwise reduce, shaped
the same "local partials + rotating merge" design used here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .attention import (
    _NEG_BIG,
    _finalize,
    _fit_tile,
    _lse_sentinel,
    flash_bwd_pair,
    flash_carry,
)
from .seq_common import (
    SEQ_AXIS,
    axis_size as _axis_size,
    check_divisible,
    pcast_varying,
    resolve_sp_mesh,
)

__all__ = ["ring_attention", "ring_attention_sharded"]


def _hop_regime(step, my):
    """0 = diagonal (causal kernel), 1 = fully visible (unmasked kernel),
    2 = entirely future (skip). With equal chunk lengths, the chunk
    visiting at ``step`` has source index ``(my - step) % n``; it is fully
    in the past iff ``step <= my`` and the diagonal iff ``step == 0``."""
    return jnp.where(step == 0, 0, jnp.where(step <= my, 1, 2))


def _ring_setup(q, k, axis_name, batch_axis, block_q, block_k):
    """Shared fwd/bwd prologue: ring geometry, fitted tiles, rotation
    permutation, and the variance-marking helper — one source of truth so
    the two loops cannot drift apart."""
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lc = k.shape[2]
    bq = _fit_tile(block_q, lq)
    bk = _fit_tile(block_k, lc)
    if bq is None or bk is None:
        raise ValueError(
            f"per-chip chunk lengths ({lq}, {lc}) admit no lane-aligned "
            f"tile; pad the sequence to a multiple of 128 per chip"
        )
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _vary(x):
        # the carry inherits q's variance: sp always, plus the batch axis
        # when the batch dim is sharded too (dp x sp composition)
        x = pcast_varying(x, axis_name)
        if batch_axis is not None:
            x = pcast_varying(x, batch_axis)
        return x

    return n, my, (b, h, lq, lc, d), bq, bk, perm, _vary


def _fwd_hop_branches(q, bq, bk, interpret):
    """The three forward hop bodies for ``lax.switch`` (diagonal, fully
    visible, skip); each takes and returns the (m, l, acc) carry with the
    visiting chunk closed in via the operand tuple."""

    def fold(causal):
        def run(args):
            m, l, acc, kc, vc = args
            return flash_carry(
                q, kc, vc, m, l, acc,
                causal=causal, offset=0, block_q=bq, block_k=bk,
                interpret=interpret,
            )

        return run

    def skip(args):
        m, l, acc, _, _ = args
        return m, l, acc

    return (fold(True), fold(False), skip)


def _ring_fwd_loop(
    q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
):
    """Run the forward ring. Returns the finalized local output chunk
    ``[B, H, Lq, D]`` and the per-row log-sum-exp ``[BH, Lq, 1]`` the
    backward needs."""
    n, my, (b, h, lq, lc, d), bq, bk, perm, _vary = _ring_setup(
        q, k, axis_name, batch_axis, block_q, block_k
    )
    bh = b * h
    qf = q.reshape(bh, lq, d)
    kf = k.reshape(bh, lc, d)
    vf = v.reshape(bh, lc, d)
    m0 = _vary(jnp.full((bh, lq, 1), _NEG_BIG, dtype=jnp.float32))
    l0 = _vary(jnp.zeros((bh, lq, 1), dtype=jnp.float32))
    acc0 = _vary(jnp.zeros((bh, lq, d), dtype=jnp.float32))
    branches = _fwd_hop_branches(qf, bq, bk, interpret)

    def body(step, carry):
        m, l, acc, kc, vc = carry
        if causal:
            m, l, acc = jax.lax.switch(
                _hop_regime(step, my), branches, (m, l, acc, kc, vc)
            )
        else:
            m, l, acc = branches[1]((m, l, acc, kc, vc))
        # rotate k/v to the next chip (ICI neighbor hop)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, kf, vf))
    o = _finalize(l, acc).astype(q.dtype).reshape(b, h, lq, d)
    # same sentinel convention as the flash kernel: rows that saw no valid
    # key carry _POS_BIG so the backward recomputes p == 0 for them
    return o, _lse_sentinel(m, l)


def _bwd_hop_branches(qf, dof, lse, delta, bq, bk, interpret, d):
    """The three backward hop bodies: each returns this hop's
    (dq, dk, dv) contributions in f32 (zeros for the skipped regime)."""
    from .attention import _best_blocks_bwd

    f32 = (jnp.float32, jnp.float32, jnp.float32)

    # the dkv kernel's own measured-best tiles (the transposed-score
    # kernel prefers narrow-q/wide-k — _BEST_BLOCKS_BWD) when they fit
    # the hop spans; the hop's fitted tiles otherwise
    def _kv_tiles(lc):
        tuned = _best_blocks_bwd(qf.dtype, d, qf.shape[1], lc)
        return (tuned[2], tuned[3]) if tuned is not None else (bq, bk)

    def pair(causal):
        def run(args):
            kc, vc = args
            dkv_q, dkv_k = _kv_tiles(kc.shape[1])
            return flash_bwd_pair(
                qf, kc, vc, dof, lse, delta,
                causal=causal, offset=0, block_q=bq, block_k=bk,
                dkv_block_q=dkv_q, dkv_block_k=dkv_k,
                interpret=interpret, out_dtypes=f32,
            )

        return run

    def skip(args):
        kc, _ = args
        bh, lq, _ = qf.shape
        lc = kc.shape[1]
        z = jnp.zeros((bh, lq, d), jnp.float32)
        zk = jnp.zeros((bh, lc, d), jnp.float32)
        return z, zk, zk

    return (pair(True), pair(False), skip)


def _ring_bwd_loop(
    q, k, v, o, lse, do, causal, axis_name, batch_axis,
    block_q, block_k, interpret,
):
    """The ring backward: dq accumulates on the home chip; dk/dv for each
    chunk accumulate in a carry that rotates WITH the chunk, so after n
    hops every chunk's gradient has visited every chip that attended to it
    and is back home."""
    n, my, (b, h, lq, lc, d), bq, bk, perm, _vary = _ring_setup(
        q, k, axis_name, batch_axis, block_q, block_k
    )
    bh = b * h
    qf = q.reshape(bh, lq, d)
    kf = k.reshape(bh, lc, d)
    vf = v.reshape(bh, lc, d)
    dof = do.reshape(bh, lq, d)
    delta = (
        dof.astype(jnp.float32) * o.reshape(bh, lq, d).astype(jnp.float32)
    ).sum(axis=-1, keepdims=True)
    dq0 = _vary(jnp.zeros((bh, lq, d), jnp.float32))
    dk0 = _vary(jnp.zeros((bh, lc, d), jnp.float32))
    dv0 = _vary(jnp.zeros((bh, lc, d), jnp.float32))
    branches = _bwd_hop_branches(qf, dof, lse, delta, bq, bk, interpret, d)

    def body(step, carry):
        dq, kc, vc, dkc, dvc = carry
        if causal:
            dq_h, dk_h, dv_h = jax.lax.switch(
                _hop_regime(step, my), branches, (kc, vc)
            )
        else:
            dq_h, dk_h, dv_h = branches[1]((kc, vc))
        dq = dq + dq_h
        dkc = dkc + dk_h
        dvc = dvc + dv_h
        # the visiting chunk AND its gradient hop together
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        return dq, kc, vc, dkc, dvc

    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (dq0, kf, vf, dk0, dv0)
    )
    return (
        dq.astype(q.dtype).reshape(b, h, lq, d),
        dk.astype(k.dtype).reshape(b, h, lc, d),
        dv.astype(v.dtype).reshape(b, h, lc, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_core(
    q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
):
    o, _ = _ring_fwd_loop(
        q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
    )
    return o


def _ring_core_fwd(
    q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
):
    o, lse = _ring_fwd_loop(
        q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
    )
    return o, (q, k, v, o, lse)


def _ring_core_bwd(
    causal, axis_name, batch_axis, block_q, block_k, interpret, res, do
):
    q, k, v, o, lse = res
    return _ring_bwd_loop(
        q, k, v, o, lse, do, causal, axis_name, batch_axis,
        block_q, block_k, interpret,
    )


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    batch_axis=None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
):
    """The per-shard body: call inside ``shard_map`` with q/k/v sequence
    chunks ``[B, H, L/n, D]`` sharded over ``axis_name``. Returns the local
    output chunk. Differentiable (ring-backward custom VJP).

    Causal mode requires equal q/k chunk lengths (the hop regimes assume
    aligned diagonals). ``interpret=None`` follows the DEFAULT backend's
    platform — when your shard_map targets a non-default backend (e.g. a
    virtual CPU mesh on a TPU box), pass ``interpret`` explicitly;
    :func:`ring_attention` derives it from the mesh for you."""
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError(
            f"causal ring attention requires equal q/k chunk lengths "
            f"(got {q.shape[2]} and {k.shape[2]})"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _ring_core(
        q, k, v, causal, axis_name, batch_axis, block_q, block_k, interpret
    )


@functools.lru_cache(maxsize=64)
def _ring_program(
    mesh, causal: bool, axis_name: str, batch_axis, block_q, block_k,
    interpret,
):
    """One jitted shard_map program per (mesh, causal, axis, tiles) —
    cached so repeated calls (every transformer layer, every step) hit the
    jit cache instead of retracing."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map as _shard_map

    spec = P(batch_axis, None, axis_name, None)
    return jax.jit(
        _shard_map(
            functools.partial(
                ring_attention_sharded,
                causal=causal,
                axis_name=axis_name,
                batch_axis=batch_axis,
                block_q=block_q,
                block_k=block_k,
                interpret=interpret,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # pallas_call results carry no VMA annotation, so the checker
            # cannot type the carry kernel's outputs (same setting as
            # ulysses/moe/pipeline); collective correctness is covered by
            # the oracle tests instead
            check_vma=False,
        )
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh=None,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    batch_axis=None,
    block_q: int = 1024,
    block_k: int = 1024,
):
    """Full-array entry point: shards ``[B, H, L, D]`` inputs over the
    mesh's ``axis_name`` axis, runs the ring, and returns the assembled
    ``[B, H, L, D]`` output. ``L`` must divide by the axis size.
    ``batch_axis`` additionally shards the batch dim over another mesh
    axis (dp x sp composition in one program; the ring body is batch-
    agnostic, so only the specs change).

    Per-chip chunk lengths must admit a lane-aligned kernel tile (be a
    multiple of 128, or short enough to be a single tile) — unlike the
    pre-blockwise implementation, which accepted any length but built the
    full [L/n, L/n] score matrix per hop and could not reach long
    contexts at all. Pad the sequence when this errors."""
    mesh = resolve_sp_mesh(mesh, axis_name)
    check_divisible(
        mesh.shape[axis_name], axis_name,
        q_seq_len=q.shape[2], k_seq_len=k.shape[2],
    )
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError(
            f"causal ring attention requires equal q/k sequence lengths "
            f"(got {q.shape[2]} and {k.shape[2]}); use flash_attention "
            f"for cross-length causal decoding"
        )
    if batch_axis is not None:
        if batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} is not a mesh axis; mesh has "
                f"{tuple(mesh.shape)}"
            )
        check_divisible(
            mesh.shape[batch_axis], batch_axis, batch=q.shape[0]
        )
    # interpret must follow the MESH's devices, not the default backend:
    # the multichip dryrun runs this over virtual CPU devices on a box
    # whose default platform is a TPU
    interpret = mesh.devices.flat[0].platform != "tpu"
    return _ring_program(
        mesh, causal, axis_name, batch_axis, block_q, block_k, interpret
    )(q, k, v)
