"""Ring attention: sequence/context parallelism over the device mesh.

Long sequences are sharded along the sequence axis, one chunk per chip on
the ``sp`` mesh axis. Each chip keeps its query chunk resident and the
key/value chunks rotate around the ring with ``lax.ppermute`` (ICI
neighbor exchange), one hop per step; the partial attention of the local
queries against the visiting k/v chunk folds into the same online-softmax
carry the single-chip flash kernel uses
(:func:`tensorframes_tpu.ops.attention.online_block_update`). After
``num_chips`` steps every query has attended every key, with communication
overlapped against the block computation by XLA — no chip ever holds more
than its own chunk plus one visiting chunk.

This is the blockwise/ring formulation (cf. Ring Attention; see PAPERS.md)
— the reference has nothing comparable (no attention, no sequence axis,
SURVEY §5); its closest mechanism, the rows-axis pairwise reduce, shaped
the same "local partials + rotating merge" design used here.

Causality is handled at chunk granularity with global position offsets:
chunk ``c`` of keys is masked against local queries using the ring-rotated
source index, so the math matches a dense causal mask exactly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .attention import _NEG_BIG, _finalize, online_block_update
from .seq_common import (
    SEQ_AXIS,
    check_divisible,
    pcast_varying,
    resolve_sp_mesh,
)

__all__ = ["ring_attention", "ring_attention_sharded"]


def _local_ring_step(q, kc, vc, m, l, acc, q_off, k_off, causal, scale):
    """Fold one visiting k/v chunk into the carry. Shapes: q [B,H,Lq,D],
    kc/vc [B,H,Lc,D], carry m/l [B,H,Lq,1], acc [B,H,Lq,D]."""
    lq = q.shape[2]
    lc = kc.shape[2]
    mask = None
    if causal:
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (lq, lc), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (lq, lc), 1)
        mask = q_pos >= k_pos  # shared 2-D mask for every batch/head

    def per_head(qh, kh, vh, mh, lh, acch):
        return online_block_update(qh, kh, vh, mh, lh, acch, scale, mask)

    # vmap over batch and heads; the inner update is 2-D MXU-friendly
    f = jax.vmap(jax.vmap(per_head))
    return f(q, kc, vc, m, l, acc)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    batch_axis=None,
):
    """The per-shard body: call inside ``shard_map`` with q/k/v sequence
    chunks ``[B, H, L/n, D]`` sharded over ``axis_name``. Returns the local
    output chunk."""
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lc = k.shape[2]
    scale = 1.0 / float(np.sqrt(d))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def _vary(x):
        # the carry inherits q's variance: sp always, plus the batch axis
        # when the batch dim is sharded too (dp x sp composition)
        x = pcast_varying(x, axis_name)
        if batch_axis is not None:
            x = pcast_varying(x, batch_axis)
        return x

    m0 = _vary(jnp.full((b, h, lq, 1), _NEG_BIG, dtype=jnp.float32))
    l0 = _vary(jnp.zeros((b, h, lq, 1), dtype=jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, lq, d), dtype=jnp.float32))
    q_off = my * lq

    def body(step, carry):
        m, l, acc, kc, vc = carry
        src = (my - step) % n  # which global chunk is visiting
        k_off = src * lc
        m, l, acc = _local_ring_step(
            q, kc, vc, m, l, acc, q_off, k_off, causal, scale
        )
        # rotate k/v to the next chip (ICI neighbor hop)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))
    return _finalize(l, acc).astype(q.dtype)


@functools.lru_cache(maxsize=64)
def _ring_program(mesh, causal: bool, axis_name: str, batch_axis=None):
    """One jitted shard_map program per (mesh, causal, axis) — cached so
    repeated calls (every transformer layer, every step) hit the jit cache
    instead of retracing."""
    from jax.sharding import PartitionSpec as P

    spec = P(batch_axis, None, axis_name, None)
    return jax.jit(
        jax.shard_map(
            functools.partial(
                ring_attention_sharded,
                causal=causal,
                axis_name=axis_name,
                batch_axis=batch_axis,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh=None,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    batch_axis=None,
):
    """Full-array entry point: shards ``[B, H, L, D]`` inputs over the
    mesh's ``axis_name`` axis, runs the ring, and returns the assembled
    ``[B, H, L, D]`` output. ``L`` must divide by the axis size.
    ``batch_axis`` additionally shards the batch dim over another mesh
    axis (dp x sp composition in one program; the ring body is batch-
    agnostic, so only the specs change)."""
    mesh = resolve_sp_mesh(mesh, axis_name)
    check_divisible(
        mesh.shape[axis_name], axis_name,
        q_seq_len=q.shape[2], k_seq_len=k.shape[2],
    )
    if batch_axis is not None:
        if batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} is not a mesh axis; mesh has "
                f"{tuple(mesh.shape)}"
            )
        check_divisible(
            mesh.shape[batch_axis], batch_axis, batch=q.shape[0]
        )
    return _ring_program(mesh, causal, axis_name, batch_axis)(q, k, v)
