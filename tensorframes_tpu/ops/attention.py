"""Attention: online-softmax math + a Pallas TPU flash-attention kernel.

The reference has no attention or sequence axis at all (SURVEY §5: its only
scalable axis is rows). Long context is first-class here: this module is the
single-chip building block, and :mod:`tensorframes_tpu.ops.ring` scales the
sequence axis across chips with the same online-softmax update, so the two
compose into ring attention (blockwise parallel attention over a mesh).

Layout convention: ``[batch, heads, seq, head_dim]``.

The kernel tiles queries over the grid and streams key/value blocks through
an online-softmax accumulator (running max ``m``, normalizer ``l``, output
accumulator ``acc``) held in the loop carry — the standard FlashAttention
recurrence, shaped for the MXU: every contraction is a dense
``[block_q, d] x [d, block_k]`` / ``[block_q, block_k] x [block_k, d]``
matmul with ``preferred_element_type=f32``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger

logger = get_logger("ops.attention")

__all__ = [
    "flash_attention",
    "attention_reference",
    "paged_attention",
    "ragged_paged_attention",
    "paged_page_size_hint",
    "online_block_update",
    "flash_carry",
    "flash_bwd_pair",
]

_NEG_BIG = -0.7 * float(np.finfo(np.float32).max)  # mask value; exp() == 0
#: softmax runs in BASE 2 internally: s is pre-scaled by log2(e) (folded
#: into the existing qk scale multiply, so it costs nothing) and the
#: exponentials are bare exp2 — jnp.exp lowers to exp2(x * log2e) on TPU,
#: so this removes one full-tile VPU multiply per score element. The
#: probabilities 2^(s*log2e - m2) == e^(s - m) are IDENTICAL; only the
#: internal m/l/lse state lives in the scaled domain.
_LOG2E = float(np.log2(np.e))
#: log-sum-exp sentinel for rows that attend to nothing (causal with more
#: queries than keys): exp(s - _POS_BIG) underflows to exactly 0 for any
#: finite score, so the backward recomputation gives those rows p == 0
#: and zero gradient, matching the forward's zero output.
_POS_BIG = 0.7 * float(np.finfo(np.float32).max)


def _mxu_dtype(dt):
    """Matmul input dtype: low-precision inputs keep their native MXU mode
    (bf16/f16 run at the chip's high rate), everything else computes f32.
    Accumulation is always f32 via ``preferred_element_type``."""
    import jax.numpy as jnp

    return dt if dt in (jnp.bfloat16, jnp.float16) else jnp.float32


def online_block_update(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    m: jnp.ndarray,
    l: jnp.ndarray,
    acc: jnp.ndarray,
    scale: float,
    mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One online-softmax accumulation step over a key/value block.

    ``q``: [bq, d]; ``k``/``v``: [bk, d]; carry ``m``/``l``: [bq, 1],
    ``acc``: [bq, d] (all f32). ``mask``: optional [bq, bk] bool, True =
    attend. Fully-masked prefixes are handled: rows that have seen no valid
    key keep ``l == 0`` and contribute nothing. Shared verbatim by the
    Pallas kernel and the ring step so single-chip and distributed paths
    compute identically.

    The running max ``m`` lives in the BASE-2 domain (scores pre-scaled
    by log2(e); see ``_LOG2E``) — ``l``, ``acc``, and the finalized
    output are identical to the natural-base formulation.

    MXU precision follows the INPUT dtype: bf16/f16 q/k/v keep their
    matmuls in that dtype (the MXU's native high-rate mode; v5e runs bf16
    at ~4x its f32 rate) with ``preferred_element_type=f32`` so
    accumulation — and the whole softmax state — stays f32. f32 inputs
    compute exactly as before."""
    mxu_dt = _mxu_dtype(q.dtype)
    s = jax.lax.dot_general(
        q.astype(mxu_dt),
        k.astype(mxu_dt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (scale * _LOG2E)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_BIG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # rows still fully masked keep m == _NEG_BIG; exp2(s - m) would be
    # exp2(0) = 1 for masked entries, so re-mask p explicitly
    p = jnp.exp2(s - m_new)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    alpha = jnp.exp2(m - m_new)
    l_new = alpha * l + p.sum(axis=-1, keepdims=True)
    pv_dt = _mxu_dtype(v.dtype)
    acc_new = alpha * acc + jax.lax.dot_general(
        p.astype(pv_dt),
        v.astype(pv_dt),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _finalize(l: jnp.ndarray, acc: jnp.ndarray) -> jnp.ndarray:
    return acc / jnp.maximum(l, 1e-30)


def _lse_sentinel(m: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """Per-row log-sum-exp saved for the backward — in the BASE-2 domain
    (``m`` is the base-2 running max, so this is ``log2(sum exp)``;
    ``_bwd_tile_terms`` recomputes p with exp2 against it) — with the
    ``_POS_BIG`` sentinel on rows that attended to nothing (so the
    backward recomputes p == 0 and zero gradient there). The single
    source of this convention — the flash kernel's emit and the ring
    forward both use it; the backward's empty-row guarantee depends on
    them being bit-identical."""
    return jnp.where(
        l > 0.0, m + jnp.log2(jnp.maximum(l, 1e-30)), _POS_BIG
    )


#: measured-best (block_q, block_k) per (dtype kind, head_dim bucket,
#: min seq len) on v5e, chain-differential timed (benchmarks/
#: attention_bench.py methodology; sweep recorded in BENCH_ALL_r04.json).
#: 1024x1024 won every measured combo — bigger tiles (2048+) exceed VMEM
#: and fail to compile, 512-wide tiles lose 3-10% to per-tile overhead:
#:   bf16 D=128: L=8k 118.8 TF/s, L=16k 129.3, L=32k 127.5 (vs 100-117
#:   for 512x1024 / 1024x2048); bf16 D=64: 55-56 TF/s (half-width MXU
#:   contraction); f32 D=128: same ordering (f32 inputs ride the MXU's
#:   default bf16 pass, so tile behavior tracks bf16). Sweep predates
#:   the base-2 softmax (which lifted all rows ~4-6% uniformly; tile
#:   ordering unchanged).
#: The table keys exist so future chips/dtypes can diverge without an
#: API change; the lookup picks the largest-L entry <= L.
_BEST_BLOCKS = {
    # (is_lowp, d_bucket): [(min_L, (block_q, block_k)), ...] descending
    (True, 128): [(0, (1024, 1024))],
    (True, 64): [(0, (1024, 1024))],
    (False, 128): [(0, (1024, 1024))],
    (False, 64): [(0, (1024, 1024))],
}


def _static_best_blocks(dtype, d, l):
    """The measured-best table lookup alone (no tuner): the seed prior
    for the autotuner, and what ``paged_page_size_hint`` reads — the
    hint wants the table's block_k, which the tuning grid never varies,
    so consulting the tuner there would only burn a trial budget."""
    is_lowp = dtype in (jnp.bfloat16, jnp.float16)
    d_bucket = 128 if d > 64 else 64
    rows = _BEST_BLOCKS[(is_lowp, d_bucket)]
    static = rows[-1][1]
    for min_l, blocks in sorted(rows, reverse=True):
        if l >= min_l:
            static = blocks
            break
    return is_lowp, d_bucket, static


def _best_blocks(dtype, d, l):
    """Kernel tiles for this (dtype, head_dim, L): the autotuner's
    winner when one is installed (``tensorframes_tpu.tune``, surface
    ``flash.tiles``), else the measured-best static table
    ``_BEST_BLOCKS`` — which doubles as the tuner's seed prior (the
    default candidate every trial set measures first). Callers may
    always override explicitly."""
    is_lowp, d_bucket, static = _static_best_blocks(dtype, d, l)
    return _tuned_flash_blocks(is_lowp, d_bucket, l, static)


#: trial sequence cap: long-L signatures micro-benchmark at this length
#: (tile behavior is L-stable past a few k and interpret-mode trials on
#: CPU must stay sub-second); the WINNER still installs for the real L
#: bucket
_FLASH_TRIAL_L_CAP = 512


def _tuned_flash_blocks(is_lowp, d_bucket, l, static):
    """Consult the autotuner for the flash forward tiles.

    The candidate grid varies **block_q only**: the q tile sets grid
    parallelism and VMEM residency but leaves every query row's k-axis
    accumulation untouched, so each candidate is byte-identical to the
    static default — the tuning contract (docs/tuning.md). ``block_k``
    changes the online-softmax grouping (float associativity) and
    therefore stays at the table's measured value."""
    from .. import tune

    if tune.mode() == "off":
        return static
    sq, sk = static
    lb = 1 << max(7, (int(l) - 1).bit_length())  # pow2 bucket, >= 128
    sig = f"lowp={int(is_lowp)}|d={d_bucket}|L={lb}"
    default = {"block_q": int(sq), "block_k": int(sk)}
    lt = min(lb, _FLASH_TRIAL_L_CAP)
    # the default is measured CLAMPED to the trial length too, so any
    # candidate whose clamped trial equals the clamped default's would
    # run a byte-identical micro-benchmark — a coin-flip winner that
    # would then persist fleet-wide. Exclude by effective trial tile.
    eff_default = _fit_tile(min(int(sq), lt), lt)
    seen_eff = {eff_default}
    grid = []
    for bq in (256, 512, 1024, 2048):
        if bq > lt:
            # beyond trial fidelity: a candidate wider than the trial
            # sequence would measure identically to another clamped one
            # and the winner among them would be timing noise — only
            # offer what the micro-benchmark can genuinely distinguish
            continue
        fq = _fit_tile(bq, lb)
        if fq is None:
            continue
        eff = _fit_tile(min(int(fq), lt), lt)
        if eff in seen_eff:
            continue
        seen_eff.add(eff)
        cand = {"block_q": int(fq), "block_k": int(sk)}
        if cand != default:
            grid.append(cand)

    def feats(cand):
        # one forward tile does ~4*bq*bk*d MXU flops (qk^T + pv) and
        # touches the q/k/v/o tiles; tiles-per-sequence is the dispatch
        # count the overhead weight prices
        bq = min(cand["block_q"], lt)
        bk = min(cand["block_k"], lt)
        itemsize = 2 if is_lowp else 4
        tiles = max(1, lt // bq) * max(1, lt // bk)
        flops = 4.0 * bq * bk * d_bucket * tiles
        nbytes = (2 * bq + 2 * bk) * d_bucket * itemsize * tiles
        return flops, nbytes, tiles

    def trial(cand):
        rng = np.random.default_rng(0)
        dt = jnp.bfloat16 if is_lowp else jnp.float32
        q = jnp.asarray(
            rng.normal(size=(1, 1, lt, d_bucket)).astype(np.float32), dt
        )
        k = jnp.asarray(
            rng.normal(size=(1, 1, lt, d_bucket)).astype(np.float32), dt
        )
        v = jnp.asarray(
            rng.normal(size=(1, 1, lt, d_bucket)).astype(np.float32), dt
        )
        jax.block_until_ready(
            flash_attention(
                q, k, v,
                block_q=min(cand["block_q"], lt),
                block_k=min(cand["block_k"], lt),
            )
        )

    try:
        win = tune.lookup(
            "flash.tiles", sig, default, grid=grid, feats=feats,
            trial=trial,
        )
        bq, bk = int(win["block_q"]), int(win["block_k"])
        if bq >= 1 and bk >= 1:
            return (bq, bk)
    except Exception:
        logger.warning(
            "flash tile tuning lookup failed; using the static table",
            exc_info=True,
        )
    return static


def _check_tiles(block_q, lq, block_k, lk):
    """The public kernel entry points floor-divide the grid; a block that
    does not divide its sequence would silently drop the tail rows."""
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"block sizes ({block_q}, {block_k}) must divide the sequence "
            f"lengths ({lq}, {lk}); see _fit_tile / flash_attention for "
            f"automatic fitting"
        )


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, causal: bool = False
) -> jnp.ndarray:
    """Dense softmax attention oracle, [B, H, L, D] layout."""
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        qi = jnp.arange(lq)[:, None] + (lk - lq)
        ki = jnp.arange(lk)[None, :]
        valid = qi >= ki
        s = jnp.where(valid, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    if causal:
        # lq > lk leaves early rows with no visible key at all; the kernels
        # return zeros for such rows (l == 0 finalize), so the oracle must
        # too rather than softmax-averaging over the mask fill
        p = jnp.where(valid.any(axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def _check_paged_inputs(q, k_pages, v_pages, page_table, lengths):
    """Shared validation for the paged decode reads (gather and fused).

    The position mask is ``arange(T) < lengths`` and the gather indexes
    with ``page_table`` directly, so a wrong dtype does not fail — it
    silently miscomputes (a float ``lengths`` compares almost-equal, an
    int64 table under x64 re-traces to a different layout). Serving
    correctness rides on these being right, so reject loudly at trace
    time instead."""
    if np.ndim(q) != 4:
        raise ValueError(
            f"q must be [slots, n_kv, group, head_dim]; got shape "
            f"{np.shape(q)}"
        )
    slots, n_kv, _, hd = np.shape(q)
    for name, arr in (("k_pages", k_pages), ("v_pages", v_pages)):
        if np.ndim(arr) != 4:
            raise ValueError(
                f"{name} must be [pool_pages, page_size, n_kv, head_dim]; "
                f"got shape {np.shape(arr)}"
            )
    if np.shape(k_pages) != np.shape(v_pages):
        raise ValueError(
            f"k_pages and v_pages must share a shape; got "
            f"{np.shape(k_pages)} vs {np.shape(v_pages)}"
        )
    if np.shape(k_pages)[2] != n_kv or np.shape(k_pages)[3] != hd:
        raise ValueError(
            f"page pool holds (n_kv={np.shape(k_pages)[2]}, "
            f"head_dim={np.shape(k_pages)[3]}) but q asks for "
            f"(n_kv={n_kv}, head_dim={hd})"
        )
    if np.ndim(page_table) != 2 or np.shape(page_table)[0] != slots:
        raise ValueError(
            f"page_table must be [slots={slots}, max_pages]; got shape "
            f"{np.shape(page_table)}"
        )
    if np.shape(lengths) != (slots,):
        raise ValueError(
            f"lengths must be [slots={slots}]; got shape "
            f"{np.shape(lengths)}"
        )
    for name, arr in (("page_table", page_table), ("lengths", lengths)):
        dt = np.dtype(getattr(arr, "dtype", None) or np.asarray(arr).dtype)
        if dt != np.dtype(np.int32):
            raise ValueError(
                f"{name} must be int32 (got {dt}): the position mask and "
                f"the page gather consume it as-is, and a wrong dtype "
                f"miscomputes silently — cast with .astype(np.int32)"
            )


def paged_attention(q, k_pages, v_pages, page_table, lengths):
    """Single-token attention read over a PAGED KV cache — the decode-side
    gather for the serving engine (:mod:`tensorframes_tpu.serve`), where
    each sequence's keys/values live in fixed-size pages scattered through
    a static pool instead of one contiguous cache row.

    ``q`` [S, n_kv, group, hd] — one query token per slot, grouped-query
    layout (``group = n_heads / n_kv``; 1-sized slot batches and MHA both
    degenerate cleanly). ``k_pages``/``v_pages`` [pool_pages, page_size,
    n_kv, hd] — the shared page pool. ``page_table`` [S, max_pages] int32
    — each slot's ordered page list (entries past the sequence's live
    pages may point anywhere valid; the position mask excludes them).
    ``lengths`` [S] int32 — valid positions per slot, INCLUDING the token
    just written.

    Every shape is static: the gather reads ``max_pages * page_size``
    positions per slot and masks ``t >= lengths`` to ``_NEG_BIG`` before
    the softmax (masked lanes underflow to exactly 0), so one compiled
    program serves every mix of sequence lengths and slot turnover — the
    no-recompile property continuous batching depends on. The einsum
    family matches the dense decode-cache read in
    ``models.transformer.transformer_generate`` (same contraction axes,
    same mask value), so paged and dense decode agree to float
    associativity. Returns [S, n_kv, group, hd].

    This is the REFERENCE formulation: it materializes two
    ``[S, max_pages * page_size, n_kv, hd]`` gathered copies per call, so
    a ragged batch pays max-length bandwidth for every slot.
    :func:`ragged_paged_attention` is the fused kernel that walks the
    page table in-kernel instead; this gather stays as its oracle."""
    _check_paged_inputs(q, k_pages, v_pages, page_table, lengths)
    slots, n_kv, group, hd = q.shape
    mp = page_table.shape[1]
    ps = k_pages.shape[1]
    t = mp * ps
    # [S, max_pages, ps, n_kv, hd] -> [S, T, n_kv, hd]: pages in table
    # order ARE position order (page i holds positions i*ps..(i+1)*ps-1)
    kg = k_pages[page_table].reshape(slots, t, n_kv, hd)
    vg = v_pages[page_table].reshape(slots, t, n_kv, hd)
    scale = 1.0 / float(np.sqrt(hd))
    s = jnp.einsum("bkgd,btkd->bkgt", q, kg) * scale
    visible = jnp.arange(t)[None, :] < lengths[:, None]  # [S, T]
    s = jnp.where(visible[:, None, None, :], s, _NEG_BIG)
    return jnp.einsum("bkgt,btkd->bkgd", jax.nn.softmax(s, axis=-1), vg)


def paged_page_size_hint(dtype, head_dim: int) -> int:
    """The measured-best key-tile width for the fused paged read, from
    the flash sweep's ``_BEST_BLOCKS``: the ragged kernel's key tile IS
    one page (page indirection makes multi-page tiles non-contiguous in
    the pool, so the tile cannot grow past a page), which makes
    ``page_size`` the paged analog of ``block_k``. Pools sized with this
    page size run the kernel at the sweep's best key tile; smaller pages
    trade kernel efficiency for finer allocation granularity (the old
    serving default of 16 leaned all the way toward granularity — this
    hint is now the engine's default, clamped to ``max_seq_len``, with
    the autotuner's ``serve.page_size`` winner overriding it)."""
    return _static_best_blocks(dtype, head_dim, 0)[2][1]


def _ragged_paged_kernel(
    ptab_ref, lens_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, page_size, scale,
):
    """Grid = (slots, n_kv_heads, max_pages); the page axis is innermost
    and sequential, so the VMEM scratch carries the online-softmax state
    (``online_block_update`` — the same recurrence the flash kernel and
    the ring step fold with) across a slot's pages. One grid step streams
    ONE page's [page_size, hd] k/v tiles through the carry: the page
    table is a scalar-prefetch input, so the BlockSpec index maps chase
    the indirection and only this slot's OWN pages cross HBM->VMEM — no
    [slots, max_pages * page_size] gather is ever materialized.

    Pages at or past ``lengths[s]`` are skipped entirely (``pl.when``),
    so a 1-token sequence in a ragged batch does one page of work while
    its max-length neighbor does them all — compute scales with LIVE
    tokens. (Their table entries point at the trash page, so the
    prefetch pipeline still fetches a page-sized tile, but always the
    same hot one.) The boundary page masks ``position >= length`` to
    ``_NEG_BIG`` before the update, exactly like the gather oracle."""
    from jax.experimental import pallas as pl

    si = pl.program_id(0)
    pi = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(pi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = lens_ref[si]
    base = pi * page_size
    group = q_ref.shape[2]

    def update(with_mask):
        q = q_ref[0, 0]        # [group, hd]
        kj = k_ref[0, :, 0, :]  # [page_size, hd]
        vj = v_ref[0, :, 0, :]
        mask = None
        if with_mask:
            pos = base + jax.lax.broadcasted_iota(
                jnp.int32, (group, page_size), 1
            )
            mask = pos < length
        m, l, acc = online_block_update(
            q, kj, vj, m_scr[:], l_scr[:], acc_scr[:], scale, mask
        )
        m_scr[:] = m
        l_scr[:] = l
        acc_scr[:] = acc

    # three regimes per page, mirroring the flash kernel's causal tiles:
    # fully past the sequence (skip — the ragged win), fully visible
    # interior (no mask work), and the boundary page (masked)
    interior = base + page_size <= length
    boundary = jnp.logical_and(base < length, jnp.logical_not(interior))

    @pl.when(interior)
    def _():
        update(with_mask=False)

    @pl.when(boundary)
    def _():
        update(with_mask=True)

    @pl.when(pi == npg - 1)
    def _emit():
        o_ref[0, 0] = _finalize(l_scr[:], acc_scr[:]).astype(o_ref.dtype)


def ragged_paged_attention(
    q, k_pages, v_pages, page_table, lengths, interpret: Optional[bool] = None
):
    """Fused single-token paged-attention read: the Pallas kernel that
    replaces :func:`paged_attention`'s gather for the serving decode step
    (Ragged Paged Attention, PAPERS.md arXiv:2604.15464).

    Same contract as the gather oracle — ``q`` [S, n_kv, group, hd],
    ``k_pages``/``v_pages`` [pool_pages, page_size, n_kv, hd],
    ``page_table`` [S, max_pages] int32, ``lengths`` [S] int32 (valid
    positions INCLUDING the token just written) — and agrees with it to
    float tolerance (online softmax vs one-shot softmax associativity).
    Returns [S, n_kv, group, hd] in ``q``'s dtype.

    Why it wins: the gather reads ``max_pages * page_size`` positions
    per slot regardless of the slot's real length; this kernel walks
    each slot's page table in-kernel with scalar prefetch and stops the
    COMPUTE at the slot's boundary page, so a ragged batch's bandwidth
    and FLOPs scale with live tokens. The key tile is one page (see
    :func:`paged_page_size_hint` for the measured-best width); the
    online-softmax carry is the flash kernel's own recurrence
    (:func:`online_block_update`), held in VMEM scratch across the
    sequential page axis. Shapes are static, so the serving engine's
    no-recompile property is untouched. ``interpret`` defaults to True
    off-TPU so tests run on CPU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _check_paged_inputs(q, k_pages, v_pages, page_table, lengths)
    slots, n_kv, group, hd = q.shape
    mp = page_table.shape[1]
    ps = k_pages.shape[1]
    scale = 1.0 / float(np.sqrt(hd))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    kernel = functools.partial(
        _ragged_paged_kernel, page_size=ps, scale=scale
    )
    # index maps receive the scalar-prefetch refs after the grid indices:
    # the k/v maps dereference the page table, so the pipeline fetches
    # exactly the pages the table names, in table (= position) order
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, n_kv, mp),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, hd),
                lambda s, h, p, ptab, lens: (s, h, 0, 0),
            ),
            pl.BlockSpec(
                (1, ps, 1, hd),
                lambda s, h, p, ptab, lens: (ptab[s, p], 0, h, 0),
            ),
            pl.BlockSpec(
                (1, ps, 1, hd),
                lambda s, h, p, ptab, lens: (ptab[s, p], 0, h, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, hd), lambda s, h, p, ptab, lens: (s, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, n_kv, group, hd), q.dtype),
        compiler_params=_dim_semantics(pltpu, interpret),
        interpret=interpret,
    )(page_table, lengths, q, k_pages, v_pages)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, block_q, block_k, causal, offset, scale,
):
    """Grid = (batch*heads, q_blocks, k_blocks); the k axis is innermost and
    sequential on TPU, so the VMEM scratch carries the online-softmax state
    across k steps — only one [block_k, d] key/value tile is resident at a
    time (true streaming; context length is HBM-bound, not VMEM-bound).

    ``offset = lk - lq`` aligns the causal diagonal bottom-right, matching
    :func:`attention_reference` for cross-length attention."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def update(with_mask):
        # keep the INPUT dtype: bf16 q/k/v run their matmuls in the MXU's
        # native bf16 mode (online_block_update accumulates f32)
        q = q_ref[0]  # [block_q, d]
        kj = k_ref[0]
        vj = v_ref[0]
        mask = (
            _frontier_mask(iq, ik, block_q, block_k, offset)
            if with_mask
            else None
        )
        m, l, acc = online_block_update(
            q, kj, vj, m_scr[:], l_scr[:], acc_scr[:], scale, mask
        )
        m_scr[:] = m
        l_scr[:] = l
        acc_scr[:] = acc

    if causal:
        # three regimes per tile: fully in the masked future (skip), fully
        # visible interior (no mask work — most tiles at long L), and the
        # diagonal frontier (masked). Skipping the iota/where on interior
        # tiles removes VPU work from the hot path.
        visible, interior = _causal_tile_regimes(
            iq, ik, block_q, block_k, offset
        )

        @pl.when(interior)
        def _():
            update(with_mask=False)

        @pl.when(jnp.logical_and(visible, jnp.logical_not(interior)))
        def _():
            update(with_mask=True)

    else:
        update(with_mask=False)

    @pl.when(ik == nk - 1)
    def _emit():
        o_ref[0] = _finalize(l_scr[:], acc_scr[:]).astype(o_ref.dtype)
        # [bq, 1] rows saved for the backward pass
        lse_ref[0] = _lse_sentinel(m_scr[:], l_scr[:])


def _fit_tile(block, length):
    # largest tile <= the requested block that divides the sequence —
    # lane-aligned (multiple of 128) unless it is the whole sequence.
    # Keeps every length the old 128-tile default accepted working
    # (e.g. L=640 fits 128 when 512 does not divide it).
    cap = min(block, length)
    if length % cap == 0:
        return cap
    fits = [t for t in range(128, cap + 1, 128) if length % t == 0]
    return max(fits) if fits else None


def _dim_semantics(pltpu, interpret):
    # batch*heads and the non-innermost tile axis are independent; only
    # the innermost axis is a sequential reduction (the scratch carry)
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    """The forward pallas call: returns ``(o [B,H,Lq,D], lse [B*H,Lq,1])``.
    ``lse`` (log-sum-exp per query row) is the one extra output the
    FlashAttention backward needs to recompute softmax tiles without the
    [L, L] matrix."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = 1.0 / float(np.sqrt(d))
    bh = b * h
    qf = q.reshape(bh, lq, d)
    kf = k.reshape(bh, lk, d)
    vf = v.reshape(bh, lk, d)
    # NOTE on D=64 (r05): head-pair packing per grid step was built and
    # measured — at its VMEM-safe tiles (two f32 score tiles cap it at
    # bq*bk <= 512k) it reached 24.1% MFU, LOSING to the single-head
    # kernel at full 1024x1024 tiles (28.7%); tile area beats head
    # packing, so the variant was removed (flash_sweep4_r05.json). The
    # D=64 ceiling itself is hardware: the bare matmul pair measures
    # 59.2% of peak (flash_sweep_r05.json attention_matmul_ceiling).
    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        offset=lk - lq,
        scale=scale,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, lq // block_q, lk // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, d), lambda bi, qi, ki: (bi, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_q, d), lambda bi, qi, ki: (bi, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, block_q, 1), lambda bi, qi, ki: (bi, qi, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_dim_semantics(pltpu, interpret),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, lq, d), lse


def _flash_carry_kernel(
    q_ref, k_ref, v_ref, m_in_ref, l_in_ref, acc_in_ref,
    m_out_ref, l_out_ref, acc_out_ref, m_scr, l_scr, acc_scr,
    *, block_q, block_k, causal, offset, scale,
):
    """Carry-mode forward: like :func:`_flash_kernel` but the online-softmax
    state STARTS from an incoming (m, l, acc) and is emitted un-finalized.
    This is the building block ring attention folds one visiting k/v chunk
    with — per-chip memory stays O(block), never O((L/n)^2), because the
    chunk streams through VMEM one [block_k, d] tile at a time exactly as
    in the single-chip kernel."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = m_in_ref[0]
        l_scr[:] = l_in_ref[0]
        acc_scr[:] = acc_in_ref[0]

    def update(with_mask):
        mask = (
            _frontier_mask(iq, ik, block_q, block_k, offset)
            if with_mask
            else None
        )
        m, l, acc = online_block_update(
            q_ref[0], k_ref[0], v_ref[0],
            m_scr[:], l_scr[:], acc_scr[:], scale, mask,
        )
        m_scr[:] = m
        l_scr[:] = l
        acc_scr[:] = acc

    if causal:
        visible, interior = _causal_tile_regimes(
            iq, ik, block_q, block_k, offset
        )

        @pl.when(interior)
        def _():
            update(with_mask=False)

        @pl.when(jnp.logical_and(visible, jnp.logical_not(interior)))
        def _():
            update(with_mask=True)

    else:
        update(with_mask=False)

    @pl.when(ik == nk - 1)
    def _emit():
        m_out_ref[0] = m_scr[:]
        l_out_ref[0] = l_scr[:]
        acc_out_ref[0] = acc_scr[:]


def flash_carry(
    q, k, v, m, l, acc, *, causal, offset, block_q, block_k, interpret
):
    """Fold one key/value span into an online-softmax carry with the flash
    kernel. Flat layout: ``q`` [BH, Lq, D]; ``k``/``v`` [BH, Lk, D]; carry
    ``m``/``l`` [BH, Lq, 1] and ``acc`` [BH, Lq, D], all f32. Returns the
    updated (m, l, acc), not finalized — callers chain spans (ring hops)
    and finalize once. ``offset`` is the static causal diagonal offset
    (``q_global - k_global`` of the first elements); only the diagonal
    ring hop is causal and there it is 0."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = q.shape
    lk = k.shape[1]
    _check_tiles(block_q, lq, block_k, lk)
    scale = 1.0 / float(np.sqrt(d))
    kernel = functools.partial(
        _flash_carry_kernel,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        offset=offset,
        scale=scale,
    )
    q_spec = pl.BlockSpec(
        (1, block_q, d), lambda bi, qi, ki: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    k_spec = pl.BlockSpec(
        (1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
        memory_space=pltpu.VMEM,
    )
    row_spec = pl.BlockSpec(
        (1, block_q, 1), lambda bi, qi, ki: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, lq // block_q, lk // block_k),
        in_specs=[q_spec, k_spec, k_spec, row_spec, row_spec, q_spec],
        out_specs=[row_spec, row_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, lq, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_dim_semantics(pltpu, interpret),
        interpret=interpret,
    )(q, k, v, m, l, acc)


def _bwd_tile_terms(q, kj, vj, do, lse, dlt, scale, mask):
    """Shared per-tile recomputation for both backward kernels: softmax
    probabilities ``p`` and score gradient ``ds`` for one (q, k) tile pair.
    ``lse``/``dlt`` are [bq, 1] (``lse`` in the base-2 domain, matching
    :func:`_lse_sentinel`); fully-masked rows carry the ``_POS_BIG``
    lse sentinel, so ``p`` (and with it every gradient term) is exactly 0
    there. f32 throughout except the matmuls, which keep the input's MXU
    mode (bf16 tiles run the backward at the chip's high rate, like the
    forward)."""
    mxu_dt = _mxu_dtype(q.dtype)
    s = jax.lax.dot_general(
        q.astype(mxu_dt),
        kj.astype(mxu_dt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (scale * _LOG2E)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_BIG)
    p = jnp.exp2(s - lse)  # masked / empty-row entries underflow to 0
    dp = jax.lax.dot_general(
        do.astype(mxu_dt),
        vj.astype(mxu_dt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - dlt) * scale
    return p, ds, mxu_dt


def _causal_tile_regimes(q_block_idx, k_block_idx, block_q, block_k, offset):
    """(visible, interior) predicates for one (q, k) tile pair under the
    bottom-right-aligned causal mask — shared by all three kernels so the
    skip/frontier logic cannot diverge between forward and backward."""
    visible = k_block_idx * block_k <= offset + (q_block_idx + 1) * block_q - 1
    interior = (k_block_idx + 1) * block_k - 1 <= offset + q_block_idx * block_q
    return visible, interior


def _frontier_mask(q_block_idx, k_block_idx, block_q, block_k, offset):
    """The [block_q, block_k] causal mask for a frontier tile (True =
    attend), ``q_pos >= k_pos`` with the bottom-right offset — the other
    half of the shared causal logic (see :func:`_causal_tile_regimes`)."""
    q_pos = offset + q_block_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = k_block_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos >= k_pos


def _frontier_mask_t(q_block_idx, k_block_idx, block_q, block_k, offset):
    """:func:`_frontier_mask` transposed — the [block_k, block_q] mask
    for the dkv kernel's transposed-score tiles (same predicate, iota
    axes swapped so no relayout is spent transposing the mask)."""
    q_pos = offset + q_block_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 1
    )
    k_pos = k_block_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0
    )
    return q_pos >= k_pos


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, block_q, block_k, causal, offset, scale,
):
    """dQ: grid (batch*heads, q_blocks, k_blocks), k innermost sequential;
    the dq tile accumulates in VMEM scratch across k steps (mirror of the
    forward's online accumulation)."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute(with_mask):
        qi = q_ref[0]
        kj = k_ref[0]
        doi = do_ref[0]
        mask = (
            _frontier_mask(iq, ik, block_q, block_k, offset)
            if with_mask
            else None
        )
        _, ds, mxu_dt = _bwd_tile_terms(
            qi, kj, v_ref[0], doi, lse_ref[0], delta_ref[0], scale, mask
        )
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(mxu_dt),
            kj.astype(mxu_dt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        visible, interior = _causal_tile_regimes(
            iq, ik, block_q, block_k, offset
        )

        @pl.when(interior)
        def _():
            compute(with_mask=False)

        @pl.when(jnp.logical_and(visible, jnp.logical_not(interior)))
        def _():
            compute(with_mask=True)

    else:
        compute(with_mask=False)

    @pl.when(ik == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, block_q, block_k, causal, offset, scale,
):
    """dK/dV: grid (batch*heads, k_blocks, q_blocks), q innermost
    sequential; one kernel owns one k tile and streams the q tiles that
    can see it, accumulating both gradients in VMEM scratch.

    The math runs in the TRANSPOSED-score formulation: ``s^T = K Q^T``
    ([bk, bq]) so that all four contractions — s^T, dp^T = V dO^T,
    dV += p^T dO, dK += ds^T Q — contract over their operands' MINOR
    axis. The direct formulation needed two axis-0 contractions
    (``P^T dO``, ``dS^T Q``) whose operand relayouts held this kernel at
    73% of the matmul ceiling while the dq kernel (all-natural
    contractions) ran at 93% (r05 per-kernel sweep,
    flash_sweep2_r05.json). The [bq, 1] lse/delta rows transpose to
    [1, bq] lane vectors once per tile — trivial next to the matmuls."""
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute(with_mask):
        qi = q_ref[0]
        kj = k_ref[0]
        vj = v_ref[0]
        doi = do_ref[0]
        mxu_dt = _mxu_dtype(qi.dtype)
        st = jax.lax.dot_general(
            kj.astype(mxu_dt),
            qi.astype(mxu_dt),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (scale * _LOG2E)
        if with_mask:
            st = jnp.where(
                _frontier_mask_t(iq, jk, block_q, block_k, offset),
                st,
                _NEG_BIG,
            )
        lse_row = lse_ref[0].reshape(1, block_q)
        pt = jnp.exp2(st - lse_row)  # [bk, bq]; masked rows underflow to 0
        dpt = jax.lax.dot_general(
            vj.astype(mxu_dt),
            doi.astype(mxu_dt),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dst = pt * (dpt - delta_ref[0].reshape(1, block_q)) * scale
        dv_scr[:] += jax.lax.dot_general(
            pt.astype(mxu_dt),
            doi.astype(mxu_dt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            dst.astype(mxu_dt),
            qi.astype(mxu_dt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        visible, interior = _causal_tile_regimes(
            iq, jk, block_q, block_k, offset
        )

        @pl.when(interior)
        def _():
            compute(with_mask=False)

        @pl.when(jnp.logical_and(visible, jnp.logical_not(interior)))
        def _():
            compute(with_mask=True)

    else:
        compute(with_mask=False)

    @pl.when(iq == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


#: measured-best backward tiles per (dtype kind, head_dim bucket) — the
#: r05 per-kernel sweep (benchmarks/flash_sweep2_r05.py): the dq kernel
#: (3 matmuls/tile, k innermost) and the dk/dv kernel (4 matmuls/tile, q
#: innermost) run different matmul mixes and need not share the
#: forward's optimum. Keys as in ``_BEST_BLOCKS``; values are
#: ((dq_block_q, dq_block_k), (dkv_block_q, dkv_block_k)).
_BEST_BLOCKS_BWD = {
    # dq (3 natural matmuls, k innermost) peaks at square 1024 tiles
    # (178 TF/s real rate = 93% of ceiling); the transposed-score dkv
    # kernel prefers narrow-q/wide-k (154 TF/s at 512x2048 vs 146 at
    # square). flash_sweep2/3_r05.json. f32 inputs DOUBLE every score
    # intermediate: dkv at 512x2048 f32 needs 26.5 MB of scoped VMEM
    # (measured compile failure) — the f32 rows keep square tiles.
    (True, 128): ((1024, 1024), (512, 2048)),
    (True, 64): ((1024, 1024), (512, 2048)),
    (False, 128): ((1024, 1024), (512, 1024)),
    (False, 64): ((1024, 1024), (512, 1024)),
}


def _best_blocks_bwd(dtype, d, lq, lk):
    """Measured-best (dq, dkv) tile pairs, clamped so every tile divides
    its sequence (``_fit_tile``); falls back to the forward tiles when no
    lane-aligned fit exists."""
    is_lowp = dtype in (jnp.bfloat16, jnp.float16)
    d_bucket = 128 if d > 64 else 64
    (dq_q, dq_k), (kv_q, kv_k) = _BEST_BLOCKS_BWD[(is_lowp, d_bucket)]
    fit = (
        _fit_tile(dq_q, lq), _fit_tile(dq_k, lk),
        _fit_tile(kv_q, lq), _fit_tile(kv_k, lk),
    )
    if any(t is None for t in fit):
        return None
    return fit


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, causal, block_q, block_k, interpret, tune_bwd):
    o, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_core_fwd(q, k, v, causal, block_q, block_k, interpret, tune_bwd):
    o, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_core_bwd(causal, block_q, block_k, interpret, tune_bwd, res, do):
    """FlashAttention-2 backward: recompute each softmax tile from q/k and
    the saved per-row log-sum-exp, never materializing [L, L]. Two pallas
    calls — dq accumulates over k tiles, dk/dv over q tiles — each with
    its OWN measured-best tiles (``_BEST_BLOCKS_BWD``; the forward tiles
    are only the fallback when no tuned tile divides the sequence), and
    the same causal skip/frontier regimes as the forward."""
    q, k, v, o, lse = res
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bh = b * h
    qf = q.reshape(bh, lq, d)
    kf = k.reshape(bh, lk, d)
    vf = v.reshape(bh, lk, d)
    dof = do.reshape(bh, lq, d)
    # delta_i = rowsum(dO_i * O_i): one cheap fused elementwise pass
    delta = (
        dof.astype(jnp.float32) * o.reshape(bh, lq, d).astype(jnp.float32)
    ).sum(axis=-1, keepdims=True)
    # caller-supplied tiles are a VMEM knob and must stay binding (a
    # program sized to fit with small tiles must not OOM in its VJP);
    # only DEFAULTED tiles consult the tuned backward table
    tuned = _best_blocks_bwd(q.dtype, d, lq, lk) if tune_bwd else None
    if tuned is None:
        tuned = (block_q, block_k, block_q, block_k)
    dq_q, dq_k, kv_q, kv_k = tuned
    dq, dk, dv = flash_bwd_pair(
        qf, kf, vf, dof, lse, delta,
        causal=causal, offset=lk - lq,
        block_q=dq_q, block_k=dq_k, interpret=interpret,
        dkv_block_q=kv_q, dkv_block_k=kv_k,
        out_dtypes=(q.dtype, k.dtype, v.dtype),
    )
    return (
        dq.reshape(b, h, lq, d),
        dk.reshape(b, h, lk, d),
        dv.reshape(b, h, lk, d),
    )


def flash_bwd_pair(
    qf, kf, vf, dof, lse, delta, *,
    causal, offset, block_q, block_k, interpret, out_dtypes,
    dkv_block_q=None, dkv_block_k=None,
):
    """The two FlashAttention-2 backward pallas calls for one q-span/k-span
    pair, flat [BH, L, D] layout, with the causal diagonal at static
    ``offset``. Shared by the single-chip VJP (offset = lk - lq) and the
    ring backward (per-hop gradients; offset 0 on the diagonal hop).
    ``out_dtypes`` picks the emitted (dq, dk, dv) dtypes — the ring passes
    f32 so cross-hop accumulation never truncates. ``dkv_block_*``
    override the dk/dv kernel's tiles (it prefers wide-q/narrow-k, the
    transpose of the dq kernel's optimum — see ``_BEST_BLOCKS_BWD``);
    they default to the dq tiles."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, lq, d = qf.shape
    lk = kf.shape[1]
    dkv_block_q = dkv_block_q or block_q
    dkv_block_k = dkv_block_k or block_k
    _check_tiles(block_q, lq, block_k, lk)
    _check_tiles(dkv_block_q, lq, dkv_block_k, lk)
    scale = 1.0 / float(np.sqrt(d))
    dq_dt, dk_dt, dv_dt = out_dtypes

    q_spec = pl.BlockSpec(
        (1, block_q, d), lambda bi, qi, ki: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    k_spec = pl.BlockSpec(
        (1, block_k, d), lambda bi, qi, ki: (bi, ki, 0),
        memory_space=pltpu.VMEM,
    )
    row_spec = pl.BlockSpec(
        (1, block_q, 1), lambda bi, qi, ki: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_q=block_q,
            block_k=block_k,
            causal=causal,
            offset=offset,
            scale=scale,
        ),
        grid=(bh, lq // block_q, lk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), dq_dt),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_dim_semantics(pltpu, interpret),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # k-major grid: index maps swap which grid axis picks the q vs k tile
    qk_q_spec = pl.BlockSpec(
        (1, dkv_block_q, d), lambda bi, ki, qi: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    qk_k_spec = pl.BlockSpec(
        (1, dkv_block_k, d), lambda bi, ki, qi: (bi, ki, 0),
        memory_space=pltpu.VMEM,
    )
    qk_row_spec = pl.BlockSpec(
        (1, dkv_block_q, 1), lambda bi, ki, qi: (bi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=dkv_block_q,
            block_k=dkv_block_k,
            causal=causal,
            offset=offset,
            scale=scale,
        ),
        grid=(bh, lk // dkv_block_k, lq // dkv_block_q),
        in_specs=[
            qk_q_spec, qk_k_spec, qk_k_spec, qk_q_spec,
            qk_row_spec, qk_row_spec,
        ],
        out_specs=[qk_k_spec, qk_k_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), dk_dt),
            jax.ShapeDtypeStruct((bh, lk, d), dv_dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((dkv_block_k, d), jnp.float32),
            pltpu.VMEM((dkv_block_k, d), jnp.float32),
        ],
        compiler_params=_dim_semantics(pltpu, interpret),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Tiled attention, [B, H, L, D] layout. DIFFERENTIABLE: a custom VJP
    runs the FlashAttention-2 backward as two more pallas kernels (dq over
    k tiles; dk/dv over q tiles), recomputing softmax tiles from the saved
    per-row log-sum-exp — long-context training never materializes [L, L]
    in either direction.

    Default tiles come from the measured-best table ``_BEST_BLOCKS``
    (chain-differential timed per dtype/head_dim/L on v5e; 1024x1024 on
    every current entry, clamped to the sequence) — bigger tiles amortize
    the online-softmax rescale and keep the MXU on larger matmuls, and
    2048+ tiles exceed VMEM. bf16 inputs run the matmuls in the MXU's
    native bf16 mode with f32 accumulation (see
    :func:`online_block_update`), forward and backward.

    One grid step owns one (query block, key block) pair; the online-softmax
    state lives in VMEM scratch across the key axis, so K/V stream through
    VMEM one tile at a time. Sequence lengths must be multiples of the block
    sizes (callers pad; the ring layer shards to equal chunks anyway).
    Causal masking aligns the diagonal bottom-right when ``lq != lk`` (same
    convention as :func:`attention_reference`). ``interpret`` defaults to
    True off-TPU so tests run on CPU."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    # explicit tiles are a VMEM knob: they bind the backward too (the
    # tuned _BEST_BLOCKS_BWD table applies only when tiles defaulted)
    tune_bwd = block_q is None and block_k is None
    if block_q is None or block_k is None:
        tuned_q, tuned_k = _best_blocks(q.dtype, d, max(lq, lk))
        block_q = block_q or tuned_q
        block_k = block_k or tuned_k
    block_q = _fit_tile(block_q, lq)
    block_k = _fit_tile(block_k, lk)
    if block_q is None or block_k is None:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) admit no lane-aligned tile; "
            f"pad to a multiple of 128 (callers pad; the ring layer shards "
            f"to equal chunks anyway)"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_core(
        q, k, v, causal, block_q, block_k, interpret, tune_bwd
    )
