"""Op-builder facade: the reference's Py4J builder flow, JVM-free.

The reference's Python client drives a stateful JVM builder
(``PythonOpBuilder``,
``/root/reference/src/main/scala/org/tensorframes/impl/PythonInterface.scala:86-170``):
accumulate a graph (bytes or file path), shape hints, fetches, and an input
map, then ``buildDF()`` (maps/aggregates) or ``buildRow()`` (reduces). This
facade keeps that calling convention for users porting reference code, over
the native CapturedGraph/engine stack — no sockets, no JVM.

Example (reference style)::

    out = (OpBuilder.map_blocks(df)
             .graph_from_file("prog.tfs")
             .inputs({"x": "col_a"})
             .build_df())
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .capture import CapturedGraph, deserialize_graph, load_graph
from .frame import GroupedFrame, TensorFrame
from .schema import Shape

__all__ = ["OpBuilder"]

_MAP_KINDS = ("map_blocks", "map_blocks_trimmed", "map_rows", "aggregate")
_ROW_KINDS = ("reduce_blocks", "reduce_rows")


class OpBuilder:
    """Stateful builder: graph + hints + fetches + inputs -> engine call."""

    def __init__(self, kind: str, dframe, trim: bool = False):
        if kind not in _MAP_KINDS + _ROW_KINDS:
            raise ValueError(f"unknown op kind {kind!r}")
        self._kind = kind
        self._df = dframe
        self._trim = trim
        self._graph: Optional[CapturedGraph] = None
        self._fetches: Optional[List[str]] = None
        self._hints: Dict[str, Shape] = {}
        self._inputs: Dict[str, str] = {}

    # -- constructors matching PythonInterface.scala:46-68 ------------------

    @staticmethod
    def map_blocks(dframe: TensorFrame, trim: bool = False) -> "OpBuilder":
        return OpBuilder("map_blocks", dframe, trim=trim)

    @staticmethod
    def map_rows(dframe: TensorFrame) -> "OpBuilder":
        return OpBuilder("map_rows", dframe)

    @staticmethod
    def reduce_blocks(dframe: TensorFrame) -> "OpBuilder":
        return OpBuilder("reduce_blocks", dframe)

    @staticmethod
    def reduce_rows(dframe: TensorFrame) -> "OpBuilder":
        return OpBuilder("reduce_rows", dframe)

    @staticmethod
    def aggregate_blocks(grouped: GroupedFrame) -> "OpBuilder":
        return OpBuilder("aggregate", grouped)

    # -- accumulation (PythonOpBuilder.graph/graphFromFile/shape/fetches/
    # -- inputs, PythonInterface.scala:97-127) ------------------------------

    def graph(self, data) -> "OpBuilder":
        """Attach the program: serialized bytes or a CapturedGraph."""
        if isinstance(data, CapturedGraph):
            self._graph = data
        elif isinstance(data, (bytes, bytearray)):
            self._graph = deserialize_graph(bytes(data))
        else:
            raise TypeError("graph() takes serialized bytes or a CapturedGraph")
        return self

    def graph_from_file(self, path: str) -> "OpBuilder":
        """Load a serialized program (reference ``graphFromFile``,
        ``PythonInterface.scala:115-118``)."""
        self._graph = load_graph(path)
        return self

    def shape(self, names: Sequence[str], shapes: Sequence[Sequence[int]]) -> "OpBuilder":
        """Shape hints by tensor name (reference ``builder.shape``)."""
        for name, dims in zip(names, shapes):
            self._hints[name] = Shape.from_jax(
                tuple(None if d in (-1, None) else int(d) for d in dims)
            )
        return self

    def fetches(self, names: Sequence[str]) -> "OpBuilder":
        self._fetches = list(names)
        return self

    def inputs(self, placeholder_names, field_names=None) -> "OpBuilder":
        """Placeholder -> column map; accepts a dict or two parallel lists
        (the reference's wire format, ``PythonInterface.scala:120-127``)."""
        if field_names is None:
            self._inputs.update(dict(placeholder_names))
        else:
            self._inputs.update(zip(placeholder_names, field_names))
        return self

    # -- build --------------------------------------------------------------

    def _final_graph(self) -> CapturedGraph:
        if self._graph is None:
            raise ValueError("no graph attached; call graph()/graph_from_file()")
        g = self._graph
        if self._fetches is not None:
            missing = [f for f in self._fetches if f not in g.fetch_names]
            if missing:
                raise KeyError(
                    f"fetches {missing} not among program outputs "
                    f"{g.fetch_names}"
                )
            if list(self._fetches) != g.fetch_names:
                g = CapturedGraph(
                    g.fn,
                    list(g.placeholders.values()),
                    self._fetches,
                    g.inputs_map,
                    g.shape_hints,
                )
        if self._inputs:
            g = g.with_inputs(self._inputs)
        if self._hints:
            g = g.with_hints(
                {k: v for k, v in self._hints.items() if k in g.fetch_names}
            )
        return g

    def build_df(self) -> TensorFrame:
        """Run a map/aggregate (reference ``buildDF``,
        ``PythonInterface.scala:144-151``)."""
        from . import engine

        g = self._final_graph()
        if self._kind == "map_blocks":
            return engine.map_blocks(g, self._df, trim=self._trim)
        if self._kind == "map_blocks_trimmed":
            return engine.map_blocks(g, self._df, trim=True)
        if self._kind == "map_rows":
            return engine.map_rows(g, self._df)
        if self._kind == "aggregate":
            return engine.aggregate(g, self._df)
        raise ValueError(f"build_df not valid for {self._kind!r}")

    def build_row(self):
        """Run a reduce (reference ``buildRow``,
        ``PythonInterface.scala:129-142``)."""
        from . import engine

        g = self._final_graph()
        if self._kind == "reduce_blocks":
            return engine.reduce_blocks(g, self._df)
        if self._kind == "reduce_rows":
            return engine.reduce_rows(g, self._df)
        raise ValueError(f"build_row not valid for {self._kind!r}")

    # camelCase aliases matching the reference wire names
    buildDF = build_df
    buildRow = build_row
    graphFromFile = graph_from_file
