// Native group-key coder, list-direct path.
//
// The aggregate path's string-key coding (see engine/ops.py
// _group_sort_impl) needs first-appearance integer codes for N byte
// strings held in a Python list. Marshalling them into a contiguous
// buffer from Python costs more than the coding itself (measured 4.5 s
// of join + len() loops against 0.5 s of hashing at 10M rows), so this
// library takes the list itself: pointers are read via the CPython API
// under the GIL (zero copies — PyBytes internals are stable while the
// list holds references), then the GIL is RELEASED for the hash pass.
//
// The hash pass is chunk-parallel (one local open-addressing table per
// chunk, a serial first-appearance merge over distinct entries, then a
// parallel translate — the same scheme as tfs_code_keys in
// executor.cpp) and degenerates to a single serial pass on one-CPU
// hosts. Open addressing with byte-wise FNV-1a beats unordered_map by
// avoiding per-node allocation; slots store the first row index of the
// key so comparisons read the original bytes.
//
// Built as its own shared object (libtfscoder.so): it links against the
// CPython API, and a host where that fails must not take down the plain
// packer kernels in libtfspacker.so.

#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct View {
  const char* p;
  int64_t len;
};

inline uint64_t Hash(const View& v) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a
  for (int64_t i = 0; i < v.len; ++i) {
    h ^= static_cast<unsigned char>(v.p[i]);
    h *= 1099511628211ull;
  }
  return h ^ (h >> 32);
}

inline bool Eq(const View& a, const View& b) {
  return a.len == b.len && std::memcmp(a.p, b.p, a.len) == 0;
}

// open-addressing table of row indices; the key of slot s is
// views[slots[s]]. -1 = empty.
class Table {
 public:
  explicit Table(int64_t expected) {
    int64_t cap = 64;
    while (cap < expected * 2) cap <<= 1;
    mask_ = cap - 1;
    slots_.assign(cap, -1);
  }

  // returns the representative row of the key (inserting row if new)
  int64_t FindOrInsert(const std::vector<View>& views, int64_t row) {
    const View& key = views[row];
    uint64_t s = Hash(key) & mask_;
    for (;;) {
      int64_t r = slots_[s];
      if (r < 0) {
        if (static_cast<int64_t>(count_) * 2 >
            static_cast<int64_t>(slots_.size())) {
          Grow(views);
          return FindOrInsert(views, row);
        }
        slots_[s] = row;
        ++count_;
        return row;
      }
      if (Eq(views[r], key)) return r;
      s = (s + 1) & mask_;
    }
  }

  int64_t size() const { return count_; }

 private:
  void Grow(const std::vector<View>& views) {
    std::vector<int64_t> old;
    old.swap(slots_);
    mask_ = mask_ * 2 + 1;
    slots_.assign(mask_ + 1, -1);
    for (int64_t r : old) {
      if (r < 0) continue;
      uint64_t s = Hash(views[r]) & mask_;
      while (slots_[s] >= 0) s = (s + 1) & mask_;
      slots_[s] = r;
    }
  }

  std::vector<int64_t> slots_;
  uint64_t mask_;
  int64_t count_ = 0;
};

}  // namespace

extern "C" {

int64_t tfs_coder_abi_version() { return 1; }

// First-appearance int32 codes for a list of bytes objects. Returns the
// distinct-key count, -2 when an element is not exactly `bytes` (caller
// falls back to the buffer path), -1 on other errors.
int64_t tfs_code_keys_list(PyObject* list, int32_t* out_codes) {
  if (!PyList_Check(list)) return -1;
  const int64_t n = PyList_GET_SIZE(list);
  if (n == 0) return 0;
  std::vector<View> views(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(list, i);  // borrowed
    if (!PyBytes_Check(o)) return -2;
    views[static_cast<size_t>(i)] = {PyBytes_AS_STRING(o),
                                     PyBytes_GET_SIZE(o)};
  }

  int64_t groups = 0;
  Py_BEGIN_ALLOW_THREADS;

  int64_t threads = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (threads < 1) threads = 1;
  if (threads > 16) threads = 16;
  int64_t chunks = std::min<int64_t>(threads, (n + 65535) / 65536);
  if (chunks < 1) chunks = 1;
  const int64_t per = (n + chunks - 1) / chunks;

  // phase 1: per-chunk local coding (provisional code = local rank)
  std::vector<std::vector<int64_t>> first_rows(
      static_cast<size_t>(chunks));
  auto local_pass = [&](int64_t c) {
    const int64_t b = c * per;
    const int64_t e = std::min(n, b + per);
    Table t(std::min<int64_t>(e - b, 1 << 16));
    std::vector<int64_t>& fr = first_rows[static_cast<size_t>(c)];
    for (int64_t i = b; i < e; ++i) {
      const int64_t rep = t.FindOrInsert(views, i);
      if (rep == i) {
        out_codes[i] = static_cast<int32_t>(fr.size());
        fr.push_back(i);
      } else {
        out_codes[i] = out_codes[rep];
      }
    }
  };
  if (chunks == 1) {
    local_pass(0);
  } else {
    std::vector<std::thread> ts;
    for (int64_t c = 1; c < chunks; ++c) {
      ts.emplace_back(local_pass, c);
    }
    local_pass(0);
    for (auto& t : ts) t.join();
  }

  // phase 2: serial merge over distinct entries, first-appearance order
  struct Entry {
    int64_t row;
    int32_t chunk;
    int32_t local;
  };
  std::vector<Entry> entries;
  size_t total = 0;
  for (const auto& fr : first_rows) total += fr.size();
  entries.reserve(total);
  for (int64_t c = 0; c < chunks; ++c) {
    const auto& fr = first_rows[static_cast<size_t>(c)];
    for (size_t l = 0; l < fr.size(); ++l) {
      entries.push_back({fr[l], static_cast<int32_t>(c),
                         static_cast<int32_t>(l)});
    }
  }
  if (chunks == 1) {
    groups = static_cast<int64_t>(entries.size());
  } else {
    std::sort(entries.begin(), entries.end(),
              [](const Entry& a, const Entry& b) { return a.row < b.row; });
    Table g(static_cast<int64_t>(entries.size()));
    std::vector<std::vector<int32_t>> trans(static_cast<size_t>(chunks));
    for (int64_t c = 0; c < chunks; ++c) {
      trans[static_cast<size_t>(c)].resize(
          first_rows[static_cast<size_t>(c)].size());
    }
    // the Table returns the FIRST row inserted for each key, which
    // under row-sorted insertion IS the global first appearance;
    // rep_gid (sorted by rep row, append-only) maps it to its code
    int64_t next = 0;
    std::vector<std::pair<int64_t, int32_t>> rep_gid;
    rep_gid.reserve(entries.size());
    for (const Entry& en : entries) {
      const int64_t rep = g.FindOrInsert(views, en.row);
      int32_t gid;
      if (rep == en.row) {
        gid = static_cast<int32_t>(next++);
        rep_gid.push_back({rep, gid});
      } else {
        // find the gid assigned to rep: rep rows arrive sorted, so a
        // binary search over rep_gid (sorted by rep row) resolves it
        auto it = std::lower_bound(
            rep_gid.begin(), rep_gid.end(), std::make_pair(rep, 0),
            [](const std::pair<int64_t, int32_t>& a,
               const std::pair<int64_t, int32_t>& b) {
              return a.first < b.first;
            });
        gid = it->second;
      }
      trans[static_cast<size_t>(en.chunk)][static_cast<size_t>(en.local)] =
          gid;
    }
    groups = next;

    // phase 3: parallel translate
    auto translate = [&](int64_t c) {
      const auto& tr = trans[static_cast<size_t>(c)];
      const int64_t b = c * per;
      const int64_t e = std::min(n, b + per);
      for (int64_t i = b; i < e; ++i) {
        out_codes[i] = tr[static_cast<size_t>(out_codes[i])];
      }
    };
    std::vector<std::thread> ts;
    for (int64_t c = 1; c < chunks; ++c) ts.emplace_back(translate, c);
    translate(0);
    for (auto& t : ts) t.join();
  }

  Py_END_ALLOW_THREADS;
  return groups;
}

}  // extern "C"
