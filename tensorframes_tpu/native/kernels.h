// Row-range kernel bodies shared by the serial entry points (packer.cpp)
// and the thread-pool executor (executor.cpp): exactly one implementation
// of each loop, parameterized by [row_begin, row_end).

#ifndef TFS_NATIVE_KERNELS_H_
#define TFS_NATIVE_KERNELS_H_

#include <cstdint>
#include <cstring>

namespace tfs {

inline void GatherRowsRange(const char* src, int64_t row_bytes,
                            const int64_t* idx, int64_t begin, int64_t end,
                            char* out) {
  for (int64_t k = begin; k < end; ++k) {
    std::memcpy(out + k * row_bytes, src + idx[k] * row_bytes, row_bytes);
  }
}

inline void ScatterRowsRange(const char* src, int64_t row_bytes,
                             const int64_t* idx, int64_t begin, int64_t end,
                             char* out) {
  for (int64_t k = begin; k < end; ++k) {
    std::memcpy(out + idx[k] * row_bytes, src + k * row_bytes, row_bytes);
  }
}

inline void PadRaggedRange(const char* flat, const int64_t* offsets,
                           int64_t begin, int64_t end, int64_t max_len,
                           int64_t elem_size, const char* pad_elem,
                           char* out) {
  const int64_t row_bytes = max_len * elem_size;
  for (int64_t i = begin; i < end; ++i) {
    const int64_t len = offsets[i + 1] - offsets[i];
    char* dst = out + i * row_bytes;
    std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
    const int64_t pad_count = max_len - len;
    if (pad_count <= 0) continue;
    char* pad_dst = dst + len * elem_size;
    if (pad_elem == nullptr) {
      std::memset(pad_dst, 0, pad_count * elem_size);
    } else {
      for (int64_t j = 0; j < pad_count; ++j) {
        std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
      }
    }
  }
}

inline void GatherRaggedPadRange(const char* flat, const int64_t* offsets,
                                 const int64_t* idx, int64_t begin,
                                 int64_t end, int64_t max_len,
                                 int64_t elem_size, const char* pad_elem,
                                 char* out) {
  const int64_t row_bytes = max_len * elem_size;
  for (int64_t k = begin; k < end; ++k) {
    const int64_t i = idx[k];
    const int64_t len = offsets[i + 1] - offsets[i];
    char* dst = out + k * row_bytes;
    std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
    const int64_t pad_count = max_len - len;
    if (pad_count <= 0) continue;
    char* pad_dst = dst + len * elem_size;
    if (pad_elem == nullptr) {
      std::memset(pad_dst, 0, pad_count * elem_size);
    } else {
      for (int64_t j = 0; j < pad_count; ++j) {
        std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
      }
    }
  }
}

}  // namespace tfs

#endif  // TFS_NATIVE_KERNELS_H_
