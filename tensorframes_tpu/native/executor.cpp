// Native executor: a persistent thread pool running the packer kernels in
// parallel for large columns.
//
// The reference's runtime-side concurrency lives in Spark's task executor
// (tasks scheduled across JVM worker threads); here the engine is a single
// Python process, so the native layer carries its own pool. Kernels are
// pure byte movement with disjoint output ranges per row, so row-range
// splitting is race-free by construction. The pool is created lazily on
// first use and sized to the hardware (capped), overridable for tests.
//
// Build: compiled together with packer.cpp into libtfspacker.so (see
// tensorframes_tpu/data/packer.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

class Pool {
 public:
  explicit Pool(int n) : stop_(false), pending_(0) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Work(); });
    }
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int size() const { return static_cast<int>(workers_.size()); }

  // run fn(chunk_begin, chunk_end) over [0, n) split across the pool and
  // the calling thread; returns when every chunk is done
  void ParallelFor(int64_t n, int64_t min_chunk,
                   const std::function<void(int64_t, int64_t)>& fn) {
    const int workers = size() + 1;  // + calling thread
    int64_t chunks = (n + min_chunk - 1) / min_chunk;
    if (chunks > workers) chunks = workers;
    if (chunks <= 1) {
      fn(0, n);
      return;
    }
    const int64_t per = (n + chunks - 1) / chunks;
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (int64_t c = 1; c < chunks; ++c) {
        const int64_t b = c * per;
        const int64_t e = std::min(n, b + per);
        if (b >= e) continue;
        ++pending_;
        tasks_.push([fn, b, e] { fn(b, e); });
      }
    }
    cv_.notify_all();
    fn(0, std::min(n, per));  // calling thread takes the first chunk
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
  }

 private:
  void Work() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.pop();
      }
      task();
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (--pending_ == 0) done_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable done_cv_;
  bool stop_;
  int64_t pending_;
};

std::mutex g_pool_mu;
std::condition_variable g_idle_cv;
Pool* g_pool = nullptr;
int g_threads = 0;  // 0 = auto
int g_in_use = 0;

Pool* GetPoolLocked() {
  if (g_pool == nullptr) {
    int n = g_threads;
    if (n <= 0) {
      n = static_cast<int>(std::thread::hardware_concurrency());
      if (n > 16) n = 16;
      if (n < 1) n = 1;
    }
    g_pool = new Pool(n - 1);  // calling thread participates
  }
  return g_pool;
}

// RAII pool lease: set_threads must not delete the pool out from under a
// concurrent ParallelFor (ctypes releases the GIL, so concurrent native
// calls are real); the lease counter makes the swap wait for idle.
class PoolLease {
 public:
  PoolLease() {
    std::unique_lock<std::mutex> lk(g_pool_mu);
    pool_ = GetPoolLocked();
    ++g_in_use;
  }
  ~PoolLease() {
    std::unique_lock<std::mutex> lk(g_pool_mu);
    if (--g_in_use == 0) g_idle_cv.notify_all();
  }
  Pool* operator->() { return pool_; }

 private:
  Pool* pool_;
};

//: below this many bytes per chunk, splitting costs more than it saves
constexpr int64_t kMinChunkBytes = 1 << 20;

}  // namespace

extern "C" {

// set the pool size BEFORE first use (tests); 0 restores auto sizing.
// Returns the previously configured value.
int64_t tfs_executor_set_threads(int64_t n) {
  std::unique_lock<std::mutex> lk(g_pool_mu);
  g_idle_cv.wait(lk, [] { return g_in_use == 0; });  // drain active leases
  const int64_t old = g_threads;
  g_threads = static_cast<int>(n);
  delete g_pool;
  g_pool = nullptr;
  return old;
}

int64_t tfs_executor_threads() {
  PoolLease pool;
  return pool->size() + 1;
}

// parallel variants of the packer kernels: identical semantics, row
// ranges split across the pool (outputs are disjoint per row)

void tfs_par_gather_rows(const char* src, int64_t row_bytes,
                         const int64_t* idx, int64_t n_idx, char* out) {
  const int64_t min_rows = kMinChunkBytes / (row_bytes ? row_bytes : 1) + 1;
  PoolLease pool;
  pool->ParallelFor(n_idx, min_rows, [&](int64_t b, int64_t e) {
    for (int64_t k = b; k < e; ++k) {
      std::memcpy(out + k * row_bytes, src + idx[k] * row_bytes, row_bytes);
    }
  });
}

void tfs_par_scatter_rows(const char* src, int64_t row_bytes,
                          const int64_t* idx, int64_t n_idx, char* out) {
  const int64_t min_rows = kMinChunkBytes / (row_bytes ? row_bytes : 1) + 1;
  PoolLease pool;
  pool->ParallelFor(n_idx, min_rows, [&](int64_t b, int64_t e) {
    for (int64_t k = b; k < e; ++k) {
      std::memcpy(out + idx[k] * row_bytes, src + k * row_bytes, row_bytes);
    }
  });
}

void tfs_par_pad_ragged(const char* flat, const int64_t* offsets,
                        int64_t n_rows, int64_t max_len, int64_t elem_size,
                        const char* pad_elem, char* out) {
  const int64_t row_bytes = max_len * elem_size;
  const int64_t min_rows = kMinChunkBytes / (row_bytes ? row_bytes : 1) + 1;
  PoolLease pool;
  pool->ParallelFor(n_rows, min_rows, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const int64_t len = offsets[i + 1] - offsets[i];
      char* dst = out + i * row_bytes;
      std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
      const int64_t pad_count = max_len - len;
      if (pad_count <= 0) continue;
      char* pad_dst = dst + len * elem_size;
      if (pad_elem == nullptr) {
        std::memset(pad_dst, 0, pad_count * elem_size);
      } else {
        for (int64_t j = 0; j < pad_count; ++j) {
          std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
        }
      }
    }
  });
}

void tfs_par_gather_ragged_pad(const char* flat, const int64_t* offsets,
                               const int64_t* idx, int64_t n_idx,
                               int64_t max_len, int64_t elem_size,
                               const char* pad_elem, char* out) {
  const int64_t row_bytes = max_len * elem_size;
  const int64_t min_rows = kMinChunkBytes / (row_bytes ? row_bytes : 1) + 1;
  PoolLease pool;
  pool->ParallelFor(n_idx, min_rows, [&](int64_t b, int64_t e) {
    for (int64_t k = b; k < e; ++k) {
      const int64_t i = idx[k];
      const int64_t len = offsets[i + 1] - offsets[i];
      char* dst = out + k * row_bytes;
      std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
      const int64_t pad_count = max_len - len;
      if (pad_count <= 0) continue;
      char* pad_dst = dst + len * elem_size;
      if (pad_elem == nullptr) {
        std::memset(pad_dst, 0, pad_count * elem_size);
      } else {
        for (int64_t j = 0; j < pad_count; ++j) {
          std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
        }
      }
    }
  });
}

}  // extern "C"
