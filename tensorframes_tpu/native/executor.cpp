// Native executor: a persistent thread pool running the packer kernels in
// parallel for large columns.
//
// The reference's runtime-side concurrency lives in Spark's task executor
// (tasks scheduled across JVM worker threads); here the engine is a single
// Python process, so the native layer carries its own pool. Kernel bodies
// live in kernels.h (shared with the serial entry points in packer.cpp);
// outputs are disjoint per row, so row-range splitting is race-free. The
// pool is created lazily, sized to the hardware (capped), overridable for
// tests; completion is tracked PER INVOCATION so concurrent callers
// (ctypes releases the GIL) never wait on each other's work.
//
// Build: compiled together with packer.cpp into libtfspacker.so (see
// tensorframes_tpu/data/packer.py).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <queue>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

#include "kernels.h"

namespace {

class Pool {
 public:
  explicit Pool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Work(); });
    }
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  int size() const { return static_cast<int>(workers_.size()); }

  // run fn(chunk_begin, chunk_end) over [0, n) split across the pool and
  // the calling thread; returns when THIS invocation's chunks are done
  // (other invocations may be in flight on the same pool)
  void ParallelFor(int64_t n, int64_t min_chunk,
                   const std::function<void(int64_t, int64_t)>& fn) {
    const int workers = size() + 1;  // + calling thread
    int64_t chunks = (n + min_chunk - 1) / min_chunk;
    if (chunks > workers) chunks = workers;
    if (chunks <= 1) {
      fn(0, n);
      return;
    }
    struct Invocation {
      std::mutex m;
      std::condition_variable done;
      int64_t remaining = 0;
    } inv;
    const int64_t per = (n + chunks - 1) / chunks;
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (int64_t c = 1; c < chunks; ++c) {
        const int64_t b = c * per;
        const int64_t e = std::min(n, b + per);
        if (b >= e) continue;
        {
          std::unique_lock<std::mutex> ilk(inv.m);
          ++inv.remaining;
        }
        tasks_.push([&fn, &inv, b, e] {
          fn(b, e);
          std::unique_lock<std::mutex> ilk(inv.m);
          if (--inv.remaining == 0) inv.done.notify_one();
        });
      }
    }
    cv_.notify_all();
    fn(0, std::min(n, per));  // calling thread takes the first chunk
    std::unique_lock<std::mutex> ilk(inv.m);
    inv.done.wait(ilk, [&inv] { return inv.remaining == 0; });
  }

 private:
  void Work() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.pop();
      }
      task();
    }
  }

  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> tasks_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

std::mutex g_pool_mu;
std::condition_variable g_idle_cv;
Pool* g_pool = nullptr;
int g_threads = 0;  // 0 = auto
int g_in_use = 0;

Pool* GetPoolLocked() {
  if (g_pool == nullptr) {
    int n = g_threads;
    if (n <= 0) {
      n = static_cast<int>(std::thread::hardware_concurrency());
      if (n > 16) n = 16;
      if (n < 1) n = 1;
    }
    g_pool = new Pool(n - 1);  // calling thread participates
  }
  return g_pool;
}

// RAII pool lease: set_threads must not delete the pool out from under a
// concurrent ParallelFor (ctypes releases the GIL, so concurrent native
// calls are real); the lease counter makes the swap wait for idle.
class PoolLease {
 public:
  PoolLease() {
    std::unique_lock<std::mutex> lk(g_pool_mu);
    pool_ = GetPoolLocked();
    ++g_in_use;
  }
  ~PoolLease() {
    std::unique_lock<std::mutex> lk(g_pool_mu);
    if (--g_in_use == 0) g_idle_cv.notify_all();
  }
  Pool* operator->() { return pool_; }

 private:
  Pool* pool_;
};

//: below this many bytes per chunk, splitting costs more than it saves
constexpr int64_t kMinChunkBytes = 1 << 20;

inline int64_t MinRows(int64_t row_bytes) {
  return kMinChunkBytes / (row_bytes ? row_bytes : 1) + 1;
}

}  // namespace

extern "C" {

// resize the pool (0 restores auto sizing); waits for in-flight kernels
// to drain before swapping. Returns the previously configured value.
int64_t tfs_executor_set_threads(int64_t n) {
  std::unique_lock<std::mutex> lk(g_pool_mu);
  g_idle_cv.wait(lk, [] { return g_in_use == 0; });
  const int64_t old = g_threads;
  g_threads = static_cast<int>(n);
  delete g_pool;
  g_pool = nullptr;
  return old;
}

int64_t tfs_executor_threads() {
  PoolLease pool;
  return pool->size() + 1;
}

// parallel entry points: one shared kernel body each (kernels.h)

void tfs_par_gather_rows(const char* src, int64_t row_bytes,
                         const int64_t* idx, int64_t n_idx, char* out) {
  PoolLease pool;
  pool->ParallelFor(n_idx, MinRows(row_bytes), [&](int64_t b, int64_t e) {
    tfs::GatherRowsRange(src, row_bytes, idx, b, e, out);
  });
}

void tfs_par_scatter_rows(const char* src, int64_t row_bytes,
                          const int64_t* idx, int64_t n_idx, char* out) {
  PoolLease pool;
  pool->ParallelFor(n_idx, MinRows(row_bytes), [&](int64_t b, int64_t e) {
    tfs::ScatterRowsRange(src, row_bytes, idx, b, e, out);
  });
}

void tfs_par_pad_ragged(const char* flat, const int64_t* offsets,
                        int64_t n_rows, int64_t max_len, int64_t elem_size,
                        const char* pad_elem, char* out) {
  PoolLease pool;
  pool->ParallelFor(
      n_rows, MinRows(max_len * elem_size), [&](int64_t b, int64_t e) {
        tfs::PadRaggedRange(
            flat, offsets, b, e, max_len, elem_size, pad_elem, out);
      });
}

void tfs_par_gather_ragged_pad(const char* flat, const int64_t* offsets,
                               const int64_t* idx, int64_t n_idx,
                               int64_t max_len, int64_t elem_size,
                               const char* pad_elem, char* out) {
  PoolLease pool;
  pool->ParallelFor(
      n_idx, MinRows(max_len * elem_size), [&](int64_t b, int64_t e) {
        tfs::GatherRaggedPadRange(
            flat, offsets, idx, b, e, max_len, elem_size, pad_elem, out);
      });
}

// First-appearance integer coding of n byte strings (the group-by key
// coding pass, the analog of pandas.factorize for the aggregate path):
// strings live in one packed buffer with offsets[n+1]. Two parallel
// phases around a tiny serial merge:
//   1. each chunk builds a local string -> local-code map and writes
//      provisional local codes;
//   2. local dictionaries merge by GLOBAL first-appearance row (sorted
//      over sum-of-distinct entries, usually << n), yielding a
//      local-code -> global-code translation per chunk;
//   3. chunks translate their provisional codes in place.
// Returns the number of distinct keys, or -1 on error. Codes land in
// int32 (a group id is bounded by the row count; callers narrow further
// for the device upload).
int64_t tfs_code_keys(const char* buf, const int64_t* offsets, int64_t n,
                      int32_t* out_codes) {
  if (n <= 0) return 0;
  PoolLease pool;
  const int64_t workers = pool->size() + 1;
  // chunk layout must be reproducible across the two phases: fix it here
  int64_t chunks = std::min<int64_t>(workers, (n + 65535) / 65536);
  if (chunks < 1) chunks = 1;
  const int64_t per = (n + chunks - 1) / chunks;

  struct LocalDict {
    std::unordered_map<std::string_view, int32_t> map;
    std::vector<int64_t> first_row;  // local code -> global first row
  };
  std::vector<LocalDict> dicts(static_cast<size_t>(chunks));

  pool->ParallelFor(chunks, 1, [&](int64_t cb, int64_t ce) {
    for (int64_t c = cb; c < ce; ++c) {
      LocalDict& d = dicts[static_cast<size_t>(c)];
      const int64_t b = c * per;
      const int64_t e = std::min(n, b + per);
      d.map.reserve(256);
      for (int64_t i = b; i < e; ++i) {
        const std::string_view key(buf + offsets[i],
                                   static_cast<size_t>(offsets[i + 1] -
                                                       offsets[i]));
        auto it = d.map.find(key);
        if (it == d.map.end()) {
          const int32_t code = static_cast<int32_t>(d.first_row.size());
          d.map.emplace(key, code);
          d.first_row.push_back(i);
          out_codes[i] = code;
        } else {
          out_codes[i] = it->second;
        }
      }
    }
  });

  // serial merge over the distinct entries only
  struct Entry {
    int64_t row;
    int32_t chunk;
    int32_t local;
  };
  std::vector<Entry> entries;
  size_t total = 0;
  for (const auto& d : dicts) total += d.first_row.size();
  entries.reserve(total);
  for (int64_t c = 0; c < chunks; ++c) {
    const auto& fr = dicts[static_cast<size_t>(c)].first_row;
    for (size_t l = 0; l < fr.size(); ++l) {
      entries.push_back({fr[l], static_cast<int32_t>(c),
                         static_cast<int32_t>(l)});
    }
  }
  std::sort(entries.begin(), entries.end(),
            [](const Entry& a, const Entry& b) { return a.row < b.row; });
  std::unordered_map<std::string_view, int32_t> global;
  global.reserve(entries.size());
  std::vector<std::vector<int32_t>> trans(static_cast<size_t>(chunks));
  for (int64_t c = 0; c < chunks; ++c) {
    trans[static_cast<size_t>(c)].resize(
        dicts[static_cast<size_t>(c)].first_row.size());
  }
  for (const Entry& en : entries) {
    const std::string_view key(buf + offsets[en.row],
                               static_cast<size_t>(offsets[en.row + 1] -
                                                   offsets[en.row]));
    auto it = global.find(key);
    int32_t gid;
    if (it == global.end()) {
      gid = static_cast<int32_t>(global.size());
      global.emplace(key, gid);
    } else {
      gid = it->second;
    }
    trans[static_cast<size_t>(en.chunk)][static_cast<size_t>(en.local)] =
        gid;
  }

  pool->ParallelFor(chunks, 1, [&](int64_t cb, int64_t ce) {
    for (int64_t c = cb; c < ce; ++c) {
      const auto& tr = trans[static_cast<size_t>(c)];
      const int64_t b = c * per;
      const int64_t e = std::min(n, b + per);
      for (int64_t i = b; i < e; ++i) {
        out_codes[i] = tr[static_cast<size_t>(out_codes[i])];
      }
    }
  });
  return static_cast<int64_t>(global.size());
}

}  // extern "C"
