// Native data-plane kernels: row <-> columnar packing hot loops.
//
// TPU-native analog of the reference's native tensor-buffer layer — the NIO
// pack/unpack fast paths in
// /root/reference/src/main/scala/org/tensorframes/impl/DataOps.scala:20-81
// (convertFast0 / convertBackFast0) and the per-type appendRaw loops in
// datatypes.scala:328-599, which the reference itself flags as its hot
// loops (TFDataOps.scala:30-32). Here the payload work is pure byte
// movement — padding ragged rows into dense device-feedable blocks and
// back — so one size-generic implementation covers every scalar dtype.
//
// All offsets/lengths are int64 (matches Arrow large-list offsets and numpy
// int64 index arrays). Buffers are caller-allocated; functions never
// allocate. Single-threaded by design: callers batch at the column level
// and the surrounding engine overlaps host packing with device compute.
//
// Build: g++ -O3 -shared -fPIC packer.cpp -o libtfspacker.so  (see
// tensorframes_tpu/data/packer.py, which builds on demand and falls back to
// numpy when no toolchain is present).

#include <cstring>
#include <cstdint>

extern "C" {

// Pack ragged rows (flat concatenated values + offsets, Arrow-style) into a
// dense [n_rows, max_len] padded matrix. pad_elem points at one element's
// byte pattern (NULL means zero fill).
void tfs_pad_ragged(const char* flat,
                    const int64_t* offsets,  // n_rows + 1 entries
                    int64_t n_rows,
                    int64_t max_len,
                    int64_t elem_size,
                    const char* pad_elem,
                    char* out) {
  const int64_t row_bytes = max_len * elem_size;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t len = offsets[i + 1] - offsets[i];
    char* dst = out + i * row_bytes;
    std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
    char* pad_dst = dst + len * elem_size;
    const int64_t pad_count = max_len - len;
    if (pad_count <= 0) continue;
    if (pad_elem == nullptr) {
      std::memset(pad_dst, 0, pad_count * elem_size);
    } else {
      for (int64_t j = 0; j < pad_count; ++j) {
        std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
      }
    }
  }
}

// Inverse of tfs_pad_ragged: copy the first lengths[i] elements of each
// padded row into a flat output buffer.
void tfs_unpad_ragged(const char* padded,
                      const int64_t* lengths,  // n_rows entries
                      int64_t n_rows,
                      int64_t max_len,
                      int64_t elem_size,
                      char* out_flat) {
  const int64_t row_bytes = max_len * elem_size;
  int64_t off = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t len = lengths[i];
    std::memcpy(out_flat + off * elem_size, padded + i * row_bytes,
                len * elem_size);
    off += len;
  }
}

// Gather fixed-width rows by index: out[k] = src[idx[k]]. The sort/shuffle
// step of keyed aggregation and shard re-layout.
void tfs_gather_rows(const char* src,
                     int64_t row_bytes,
                     const int64_t* idx,
                     int64_t n_idx,
                     char* out) {
  for (int64_t k = 0; k < n_idx; ++k) {
    std::memcpy(out + k * row_bytes, src + idx[k] * row_bytes, row_bytes);
  }
}

// Gather ragged rows by index into a dense padded matrix: the bucketing
// step of map_rows (rows of one shape bucket stacked for vmap).
void tfs_gather_ragged_pad(const char* flat,
                           const int64_t* offsets,
                           const int64_t* idx,
                           int64_t n_idx,
                           int64_t max_len,
                           int64_t elem_size,
                           const char* pad_elem,
                           char* out) {
  const int64_t row_bytes = max_len * elem_size;
  for (int64_t k = 0; k < n_idx; ++k) {
    const int64_t i = idx[k];
    const int64_t len = offsets[i + 1] - offsets[i];
    char* dst = out + k * row_bytes;
    std::memcpy(dst, flat + offsets[i] * elem_size, len * elem_size);
    const int64_t pad_count = max_len - len;
    if (pad_count <= 0) continue;
    char* pad_dst = dst + len * elem_size;
    if (pad_elem == nullptr) {
      std::memset(pad_dst, 0, pad_count * elem_size);
    } else {
      for (int64_t j = 0; j < pad_count; ++j) {
        std::memcpy(pad_dst + j * elem_size, pad_elem, elem_size);
      }
    }
  }
}

// Scatter fixed-width rows by index: out[idx[k]] = src[k]. Inverse of
// tfs_gather_rows; used to restore original row order after bucketed
// execution.
void tfs_scatter_rows(const char* src,
                      int64_t row_bytes,
                      const int64_t* idx,
                      int64_t n_idx,
                      char* out) {
  for (int64_t k = 0; k < n_idx; ++k) {
    std::memcpy(out + idx[k] * row_bytes, src + k * row_bytes, row_bytes);
  }
}

int64_t tfs_packer_abi_version() { return 2; }

}  // extern "C"
