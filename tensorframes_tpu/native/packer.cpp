// Native data-plane kernels: row <-> columnar packing hot loops.
//
// TPU-native analog of the reference's native tensor-buffer layer — the NIO
// pack/unpack fast paths in
// /root/reference/src/main/scala/org/tensorframes/impl/DataOps.scala:20-81
// (convertFast0 / convertBackFast0) and the per-type appendRaw loops in
// datatypes.scala:328-599, which the reference itself flags as its hot
// loops (TFDataOps.scala:30-32). Here the payload work is pure byte
// movement — padding ragged rows into dense device-feedable blocks and
// back — so one size-generic implementation covers every scalar dtype.
//
// All offsets/lengths are int64 (matches Arrow large-list offsets and numpy
// int64 index arrays). Buffers are caller-allocated; functions never
// allocate. Loop bodies live in kernels.h, shared with the thread-pool
// executor (executor.cpp) that runs them split across row ranges for
// large columns.
//
// Build: g++ -O3 -shared -fPIC -pthread packer.cpp executor.cpp -o
// libtfspacker.so (see tensorframes_tpu/data/packer.py, which builds on
// demand and falls back to numpy when no toolchain is present).

#include <cstdint>
#include <cstring>

#include "kernels.h"

extern "C" {

// Pack ragged rows (flat concatenated values + offsets, Arrow-style) into a
// dense [n_rows, max_len] padded matrix. pad_elem points at one element's
// byte pattern (NULL means zero fill).
void tfs_pad_ragged(const char* flat,
                    const int64_t* offsets,  // n_rows + 1 entries
                    int64_t n_rows,
                    int64_t max_len,
                    int64_t elem_size,
                    const char* pad_elem,
                    char* out) {
  tfs::PadRaggedRange(flat, offsets, 0, n_rows, max_len, elem_size,
                      pad_elem, out);
}

// Inverse of tfs_pad_ragged: copy the first lengths[i] elements of each
// padded row into a flat output buffer. (Output offsets depend on a
// running prefix sum, so this one stays sequential.)
void tfs_unpad_ragged(const char* padded,
                      const int64_t* lengths,  // n_rows entries
                      int64_t n_rows,
                      int64_t max_len,
                      int64_t elem_size,
                      char* out_flat) {
  const int64_t row_bytes = max_len * elem_size;
  int64_t off = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t len = lengths[i];
    std::memcpy(out_flat + off * elem_size, padded + i * row_bytes,
                len * elem_size);
    off += len;
  }
}

// Gather fixed-width rows by index: out[k] = src[idx[k]]. The sort/shuffle
// step of keyed aggregation and shard re-layout.
void tfs_gather_rows(const char* src,
                     int64_t row_bytes,
                     const int64_t* idx,
                     int64_t n_idx,
                     char* out) {
  tfs::GatherRowsRange(src, row_bytes, idx, 0, n_idx, out);
}

// Gather ragged rows by index into a dense padded matrix: the bucketing
// step of map_rows (rows of one shape bucket stacked for vmap).
void tfs_gather_ragged_pad(const char* flat,
                           const int64_t* offsets,
                           const int64_t* idx,
                           int64_t n_idx,
                           int64_t max_len,
                           int64_t elem_size,
                           const char* pad_elem,
                           char* out) {
  tfs::GatherRaggedPadRange(flat, offsets, idx, 0, n_idx, max_len,
                            elem_size, pad_elem, out);
}

// Scatter fixed-width rows by index: out[idx[k]] = src[k]. Inverse of
// tfs_gather_rows; used to restore original row order after bucketed
// execution. Duplicate indices are deterministic last-wins here (the
// parallel variant requires unique indices — see data/packer.py).
void tfs_scatter_rows(const char* src,
                      int64_t row_bytes,
                      const int64_t* idx,
                      int64_t n_idx,
                      char* out) {
  tfs::ScatterRowsRange(src, row_bytes, idx, 0, n_idx, out);
}

int64_t tfs_packer_abi_version() { return 3; }

}  // extern "C"
