"""Model / training-state checkpointing.

The reference has **no** trainable-state checkpointing at all — model state
lives inside the shipped graph as frozen constants (SURVEY §5,
``core.py:41-55``). Training on TPU makes this a first-class subsystem:
param pytrees (incl. sharded arrays) save/restore via Orbax, with a small
manager for step-numbered checkpoints and resume.
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "CheckpointManager",
    "run_checkpointed_loop",
]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, tree: Any) -> None:
    """Save a pytree (params/opt state) to ``path`` (a directory)."""
    ckpt = _checkpointer()
    ckpt.save(os.path.abspath(path), tree, force=True)
    ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree. ``template`` (a matching pytree of arrays or
    ShapeDtypeStructs, possibly sharded) guides dtypes/shardings; without it
    the stored structure is returned as saved."""
    import orbax.checkpoint as ocp

    ckpt = _checkpointer()
    if template is not None:
        import jax

        targets = jax.tree.map(
            lambda x: ocp.utils.to_shape_dtype_struct(x)
            if hasattr(x, "shape")
            else x,
            template,
        )
        return ckpt.restore(os.path.abspath(path), targets)
    return ckpt.restore(os.path.abspath(path))


def run_checkpointed_loop(
    step_fn,
    state: Any,
    steps: int,
    resume: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    on_step=None,
    place_restored=None,
):
    """The auto-resume training loop shared by every trainer
    (``ShardedSGDTrainer.fit``, ``TransformerLM`` fits): restore the
    latest step-numbered checkpoint from ``resume``, run
    ``step_fn(state) -> (state, loss)`` from there to ``steps``, saving
    every ``checkpoint_every`` steps and at the end. Returns
    ``(final_state, losses_for_the_steps_actually_run)``.

    ``on_step(step_number, loss)`` fires after each completed step (and
    after that step's checkpoint committed) — metrics hooks, and the
    failure-injection point for the process-death drill in
    ``tests/test_multihost.py``. ``place_restored(state) -> state``
    re-establishes device placement on a restored tree (orbax returns
    leaves COMMITTED to specific devices; sharded trainers must re-pin
    them to the mesh before the jitted step sees them).

    The reference delegated mid-job survival to Spark's task retry
    (SURVEY §5); checkpoint+resume is the TPU-native equivalent.
    """
    if checkpoint_every and resume is None:
        raise ValueError(
            "checkpoint_every requires a checkpoint directory: pass "
            "resume=<dir> (it is used for both writing and resuming)"
        )
    mgr = None
    start = 0
    if resume is not None:
        mgr = CheckpointManager(resume)
        ck_step, restored = mgr.restore_latest(template=state)
        if ck_step is not None:
            start, state = int(ck_step), restored
            if place_restored is not None:
                state = place_restored(state)
    losses = []
    try:
        for i in range(start, steps):
            state, loss = step_fn(state)
            losses.append(float(loss))
            done = i + 1
            if (
                mgr is not None
                and checkpoint_every
                and done % checkpoint_every == 0
            ):
                mgr.save(done, state)
            if on_step is not None:
                on_step(done, losses[-1])
        if mgr is not None and steps > start and mgr.latest_step() != steps:
            mgr.save(steps, state)
    finally:
        if mgr is not None:
            mgr.close()
    return state, losses


class CheckpointManager:
    """Step-numbered checkpoints with retention + resume.

    >>> mgr = CheckpointManager("/ckpts", max_to_keep=3)
    >>> mgr.save(step, params)
    >>> step, params = mgr.restore_latest(template=params)
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, tree: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, template: Optional[Any] = None):
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None, None
        if template is not None:
            import jax

            targets = jax.tree.map(
                lambda x: ocp.utils.to_shape_dtype_struct(x)
                if hasattr(x, "shape")
                else x,
                template,
            )
            tree = self._mgr.restore(
                step, args=ocp.args.StandardRestore(targets)
            )
        else:
            tree = self._mgr.restore(step)
        return step, tree

    def close(self):
        self._mgr.close()
