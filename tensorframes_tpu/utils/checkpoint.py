"""Model / training-state checkpointing.

The reference has **no** trainable-state checkpointing at all — model state
lives inside the shipped graph as frozen constants (SURVEY §5,
``core.py:41-55``). Training on TPU makes this a first-class subsystem:
param pytrees (incl. sharded arrays) save/restore via Orbax, with a small
manager for step-numbered checkpoints and resume.
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, tree: Any) -> None:
    """Save a pytree (params/opt state) to ``path`` (a directory)."""
    ckpt = _checkpointer()
    ckpt.save(os.path.abspath(path), tree, force=True)
    ckpt.wait_until_finished()


def restore_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree. ``template`` (a matching pytree of arrays or
    ShapeDtypeStructs, possibly sharded) guides dtypes/shardings; without it
    the stored structure is returned as saved."""
    import orbax.checkpoint as ocp

    ckpt = _checkpointer()
    if template is not None:
        import jax

        targets = jax.tree.map(
            lambda x: ocp.utils.to_shape_dtype_struct(x)
            if hasattr(x, "shape")
            else x,
            template,
        )
        return ckpt.restore(os.path.abspath(path), targets)
    return ckpt.restore(os.path.abspath(path))


class CheckpointManager:
    """Step-numbered checkpoints with retention + resume.

    >>> mgr = CheckpointManager("/ckpts", max_to_keep=3)
    >>> mgr.save(step, params)
    >>> step, params = mgr.restore_latest(template=params)
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, tree: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, template: Optional[Any] = None):
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None, None
        if template is not None:
            import jax

            targets = jax.tree.map(
                lambda x: ocp.utils.to_shape_dtype_struct(x)
                if hasattr(x, "shape")
                else x,
                template,
            )
            tree = self._mgr.restore(
                step, args=ocp.args.StandardRestore(targets)
            )
        else:
            tree = self._mgr.restore(step)
        return step, tree

    def close(self):
        self._mgr.close()
