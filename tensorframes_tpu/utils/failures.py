"""Failure handling: retry transient device errors, degrade on OOM.

The reference has no failure machinery of its own — it rides Spark's task
retry and lineage (SURVEY §5: "fully delegated to Spark"). There is no
Spark here, so the engine carries its own, sized to how a PJRT/TPU runtime
actually fails:

- **transient runtime errors** (preempted tunnel, UNAVAILABLE /
  DEADLINE_EXCEEDED from the PJRT client, dropped connection): the program
  and its inputs are still on the host or reproducible from it, so the
  dispatch is safe to retry with backoff — the same property Spark exploits
  (pure per-task functions, ``DebugRowOps.scala:766-803``).
- **RESOURCE_EXHAUSTED (HBM OOM)**: retrying identically cannot help; the
  caller must shrink the work. ``map_rows`` halves its bucket chunks
  (row programs are per-row independent, so splitting is semantics-free);
  block ops surface the error with a hint, since a block program may
  compute cross-row statistics and must see the whole partition.

Coverage note — jax dispatch is asynchronous, so a retry window only sees
errors raised before it returns. Ops that materialize results promptly
(``map_rows`` chunks, the reduces, the distributed programs) synchronize
*inside* their retry windows and get full coverage. ``map_blocks`` keeps
results device-resident to pipeline chained passes; there, only
dispatch-time failures are retried, and an error during async execution
surfaces at the first materialization point instead.

Everything here is policy-free mechanics; knobs live in
:class:`tensorframes_tpu.utils.config.Config`.
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Callable, Iterator, Optional, TypeVar

from .logging import get_logger

__all__ = [
    "adopt_retry_deadline",
    "current_retry_deadline",
    "first_line",
    "is_oom",
    "is_transient",
    "retry_deadline",
    "run_with_retries",
    "record_oom_split",
    "record_preemption",
    "seed_backoff_jitter",
    "DeadlineExceededError",
    "DeviceOOMError",
    "PagePoolExhausted",
    "QuarantinedBlocksError",
    "StaleLeaseError",
    "StaleRouterEpochError",
    "TenantThrottledError",
]

logger = get_logger("failures")

from ..obs import flight as _flight  # noqa: E402
from ..obs.metrics import counter as _counter  # noqa: E402

#: one series per (op, failure reason): makes flaky-link behavior (the
#: degraded-link rows in BENCH_ALL_r05.json) graphable instead of a stream
#: of warnings
_retries_total = _counter(
    "failures.retries_total",
    "Transient device-runtime failures retried, by op and reason",
    labels=("op", "reason"),
)
_retries_exhausted_total = _counter(
    "failures.retries_exhausted_total",
    "Transient failures that ran out of retry attempts",
    labels=("op",),
)
_oom_splits_total = _counter(
    "failures.oom_splits_total",
    "OOM-degrade work-unit splits (chunk halvings / cap lowerings), by op",
    labels=("op",),
)
_preemptions_total = _counter(
    "failures.preemptions_total",
    "Work units preempted and requeued on resource exhaustion, by op",
    labels=("op",),
)


def record_oom_split(op: str) -> None:
    """Count one OOM-degrade split. The splits themselves happen in the
    engine (``map_rows`` chunk halving, raised-chunk lowering); the counter
    lives here with the rest of the failure telemetry."""
    _oom_splits_total.inc(op=op)


def first_line(err: object, limit: int = 200) -> str:
    """First line of ``str(err)``, bounded — the log/label/flight-ring
    rendering of an exception. split, not splitlines: an exception
    classified off its CAUSE chain can have an empty ``str(e)``, and
    ``"".splitlines()`` is ``[]``."""
    return str(err).split("\n", 1)[0][:limit]


def record_preemption(op: str) -> None:
    """Count one preempt-and-requeue. Like :func:`record_oom_split`, the
    preemption itself happens at the resource owner (the serving
    scheduler evicting a sequence when its KV page pool runs dry); the
    counter lives here with the rest of the failure telemetry."""
    _preemptions_total.inc(op=op)
    _flight.record("preemptions", "preempt", op=op)

T = TypeVar("T")

#: status substrings that mark a dispatch worth retrying (PJRT surfaces
#: grpc-style statuses in the exception text). Matching is
#: case-insensitive — PJRT renders ``UNAVAILABLE``, grpc-python
#: ``unavailable``, wrappers anything in between — so every marker is
#: stored lowercase and compared against lowered exception text.
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "aborted",
    "connection reset",
    "connection refused",
    "socket closed",
)

_OOM_MARKERS = (
    "resource_exhausted",
    "out of memory",
)

#: "OOM" must match as a WORD: plain substring matching (the old
#: behavior) classified "zoom"/"room"/"Bloom filter" messages as device
#: OOMs once matching went case-insensitive
_OOM_WORD = re.compile(r"\boom\b")


def _exc_chain(e: BaseException) -> Iterator[BaseException]:
    """``e`` and its explicit causes (``raise X from Y``), cycle-safe.
    PJRT statuses often arrive wrapped — a retry decision must see
    through ``RuntimeError("dispatch failed") from <UNAVAILABLE>``.
    Implicit ``__context__`` links are deliberately NOT followed: an
    unrelated error raised while handling a transient one must not
    inherit its retryability."""
    seen = set()
    cur: "BaseException | None" = e
    while cur is not None and id(cur) not in seen and len(seen) < 8:
        seen.add(id(cur))
        yield cur
        cur = cur.__cause__


def _exc_text(e: BaseException) -> str:
    """Lowered text of the whole cause chain, for marker matching."""
    return "\n".join(str(x) for x in _exc_chain(e)).lower()


class DeviceOOMError(RuntimeError):
    """Device memory exhausted and the op cannot shrink its work unit."""


class PagePoolExhausted(DeviceOOMError):
    """The serving engine's KV page pool has no free page for a growing
    sequence. A RESOURCE_EXHAUSTED sibling, but of a pool this framework
    owns: retrying identically cannot help, and the remedy is not a
    split but an eviction — the scheduler preempts a running sequence
    (freeing its pages) and requeues it for recompute rather than
    crashing the batch (see :mod:`tensorframes_tpu.serve.scheduler`)."""


class QuarantinedBlocksError(RuntimeError):
    """A strict-mode batch job finished with quarantined blocks.

    Quarantine (``engine/jobs.py``) records a block whose program failed
    deterministically — non-transient, non-OOM after retries — in the
    job's quarantine manifest and skips it, so one poison block cannot
    kill a million-row job. In strict mode (``run_job(strict=True)`` or
    ``Config.quarantine_blocks=False``) the job still completes every
    healthy block and journals them, then raises this instead of
    returning partial results. ``blocks`` holds the
    :class:`~tensorframes_tpu.engine.jobs.QuarantinedBlock` records,
    each carrying the real underlying error."""

    def __init__(self, message: str, blocks=()):
        super().__init__(message)
        self.blocks = list(blocks)


class StaleLeaseError(RuntimeError):
    """An epoch-fenced write was rejected: the lease is not ours.

    Raised by the lease primitive (``utils/leases.py``) and both of its
    tenants — the distributed batch-job layer (``engine/dist_jobs.py``)
    and the serving fleet's member registry (``serve/membership.py``,
    where a fenced member's late registration write is the "zombie
    process" rejection) — in situations that share one meaning — *this
    process does not own the shared state it is about to mutate*:

    - a worker whose block lease expired and was **reclaimed** by
      another worker (epoch bumped) tries to record its late result:
      the write fence rejects the spool/ledger mutation, so a zombie
      can never land a torn or duplicate block record;
    - :func:`~tensorframes_tpu.engine.jobs.resume_job` is asked to
      touch a journal that live workers are still draining (or another
      resume holds the journal-level lease).

    Deliberately **non-transient**: retrying cannot help — the lease is
    gone (another worker owns the block now; its recompute is
    byte-identical) or the journal is owned by someone alive. The
    remedy is to move on to the next block / wait for the drain, never
    to retry the fenced write."""


class StaleRouterEpochError(StaleLeaseError):
    """A serving member rejected a placement carrying a superseded
    router epoch (``x-router-epoch`` below the router-election lease's
    current epoch, ``serve/router_ha.py``): the placing router was
    fenced and a standby took over at epoch+1, so this is a ZOMBIE
    router's placement — admitting it would double-generate a request
    the new active router already resubmitted from the WAL. A
    :class:`StaleLeaseError` sibling on purpose: same meaning (*this
    process does not own the shared state it is mutating*), same
    non-transient classification, and the fleet's failover path treats
    it as non-replayable — a fenced router retrying the same stale
    epoch elsewhere is refused everywhere. HTTP maps it to ``409
    Conflict`` (``interop/serving.py``)."""


class TenantThrottledError(RuntimeError):
    """A generation request was refused by the multi-tenant QoS plane
    (:mod:`tensorframes_tpu.serve.tenancy`): the tenant is over its
    admission quota, its token-bucket rate limit is empty, or an SLO
    shed is active for its priority class. A per-*tenant* condition,
    not a per-*server* one — the engine has capacity, this tenant may
    not use it right now — so HTTP maps it to ``429 Too Many
    Requests`` with a ``Retry-After`` derived from ``retry_after``
    (the bucket's refill time), distinct from the all-full 503.
    Deliberately terminal: never retried by ``run_with_retries`` and
    never replayed by the fleet router (a replay would re-charge the
    tenant's budget for work it was refused)."""

    def __init__(
        self, message: str, *, retry_after: float = 1.0,
        reason: str = "quota", tenant: str = "",
    ):
        super().__init__(message)
        #: seconds until the refusing limiter expects to admit again
        self.retry_after = float(retry_after)
        #: which gate refused: ``"quota"`` | ``"rate"`` | ``"shed"``
        self.reason = str(reason)
        self.tenant = str(tenant)


class DeadlineExceededError(TimeoutError):
    """A generation request outlived its caller-supplied deadline and was
    evicted by the serving scheduler (queued or mid-generation). A
    terminal, caller-facing condition — never retried (the deadline has
    already passed) and deliberately NOT classified transient, unlike a
    PJRT ``DEADLINE_EXCEEDED`` dispatch status, which marks a retryable
    device call. HTTP maps it to 504 (``interop/serving.py``)."""


def is_oom(e: BaseException) -> bool:
    if any(isinstance(x, DeviceOOMError) for x in _exc_chain(e)):
        return True
    s = _exc_text(e)
    return any(m in s for m in _OOM_MARKERS) or _OOM_WORD.search(s) is not None


def is_transient(e: BaseException) -> bool:
    # explicitly-terminal types veto the text markers anywhere in the
    # chain: a StaleLeaseError raised `from` an UNAVAILABLE cause must
    # not inherit that cause's retryability — the lease is gone
    if any(
        isinstance(
            x,
            (DeadlineExceededError, StaleLeaseError, TenantThrottledError),
        )
        for x in _exc_chain(e)
    ) or is_oom(e):
        return False
    s = _exc_text(e)
    return any(m in s for m in _TRANSIENT_MARKERS)


def _failure_reason(e: BaseException) -> str:
    """Short label for a classified failure: the matched status marker
    (normalized), or the exception type when no marker matched."""
    if is_oom(e):
        return "OOM"
    s = _exc_text(e)
    for m in _TRANSIENT_MARKERS:
        if m in s:
            return m.upper().replace(" ", "_")
    return type(e).__name__


def _op_label(what: str) -> str:
    """Bounded op label from a human ``what`` string: ``"map_blocks
    partition 3"`` must not mint one counter series per partition."""
    return what.split(" ", 1)[0] if what else "unknown"


#: RNG behind the retry backoff's full jitter. A dedicated instance (not
#: the global ``random``) so :func:`seed_backoff_jitter` can make chaos
#: tests deterministic without perturbing any other random consumer.
_jitter_rng = random.Random()


def seed_backoff_jitter(seed: Optional[int]) -> None:
    """Re-seed the retry-backoff jitter RNG. ``None`` restores
    OS-entropy seeding. Chaos tests call this so the (jittered) delay
    sequence is reproducible run to run."""
    global _jitter_rng
    _jitter_rng = random.Random(seed)


#: thread-local retry-deadline window (absolute time.monotonic() value):
#: :class:`retry_deadline` installs it so every ``run_with_retries``
#: window reached from the calling thread — however deep in the engine —
#: is bounded without threading a parameter through every call site
_retry_deadline_tl = threading.local()


class retry_deadline:
    """Bound every ``run_with_retries`` window entered from this thread
    to a wall-clock budget::

        with retry_deadline(lease_ttl_s * 0.8):
            ledger.run_block(i, compute)   # retries stop before the TTL

    The distributed-job worker wraps each block's compute in this so a
    retrying-but-alive lease holder gives up (and lets the job fail
    resumable / the block be retried next pass) *before* its lease
    deadline passes — otherwise a long transient burst would eat the
    whole TTL mid-retry, the worker would be presumed dead, and its
    block stolen while it still intended to write. Nests: the inner
    window is clipped to the outer one. ``None``/``<= 0`` is a no-op."""

    def __init__(self, seconds: Optional[float]):
        self._seconds = seconds
        self._prev: Optional[float] = None

    def __enter__(self) -> "retry_deadline":
        self._prev = getattr(_retry_deadline_tl, "deadline", None)
        if self._seconds is not None and self._seconds > 0:
            mine = time.monotonic() + self._seconds
            _retry_deadline_tl.deadline = (
                mine if self._prev is None else min(mine, self._prev)
            )
        return self

    def __exit__(self, *exc) -> None:
        _retry_deadline_tl.deadline = self._prev


def current_retry_deadline() -> Optional[float]:
    """The calling thread's absolute retry deadline (``time.monotonic``
    scale) installed by :class:`retry_deadline`, or ``None``. Layers
    that hand work to a thread pool capture this at submit time and
    re-install it in the pool thread with :class:`adopt_retry_deadline`
    — a thread-local does not cross executor boundaries on its own, and
    a retry window running unbounded on a pool thread would defeat the
    lease-TTL clipping the window exists for (``engine/dist_jobs.py``)."""
    return getattr(_retry_deadline_tl, "deadline", None)


class adopt_retry_deadline:
    """Install an ABSOLUTE deadline (from :func:`current_retry_deadline`)
    in this thread for the duration; clips to any window already
    present. ``None`` is a no-op."""

    def __init__(self, deadline: Optional[float]):
        self._deadline = deadline
        self._prev: Optional[float] = None

    def __enter__(self) -> "adopt_retry_deadline":
        self._prev = getattr(_retry_deadline_tl, "deadline", None)
        if self._deadline is not None:
            _retry_deadline_tl.deadline = (
                self._deadline
                if self._prev is None
                else min(self._deadline, self._prev)
            )
        return self

    def __exit__(self, *exc) -> None:
        _retry_deadline_tl.deadline = self._prev


def _effective_retry_deadline(
    deadline_s: Optional[float],
) -> Optional[float]:
    """Absolute monotonic deadline for one retry window: the explicit
    ``deadline_s`` argument and the thread-local :class:`retry_deadline`
    window, whichever ends first."""
    deadline = getattr(_retry_deadline_tl, "deadline", None)
    if deadline_s is not None and deadline_s > 0:
        mine = time.monotonic() + deadline_s
        deadline = mine if deadline is None else min(mine, deadline)
    return deadline


def _backoff_delay(attempt: int, base: float) -> float:
    """Full-jitter exponential backoff: uniform over
    ``(0.05 * cap, cap]`` where ``cap = base * 2**n``.

    The deterministic ``base * 2**n`` schedule retried *synchronized*
    failures in lockstep — every client that lost the same tunnel or TPU
    runtime slammed it again at the same instant, each round. Full
    jitter (the AWS-architecture result) decorrelates the herd while
    keeping the same cap per attempt. The floor is a sliver of the cap
    rather than 0 so a retry is never an immediate hot spin."""
    cap = base * (2.0 ** attempt)
    return _jitter_rng.uniform(0.05 * cap, cap)


def run_with_retries(
    fn: Callable[[], T],
    what: str = "device dispatch",
    deadline_s: Optional[float] = None,
) -> T:
    """Run ``fn``, retrying transient runtime failures with full-jitter
    exponential backoff per the config (``max_retries`` /
    ``retry_backoff_s``; see :func:`_backoff_delay`). Raises the last
    error when attempts run out; non-transient errors propagate
    immediately.

    ``deadline_s`` (and/or an enclosing :class:`retry_deadline` window —
    the tighter bound wins) caps the *wall clock* the retry loop may
    consume: a retry whose backoff sleep would land past the deadline is
    not attempted and the last transient error raises instead. The
    attempt in progress is never interrupted — this bounds the loop, not
    the dispatch."""
    from .config import get_config

    cfg = get_config()
    deadline = _effective_retry_deadline(deadline_s)
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            out_of_time = deadline is not None and (
                time.monotonic() >= deadline
            )
            if (
                not is_transient(e)
                or attempt >= cfg.max_retries
                or out_of_time
            ):
                if is_transient(e):
                    _retries_exhausted_total.inc(op=_op_label(what))
                    _flight.record(
                        "retries", "exhausted", what=what,
                        attempts=attempt + 1, error=first_line(e),
                    )
                    if out_of_time:
                        logger.warning(
                            "%s: retry deadline reached after %d "
                            "attempt(s); giving up on the transient error",
                            what, attempt + 1,
                        )
                raise
            delay = _backoff_delay(attempt, cfg.retry_backoff_s)
            if deadline is not None and time.monotonic() + delay >= deadline:
                _retries_exhausted_total.inc(op=_op_label(what))
                # this exhaustion must reach the flight ring too — a
                # bundle whose counters say "exhausted" but whose
                # retries ring shows none contradicts itself
                _flight.record(
                    "retries", "exhausted", what=what,
                    attempts=attempt + 1, reason="deadline",
                    error=first_line(e),
                )
                logger.warning(
                    "%s: backoff of %.2fs would pass the retry deadline; "
                    "giving up after %d attempt(s)",
                    what, delay, attempt + 1,
                )
                raise
            attempt += 1
            _retries_total.inc(op=_op_label(what), reason=_failure_reason(e))
            _flight.record(
                "retries", "retry", what=what, attempt=attempt,
                reason=_failure_reason(e), delay_s=round(delay, 4),
            )
            # split, not splitlines: an exception classified off its CAUSE
            # chain can have an empty str(e), and "".splitlines() is []
            logger.warning(
                "%s failed with a transient error (%s); retry %d/%d in %.1fs",
                what,
                str(e).split("\n", 1)[0][:200],
                attempt,
                cfg.max_retries,
                delay,
            )
            time.sleep(delay)
