"""Profiling & timing hooks.

The reference ships none (SURVEY §5: timing is manual prints in ``ignore``d
suites). Here the jax profiler is first-class: ``trace()`` captures a
Perfetto/TensorBoard-compatible device trace; ``Timer`` wraps wall-clock
sections with device synchronization so numbers mean what they say.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

__all__ = ["trace", "Timer", "block_until_ready"]


def block_until_ready(tree) -> None:
    """Synchronize: wait for every array in a pytree (async dispatch means
    wall-clock without this measures dispatch, not compute)."""
    import jax

    jax.block_until_ready(tree)


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
    """Capture a device+host trace viewable in Perfetto / TensorBoard::

        with tft.utils.profiling.trace("/tmp/trace"):
            df2.collect()

    While the capture is open, observability spans
    (:func:`tensorframes_tpu.obs.span`) forward to
    ``jax.profiler.TraceAnnotation`` and appear as named slices in the
    resulting trace; outside a capture that forwarding is skipped (it
    costs real microseconds per span with nobody listening). Direct
    ``jax.profiler.start_trace`` users can opt in with
    ``tft.obs.set_annotations(True)``.
    """
    import jax

    from ..obs.tracing import set_annotations

    try:
        jax.profiler.start_trace(log_dir, host_tracer_level=host_tracer_level)
    except TypeError:
        # newer jax moved tracer options off the start_trace signature
        jax.profiler.start_trace(log_dir)
    set_annotations(True)
    try:
        yield
    finally:
        set_annotations(False)
        jax.profiler.stop_trace()


class Timer:
    """Accumulating section timer with device sync.

    >>> t = Timer()
    >>> with t.section("score"):
    ...     out = engine_call()
    >>> t.report()

    ``publish=True`` additionally streams every section duration into the
    observability registry (``profiling.timer_seconds{section=...}``
    histogram, :mod:`tensorframes_tpu.obs`), so ad-hoc Timer numbers show
    up on the same scrape as the engine/serving metrics. The default
    stays registry-free — existing callers are unaffected.
    """

    def __init__(self, publish: bool = False):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self.mins: Dict[str, float] = {}
        self.maxs: Dict[str, float] = {}
        self._publish = publish

    @contextlib.contextmanager
    def section(self, name: str, sync=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            if name not in self.mins or dt < self.mins[name]:
                self.mins[name] = dt
            if name not in self.maxs or dt > self.maxs[name]:
                self.maxs[name] = dt
            if self._publish:
                _timer_seconds().observe(dt, section=name)

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        """Per-section stats as a plain (JSON-able) dict:
        ``{section: {"total_s", "count", "min_s", "max_s", "mean_s"}}``."""
        return {
            name: {
                "total_s": self.totals[name],
                "count": self.counts[name],
                "min_s": self.mins[name],
                "max_s": self.maxs[name],
                "mean_s": self.totals[name] / self.counts[name],
            }
            for name in self.totals
        }

    def report(self) -> str:
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            n = self.counts[name]
            tot = self.totals[name]
            lines.append(
                f"{name}: {tot * 1e3:.2f} ms total, {n} calls, "
                f"{tot / n * 1e3:.3f} ms/call"
            )
        return "\n".join(lines)


def _timer_seconds():
    """The shared ``Timer`` histogram (created on first publishing Timer —
    importing this module must not touch the registry)."""
    global _timer_hist
    if _timer_hist is None:
        from ..obs.metrics import histogram

        _timer_hist = histogram(
            "profiling.timer_seconds",
            "Timer section durations (seconds), by section",
            labels=("section",),
        )
    return _timer_hist


_timer_hist = None
