"""Profiling & timing hooks.

The reference ships none (SURVEY §5: timing is manual prints in ``ignore``d
suites). Here the jax profiler is first-class: ``trace()`` captures a
Perfetto/TensorBoard-compatible device trace; ``Timer`` wraps wall-clock
sections with device synchronization so numbers mean what they say.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

__all__ = ["trace", "Timer", "block_until_ready"]


def block_until_ready(tree) -> None:
    """Synchronize: wait for every array in a pytree (async dispatch means
    wall-clock without this measures dispatch, not compute)."""
    import jax

    jax.block_until_ready(tree)


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2):
    """Capture a device+host trace viewable in Perfetto / TensorBoard::

        with tft.utils.profiling.trace("/tmp/trace"):
            df2.collect()
    """
    import jax

    try:
        jax.profiler.start_trace(log_dir, host_tracer_level=host_tracer_level)
    except TypeError:
        # newer jax moved tracer options off the start_trace signature
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Accumulating section timer with device sync.

    >>> t = Timer()
    >>> with t.section("score"):
    ...     out = engine_call()
    >>> t.report()
    """

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def section(self, name: str, sync=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = []
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            n = self.counts[name]
            tot = self.totals[name]
            lines.append(
                f"{name}: {tot * 1e3:.2f} ms total, {n} calls, "
                f"{tot / n * 1e3:.3f} ms/call"
            )
        return "\n".join(lines)
