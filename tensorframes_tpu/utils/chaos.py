"""Deterministic fault injection: chaos testing for the failure machinery.

The reference delegated failure handling to Spark's task retry and
lineage (SURVEY §5) and therefore inherited Spark's chaos tooling too;
this engine carries its own failure taxonomy (``utils/failures.py``, the
serving supervisor in ``serve/engine.py``), so it needs its own way to
PROVE that machinery under fault — on CPU, in CI, with deterministic
seeds — instead of waiting for a real TPU runtime to misbehave.

Named **injection sites** sit on the host-side dispatch paths:

- ``engine.dispatch`` — inside every batch-engine retry window
  (``map_blocks`` partitions, ``map_rows`` chunks, ``reduce_blocks``)
- ``serve.prefill`` / ``serve.prefill_chunk`` / ``serve.decode_step``
  / ``serve.verify`` — the generation engine's compiled-step
  dispatches (inside their retry windows); ``serve.verify`` is the
  speculative-decoding batched multi-token check — a ``transient``
  there retries the whole verify span invisibly, streams stay
  byte-identical
- ``kv_pages.alloc`` — the KV page-pool allocator
- ``serving.conn`` — the scoring server's per-connection handler
- ``jobs.block`` — inside a durable batch job's per-block execution
  (``engine/jobs.py``): a ``fatal`` here is the poison-block /
  quarantine drill
- ``jobs.journal_write`` — inside the job journal's write path (npz
  spool + ledger append): a ``fatal`` here simulates a crash between
  computing a block and recording it (the kill-and-resume drill)
- ``jobs.lease`` — inside a distributed-job worker's lease
  claim/reclaim path (``engine/dist_jobs.py``): a ``transient`` retries
  the claim; a ``fatal`` is the worker-dies-while-claiming drill
- ``jobs.heartbeat`` — inside the lease heartbeat renewal: ``latency``
  past the lease TTL is the presumed-dead drill (the lease expires and
  another worker reclaims the block; the stalled owner's late write is
  then fence-rejected)
- ``frame.h2d`` / ``frame.d2h`` — inside every streaming-transfer
  chunk's retry window (``frame/transfer.py``): a ``transient`` here is
  the flaky-tunnel-during-ingest drill (one chunk retries; the column
  still lands byte-identical)
- ``fleet.place`` — inside the serving fleet's placement path
  (``serve/fleet.py``): a ``transient`` here retries invisibly; a
  ``fatal`` is the router-bug drill
- ``tier.handoff`` — inside a live KV-page migration's export read and
  import write retry windows (``serve/tiers.py``): a ``transient``
  retries the page transfer invisibly (reads are pure; the write
  re-sets the same rows); a ``fatal`` aborts the migration into the
  fallback ladder (failover replay / preemption) — the stream survives
  either way
- ``fleet.migrate`` — at the head of a fleet-level slot migration
  (``serve/fleet.py``: tier handoff drain and pool-pressure
  rebalance): a ``fatal`` is the migration-machinery-bug drill — the
  request must continue via replay/preemption with no duplicated or
  lost tokens
- ``fleet.replica_fault`` — polled once per replica per fleet watchdog
  tick: any raising kind KILLS the replica whose poll fired (device
  state scrambled, every attached handle failed — the hard-process-
  fault drill for failover/replay). Suffix the site with a replica
  name to target one: ``fleet.replica_fault.r1=fatal:every=8`` — this
  site composes such names at runtime, so its dotted suffixes (its
  FAMILY, see ``SITE_FAMILIES``) skip the unknown-site warning;
  suffixes on every other site warn like any typo.

A site is one call: ``chaos.site("serve.decode_step")``. When no
schedule is configured (the default) that compiles down to a single
module-global check — the same no-op-gate pattern as the ``TFT_OBS``
observability switch — so production paths pay one predicate and
nothing else, and the sites add **zero** compiled programs (they run on
the host, never inside a traced function).

A schedule is a spec string, via ``TFT_CHAOS`` in the environment or
``set_config(chaos=...)`` (the Config field wins when non-empty)::

    seed=42;serve.decode_step=transient:p=0.2;kv_pages.alloc=pool:every=7

``;``-separated entries; ``seed=N`` seeds the shared RNG (probability
schedules are deterministic given call order), every other entry is
``site=kind[:param=value]*``:

kinds
    ``transient``  raise a synthesized PJRT-style transient error
    (``UNAVAILABLE: ...`` — retried by ``run_with_retries``);
    ``oom``  raise :class:`~.failures.DeviceOOMError`
    (``RESOURCE_EXHAUSTED`` text);
    ``pool``  raise :class:`~.failures.PagePoolExhausted`
    (the scheduler's preempt-and-requeue cue);
    ``latency``  sleep instead of raising (watchdog / deadline fodder);
    ``fatal``  raise :class:`ChaosFault`, which deliberately matches
    NEITHER marker set — the fail-fast path.

params
    ``p=0.2``   fire with probability 0.2 (seeded RNG);
    ``every=7`` fire on every 7th call of this rule;
    ``times=3`` stop after 3 injections;
    ``ms=50``   latency duration (``latency`` kind only).

``p`` and ``every`` compose (the probability applies on the every-nth
calls); a rule with neither fires on every call. Every injection
increments ``chaos.injections_total{site,kind}`` and logs one warning.
See ``docs/fault_tolerance.md`` for the harness cookbook.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from .logging import get_logger

__all__ = [
    "ChaosFault",
    "SITES",
    "SITE_FAMILIES",
    "active_spec",
    "enabled",
    "scoped",
    "site",
]

logger = get_logger("chaos")

from ..obs.metrics import counter as _counter  # noqa: E402

_m_injections = _counter(
    "chaos.injections_total",
    "Faults injected by the chaos harness, by site and kind",
    labels=("site", "kind"),
)


class ChaosFault(RuntimeError):
    """A chaos-injected FATAL fault. Its text matches neither the
    transient nor the OOM markers, so classification routes it to the
    fail-fast path (fail every in-flight handle, mark unhealthy) —
    the one failure mode retry and degradation must NOT absorb."""


#: canonical sites wired into the engine; ``site()`` accepts any name
#: (unknown sites simply never fire), these are the ones that exist
SITES = (
    "engine.dispatch",
    "serve.prefill",
    "serve.prefill_chunk",
    "serve.decode_step",
    "serve.verify",
    "kv_pages.alloc",
    "serving.conn",
    "jobs.block",
    "jobs.journal_write",
    "jobs.lease",
    "jobs.heartbeat",
    "frame.h2d",
    "frame.d2h",
    "fleet.place",
    "fleet.replica_fault",
    "fleet.member_heartbeat",
    "fleet.registry",
    "fleet.router_wal",
    "fleet.router_heartbeat",
    "tier.handoff",
    "fleet.migrate",
    "tune.trial",
    "tenancy.admit",
)

#: sites whose code COMPOSES dotted suffixes at runtime (their FAMILY):
#: ``fleet.replica_fault.<name>`` targets one replica. Only these skip
#: the unknown-site warning for suffixed names — a suffix on any other
#: wired site (``serve.decode_step.typo=...``) is still a typo that
#: would silently never fire, and must warn
SITE_FAMILIES = ("fleet.replica_fault",)

_KINDS = ("transient", "oom", "pool", "latency", "fatal")


class _Rule:
    """One ``site=kind:params`` entry with its firing state."""

    __slots__ = ("site", "kind", "p", "every", "times", "latency_s",
                 "calls", "fired")

    def __init__(
        self,
        site: str,
        kind: str,
        p: Optional[float] = None,
        every: Optional[int] = None,
        times: Optional[int] = None,
        latency_s: float = 0.05,
    ):
        if kind not in _KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} for site {site!r}; "
                f"expected one of {_KINDS}"
            )
        if every is not None and every < 1:
            raise ValueError(f"chaos every= must be >= 1; got {every}")
        if p is not None and not 0.0 <= p <= 1.0:
            raise ValueError(f"chaos p= must be in [0, 1]; got {p}")
        self.site = site
        self.kind = kind
        self.p = p
        self.every = every
        self.times = times
        self.latency_s = latency_s
        self.calls = 0
        self.fired = 0

    def should_fire(self, rng: random.Random) -> bool:
        self.calls += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.every is not None and self.calls % self.every != 0:
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        self.fired += 1
        return True


def _parse(spec: str) -> Tuple[int, Dict[str, List[_Rule]]]:
    """Spec string -> (seed, rules by site). Raises ``ValueError`` on a
    malformed spec — a typo'd chaos schedule silently doing nothing
    would defeat the whole point of a deterministic harness."""
    seed = 0
    by_site: Dict[str, List[_Rule]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[len("seed="):])
            continue
        name, sep, rest = part.partition("=")
        name = name.strip()
        if not sep or not name or not rest:
            raise ValueError(
                f"malformed chaos entry {part!r}; expected "
                "'site=kind[:param=value]*' or 'seed=N'"
            )
        kind, *params = rest.split(":")
        kw: Dict[str, object] = {}
        for prm in params:
            k, psep, v = prm.partition("=")
            if not psep:
                raise ValueError(
                    f"malformed chaos param {prm!r} in {part!r}"
                )
            if k == "p":
                kw["p"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "ms":
                kw["latency_s"] = float(v) / 1e3
            else:
                raise ValueError(
                    f"unknown chaos param {k!r} in {part!r}; "
                    "expected p=, every=, times=, ms="
                )
        by_site.setdefault(name, []).append(_Rule(name, kind.strip(), **kw))
    return seed, by_site


#: environment spec, read once; the Config field (set_config(chaos=...))
#: takes precedence whenever it is non-empty
_ENV_SPEC = os.environ.get("TFT_CHAOS", "").strip()

_lock = threading.Lock()
_rules: Dict[str, List[_Rule]] = {}
_rng = random.Random(0)
_spec = ""

#: the hot-path gate — one module-global read when disabled, same
#: pattern as the TFT_OBS switch (obs/metrics.py)
_ON = False


def _refresh() -> None:
    from .config import get_config

    global _ON, _rules, _rng, _spec
    spec = get_config().chaos or _ENV_SPEC
    with _lock:
        if spec == _spec:
            # unrelated set_config: keep rule counters and RNG state so a
            # mid-run config touch cannot reset an every-nth schedule
            return
        seed, by_site = _parse(spec)
        for name in by_site:
            # dotted suffixes of a FAMILY site (SITE_FAMILIES — e.g. the
            # fleet's per-replica kills, fleet.replica_fault.r1) fire
            # because the code composes those names at runtime; suffixes
            # on any other site are typos and warn like unknown names
            if name not in SITES and not any(
                name.startswith(s + ".") for s in SITE_FAMILIES
            ):
                # not an error (tests inject at ad-hoc sites), but a
                # typo'd production schedule silently never firing would
                # defeat the harness — say so once at configure time
                logger.warning(
                    "chaos: site %r is not one of the wired injection "
                    "sites %s; its rules will never fire unless code "
                    "calls chaos.site(%r)",
                    name, SITES, name,
                )
        _rules = by_site
        _rng = random.Random(seed)
        _spec = spec
        _ON = bool(by_site)


from .config import register_on_change  # noqa: E402

register_on_change(_refresh)


def enabled() -> bool:
    """Whether any chaos schedule is active."""
    return _ON


def active_spec() -> str:
    """The spec string currently installed ("" when disabled)."""
    return _spec


def site(name: str) -> None:
    """A chaos injection point. No-op (one module-global check) unless a
    schedule names this site; otherwise may raise a synthesized failure
    or inject latency per the schedule."""
    if not _ON:
        return
    _fire(name)


def _fire(name: str) -> None:
    with _lock:
        todo = [r for r in _rules.get(name, ()) if r.should_fire(_rng)]
    for r in todo:
        _m_injections.inc(site=name, kind=r.kind)
        from ..obs import flight as _flight

        _flight.record("chaos", r.kind, site=name)
        logger.warning("chaos: injecting %s at %s", r.kind, name)
        if r.kind == "latency":
            time.sleep(r.latency_s)
        elif r.kind == "transient":
            raise RuntimeError(
                f"UNAVAILABLE: chaos-injected transient fault at {name}"
            )
        elif r.kind == "oom":
            from .failures import DeviceOOMError

            raise DeviceOOMError(
                f"RESOURCE_EXHAUSTED: chaos-injected device OOM at {name}"
            )
        elif r.kind == "pool":
            from .failures import PagePoolExhausted

            raise PagePoolExhausted(
                f"chaos-injected page-pool exhaustion at {name}"
            )
        else:  # fatal
            raise ChaosFault(f"chaos-injected fatal fault at {name}")


class scoped:
    """Context manager installing a chaos spec for a test block::

        with chaos.scoped("seed=1;serve.decode_step=transient:every=2"):
            ...

    Installs via ``set_config(chaos=...)`` (so the gate refresh runs) and
    restores the previous spec on exit."""

    def __init__(self, spec: str):
        self._new = spec
        self._prev: Optional[str] = None

    def __enter__(self) -> "scoped":
        from .config import get_config, set_config

        self._prev = get_config().chaos
        set_config(chaos=self._new)
        return self

    def __exit__(self, *exc) -> None:
        from .config import set_config

        set_config(chaos=self._prev or "")
