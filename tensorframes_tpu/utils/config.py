"""Precision / device configuration.

The reference has no config system at all (SURVEY §5: per-op configuration is
the ``ShapeDescription`` hint object; the UDAF buffer size is a hard-coded
``10``, ``DebugRowOps.scala:573``). Knobs live here only once something
consumes them.
"""

from __future__ import annotations

import dataclasses
import os
import threading

__all__ = [
    "Config",
    "get_config",
    "set_config",
    "ensure_x64",
    "enable_compilation_cache",
]


@dataclasses.dataclass(frozen=True)
class Config:
    #: columns whose host size exceeds this are fed to the engine one
    #: partition block at a time instead of being memoized whole on device —
    #: bounds HBM use for frames larger than device memory
    #: (consumed by engine/ops.py and parallel/distributed.py).
    device_cache_bytes: int = 4 << 30
    #: upper bound on rows per vmapped device call in ``map_rows`` shape
    #: buckets; a bucket larger than this executes in chunks so activation
    #: memory stays bounded (conv/attention programs can blow up HBM far
    #: beyond the input bytes). Consumed by engine/ops.py.
    max_rows_per_device_call: int = 8192
    #: the device-resident ``map_rows`` fast path may RAISE its chunk above
    #: ``max_rows_per_device_call`` until a chunk's input+output bytes
    #: reach this bound — tiny rows (scalars, small vectors) dispatch in a
    #: few large calls instead of hundreds of row-capped ones (each
    #: dispatch costs link latency; an OOM on a raised chunk halves it
    #: back toward the row cap without leaving the device-resident path).
    #: Consumed by engine/ops.py.
    max_bytes_per_device_call: int = 64 << 20
    #: chunk size for the streaming host↔device transfer layer
    #: (``frame/transfer.py``): column-sized payloads cross the link as
    #: row chunks of at most this many bytes, several in flight at once,
    #: so consumers overlap compute with the chunks still in the air.
    #: ``<= 0`` restores the monolithic single-``device_put`` path
    #: (still retried and counted). See docs/ingest.md for tuning.
    transfer_chunk_bytes: int = 64 << 20
    #: width of the transfer thread pool: how many chunks are in flight
    #: concurrently, per direction. A single stream cannot fill a
    #: high-latency link; more streams pipeline against each other until
    #: the link saturates (guidance in docs/ingest.md).
    transfer_streams: int = 4
    #: optional WIRE cast for float32 payloads: ``"bf16"`` crosses the
    #: link as bfloat16 (half the tunnel bytes) and upcasts back to
    #: float32 on device — schemas, programs, and device dtypes are
    #: untouched, only the values round to bf16 precision (the accuracy
    #: trade the bf16 bench mode measures; see docs/ingest.md caveats).
    #: ``""`` (default) transfers verbatim — the byte-identity mode.
    transfer_dtype: str = ""
    #: retries for transient device-runtime failures (UNAVAILABLE /
    #: DEADLINE_EXCEEDED / dropped tunnel); see utils/failures.py. The
    #: reference rode Spark's task retry instead (SURVEY §5).
    max_retries: int = 2
    #: base of the exponential retry backoff, seconds.
    retry_backoff_s: float = 0.5
    #: master switch for the observability layer (``tensorframes_tpu.obs``):
    #: False makes every counter increment, histogram observation, and
    #: span a no-op. ``TFT_OBS=0`` in the environment forces the same off
    #: state regardless of this field (read once at import).
    observability: bool = True
    #: cadence of the time-series sampler (``obs/timeseries.py``): while
    #: the sampler is running (a live ``ScoringServer`` holds it, or
    #: ``obs.timeseries.acquire_sampler()``), every registered gauge,
    #: counter-derived rate, and histogram p50/p99 is snapshotted into
    #: the in-process ring-buffer store — and ``GET /varz`` / the SLO
    #: monitors read from it — once per this many seconds. ``<= 0``
    #: parks the sampler (the store only moves via explicit
    #: ``sample_once()`` calls). Re-read every tick, so retunes apply
    #: without a restart.
    obs_sample_interval_s: float = 1.0
    #: how long synchronous consumers of a generation handle wait before
    #: declaring the stream lost: ``GenerationEngine.generate`` and the
    #: HTTP ``POST /generate`` endpoint both call
    #: ``handle.result(timeout=this)``. With the serving supervisor a
    #: doomed stream is failed within a step, so this is a last-resort
    #: backstop, not the primary failure path (docs/serving_llm.md).
    serve_result_timeout_s: float = 300.0
    #: decode-step paged-attention implementation for the serving engine
    #: (``serve/engine.py``): ``"gather"`` — the reference formulation
    #: (materialized page gather + one-shot softmax, ``ops.paged_attention``)
    #: — or ``"fused"`` — the Pallas ragged paged-attention kernel
    #: (``ops.ragged_paged_attention``: in-kernel page-table walk,
    #: compute scales with live tokens). Per-engine override:
    #: ``GenerationEngine(attention_impl=...)``. The two agree to float
    #: tolerance; gather stays the default because it is the oracle.
    serve_attention_impl: str = "gather"
    #: chunked prefill: prompts longer than this many tokens prefill in
    #: fixed chunks of this size, one chunk per engine step, interleaved
    #: with decode steps — bounding the stall one long prompt imposes on
    #: the whole decode batch. ``0`` (default) prefills every prompt in
    #: one pass. Per-engine override:
    #: ``GenerationEngine(prefill_chunk_tokens=...)``.
    serve_prefill_chunk_tokens: int = 0
    #: shared-prefix KV caching (``serve/kv_pages.py:PrefixCache``):
    #: finished prefills register their prompt's complete pages, and new
    #: requests with an identical page-aligned prefix share those pages
    #: (refcounted, copy-on-write on in-page divergence) and skip
    #: prefilling the shared span. Per-engine override:
    #: ``GenerationEngine(prefix_cache=...)``.
    serve_prefix_cache: bool = False
    #: fault-injection (chaos) schedule spec, e.g.
    #: ``"seed=7;serve.decode_step=transient:p=0.2;kv_pages.alloc=pool:every=9"``.
    #: Empty (the default) disables every injection site down to a single
    #: module-global check; the ``TFT_CHAOS`` environment variable
    #: supplies the spec when this field is empty. Grammar and site list:
    #: ``utils/chaos.py`` and docs/fault_tolerance.md.
    chaos: str = ""
    #: root directory for durable batch-job journals
    #: (``engine/jobs.py``). Empty means ``$TFT_JOB_DIR`` or
    #: ``~/.cache/tensorframes_tpu/jobs``; each job gets its own
    #: subdirectory named by its job id.
    job_dir: str = ""
    #: whether :func:`tensorframes_tpu.engine.jobs.run_job` journals by
    #: default. ``run_job(..., journal=False)`` (or this field False)
    #: keeps the job's block loop and quarantine semantics but writes
    #: nothing to disk — the overhead-comparison / test mode.
    journal_batch_jobs: bool = True
    #: distributed batch jobs (``engine/dist_jobs.py``): how long a
    #: worker's block lease stays valid without a heartbeat renewal.
    #: The liveness-vs-safety knob — a crashed worker's blocks are
    #: reclaimable only after this long, but a *live* worker whose
    #: heartbeats stall longer than this is presumed dead and its block
    #: stolen (the late write is then fence-rejected). Must comfortably
    #: exceed worst-case heartbeat jitter + filesystem latency + clock
    #: skew between workers. Per-worker override: ``run_worker(lease_ttl_s=)``.
    job_lease_ttl_s: float = 30.0
    #: heartbeat renewal interval for held leases. ``0`` (default)
    #: means ``job_lease_ttl_s / 3`` — three chances to renew before
    #: expiry. Per-worker override: ``run_worker(heartbeat_s=)``.
    job_heartbeat_s: float = 0.0
    #: serving-fleet membership lease TTL (``serve/membership.py``): a
    #: member whose registry heartbeats stall longer than this is
    #: presumed dead, fenced by the router (epoch tombstone — its late
    #: registry writes raise ``StaleLeaseError``), and its in-flight
    #: streams are replayed on survivors. Shorter than the job TTL:
    #: serving failover is latency-sensitive where batch reclamation is
    #: not. Per-member override: ``MemberRegistry(ttl_s=)``.
    member_lease_ttl_s: float = 10.0
    #: membership heartbeat renewal interval. ``0`` (default) means
    #: ``member_lease_ttl_s / 3``. Per-member override:
    #: ``MemberRegistry(heartbeat_s=)``.
    member_heartbeat_s: float = 0.0
    #: directory for the flight recorder's debug bundles
    #: (``obs/flight.py``: the JSON dumped on an engine fatal,
    #: ``restart()``, block quarantine, or write-fence reject). Empty
    #: means ``$TFT_DEBUG_DIR`` or ``~/.cache/tensorframes_tpu/debug``.
    debug_bundle_dir: str = ""
    #: default quarantine policy for batch jobs: True returns partial
    #: results (``JobResult.completed`` + ``.quarantined``) when a block
    #: fails deterministically; False (strict) raises
    #: ``QuarantinedBlocksError`` at job end instead. Per-job override:
    #: ``run_job(..., strict=)``.
    quarantine_blocks: bool = True
    #: master switch for the lazy logical-plan layer (``engine/plan.py``):
    #: chained frame ops record plan nodes and are optimized once, then
    #: lowered to the ordinary dispatch when a fetch forces them. False
    #: restores strict op-at-a-time execution everywhere (the rewrite
    #: passes below are then moot). See docs/pipelines.md.
    plan_lazy_ops: bool = True
    #: plan rewrite pass 1 — **map fusion**: a chain of ``map_rows`` /
    #: ``map_blocks`` ops collapses into one jitted composite body, so N
    #: chained maps cost one compiled program and one pass over the data.
    plan_fuse_maps: bool = True
    #: plan rewrite pass 2 — **column pruning**: ops none of whose fetches
    #: are demanded downstream (by a ``select`` / ``reduce_blocks`` /
    #: ``aggregate`` consumer) are dropped from the plan, so the source
    #: columns only they bound never cross the host→device link.
    plan_prune_columns: bool = True
    #: plan rewrite pass 3 — **reduction hoisting**: a ``reduce_blocks``
    #: over a pending map chain folds into the map program's per-block
    #: epilogue — one program computes map outputs AND the block partial;
    #: partials still merge through the reduce's own ``[2, ...]`` program.
    plan_hoist_reduce: bool = True
    #: master switch for the self-tuning performance layer
    #: (``tensorframes_tpu.tune``): False makes every tuned surface
    #: (attention tiles, transfer chunk/streams, serve page size +
    #: prefill chunk, map-rows block-row budget) fall straight back to
    #: its static default. ``TFT_TUNE=0`` in the environment forces the
    #: same off state regardless of this field (checked live — the
    #: bench-regression gate pins it). See docs/tuning.md.
    autotune: bool = True
    #: tuning mode when ``autotune`` is on: ``"cached"`` (default)
    #: serves winners from the persisted tuning store but never runs a
    #: measurement trial; ``"online"`` additionally micro-benchmarks the
    #: candidate grid on first sight of an unseen signature and installs
    #: + persists the winner; ``"off"`` equals ``autotune=False``.
    tune_mode: str = "cached"
    #: wall-clock budget for one signature's online tuning pass,
    #: seconds: candidates are measured in predicted-cost order until
    #: the budget runs out, and the winner is picked among whatever was
    #: measured (the static default is always measured first, so a
    #: budget too small for the grid degrades to "keep the default").
    tune_budget_s: float = 2.0
    #: timed repeats per measured candidate (the winner is the
    #: median-wall candidate; one untimed warmup per candidate pays any
    #: compile cost outside the measurement).
    tune_trials: int = 3
    #: cap on candidates measured per signature AFTER the learned cost
    #: model ranks the grid — measured trials cover only the top-K
    #: predicted configs, and never more than half the full grid.
    tune_top_k: int = 4
    #: path of the persisted tuning store (JSONL). Empty means
    #: ``$TFT_TUNE_FILE``, else ``tune.jsonl`` next to the XLA
    #: persistent compile cache directory (the same
    #: ``~/.cache/tensorframes_tpu`` trajectory home).
    tune_file: str = ""
    #: shared directory for the fleet telemetry plane
    #: (``obs/export.py``): every process with a live sampler snapshots
    #: its metric registry + time-series store to
    #: ``<dir>/<proc-id>.json`` (atomic rename), and the read side
    #: (``obs/aggregate.py``, ``GET /varz?scope=fleet``) merges whatever
    #: snapshots it finds there. Empty means ``$TFT_TELEMETRY_DIR``;
    #: empty both ways disables export entirely.
    telemetry_dir: str = ""
    #: minimum seconds between telemetry snapshot writes. The exporter
    #: rides the time-series sampler tick, so the effective cadence is
    #: ``max(obs_sample_interval_s, this)``. Re-read every tick.
    obs_export_interval_s: float = 2.0
    #: a telemetry snapshot whose file mtime is older than this many
    #: seconds marks its process ``stale`` in every merged fleet view —
    #: flagged, never dropped, so a kill -9'd worker's last counters
    #: stay visible (docs/observability.md "Fleet telemetry").
    telemetry_stale_after_s: float = 15.0
    #: per-tenant QoS policies (``serve/tenancy.py``): a tuple of plain
    #: dicts, one per tenant, each shaped like ``{"tenant": "acme",
    #: "priority": "batch"|"standard"|"interactive", "max_active": N,
    #: "max_queued": N, "requests_per_s": R, "tokens_per_s": T,
    #: "ttft_slo_s": S}`` — every field but ``tenant`` optional, 0/absent
    #: = unlimited/none. The EMPTY default means the whole QoS plane is
    #: off: no admission checks, FIFO scheduling, preempt-youngest —
    #: byte-identical to the pre-tenancy engine at zero per-step cost
    #: (the on/off gate is a module global refreshed by the set_config
    #: callback hook, the TFT_OBS/chaos pattern). Also settable at
    #: runtime via ``POST /admin/tenants``. See docs/serving_llm.md
    #: "Multi-tenancy".
    tenants: tuple = ()
    #: master switch for the router's durable request plane
    #: (``serve/router_ha.py``): the per-request WAL, request_id
    #: dedupe/stream resume on ``POST /generate``, and standby
    #: takeover resubmission. The FALSE default means the whole plane
    #: is off — no WAL writes, no per-request tracker, streams
    #: byte-identical to the pre-WAL serving path at zero per-token
    #: cost (the on/off gate is a module global refreshed by the
    #: set_config callback hook, the tenancy/chaos pattern). See
    #: docs/fault_tolerance.md "Router HA".
    router_wal: bool = False
    #: TTL of the router-election lease (``serve/router_ha.py``): a
    #: standby detects active-router death after at most this long and
    #: takes over at epoch+1. Shorter than the member TTL — router
    #: takeover is on the client-visible path where member fencing
    #: already hides behind stream replay. Per-router override:
    #: ``RouterHA(ttl_s=)``.
    router_lease_ttl_s: float = 3.0
    #: first-token tier handoff (``serve/tiers.py`` +
    #: ``serve/fleet.py``): in a fleet with prefill/decode tier labels,
    #: a request prefills on prefill capacity and its KV pages migrate
    #: to a decode replica once the first token is out. False keeps
    #: tier labels as a routing preference only (streams stay where
    #: they prefilled). Irrelevant when every replica is ``mixed``.
    tier_handoff: bool = True
    #: pool-pressure rebalancing: before the scheduler preempts a
    #: victim for pages, the fleet tries migrating the victim's KV
    #: pages to the least-loaded decode-capable replica instead
    #: (``Scheduler.on_pressure``). False restores pure
    #: preempt-youngest. Preemption always remains the fallback.
    tier_rebalance: bool = True


_lock = threading.Lock()
_config = Config()

#: callbacks run after every set_config — lets hot paths cache derived
#: flags (e.g. the observability on/off gate) as plain module globals
#: instead of re-deriving them per call
_on_change: list = []


def register_on_change(cb) -> None:
    """Run ``cb()`` now and after every future :func:`set_config`."""
    _on_change.append(cb)
    cb()


def get_config() -> Config:
    return _config


def set_config(**kwargs) -> Config:
    global _config
    with _lock:
        _config = dataclasses.replace(_config, **kwargs)
    for cb in _on_change:
        cb()
    return _config


_cache_enabled_dir: "str | None" = None


def enable_compilation_cache(
    path: "str | None" = None,
    *,
    min_compile_time_secs: float = 0.1,
    min_entry_size_bytes: int = -1,
) -> "str | None":
    """Point XLA's persistent compilation cache at a disk directory.

    The reference pays zero compile cost — a TF 1.x session executes its
    GraphDef immediately (``TensorFlowOps.scala:76-95``) — while every
    fresh JAX process re-traces and re-compiles each program from scratch
    (~100 s of warmup on the headline bench). With this cache enabled,
    compiles are keyed on (HLO, compile options, backend) and serialized
    executables are reloaded by later processes, so a fresh process pays
    only deserialization (<1 s per program) instead of compilation.

    Called automatically on ``import tensorframes_tpu`` (opt out with
    ``TFT_NO_COMPILE_CACHE=1``). Idempotent; returns the cache dir in use,
    or ``None`` when disabled. Precedence for the directory:

    1. explicit ``path`` argument
    2. ``TFT_COMPILE_CACHE_DIR`` environment variable
    3. ``JAX_COMPILATION_CACHE_DIR`` (jax's own knob — left untouched)
    4. ``~/.cache/tensorframes_tpu/xla-cache``

    ``min_compile_time_secs`` (default 0.1 s, vs jax's 1.0 s) caches even
    small programs: engine passes dispatch many sub-second-compile thunks
    (fold programs, vmap buckets) whose re-compiles dominate short-job
    warmup. ``min_entry_size_bytes=-1`` removes the size floor for the
    same reason. Entries are content-addressed, so a shared directory is
    safe across concurrent processes.
    """
    global _cache_enabled_dir
    if os.environ.get("TFT_NO_COMPILE_CACHE", "") not in ("", "0"):
        return None
    with _lock:
        if _cache_enabled_dir is not None:
            return _cache_enabled_dir
        import jax

        if path is None:
            path = os.environ.get("TFT_COMPILE_CACHE_DIR")
        if path is None and os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            # the user already configured jax directly; respect it
            _cache_enabled_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
            return _cache_enabled_dir
        if path is None:
            path = os.path.join(
                os.path.expanduser("~"), ".cache", "tensorframes_tpu",
                "xla-cache",
            )
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:  # read-only HOME (hermetic CI): run uncached
            return None
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            min_compile_time_secs,
        )
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            min_entry_size_bytes,
        )
        _cache_enabled_dir = path
        return path


_x64_done = False


def ensure_x64() -> None:
    """Enable jax 64-bit types on demand.

    The reference's parity dtype set includes float64/int64
    (``datatypes.scala:265-267``) and its README examples round-trip doubles;
    JAX disables x64 by default, so the engine flips it lazily the first time
    a 64-bit column reaches a device computation."""
    global _x64_done
    if _x64_done:
        return
    with _lock:
        if not _x64_done:
            import jax

            jax.config.update("jax_enable_x64", True)
            _x64_done = True
