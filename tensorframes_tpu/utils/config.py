"""Precision / device configuration.

The reference has no config system at all (SURVEY §5: per-op configuration is
the ``ShapeDescription`` hint object; the UDAF buffer size is a hard-coded
``10``, ``DebugRowOps.scala:573``). Knobs live here only once something
consumes them.
"""

from __future__ import annotations

import dataclasses
import threading

__all__ = ["Config", "get_config", "set_config", "ensure_x64"]


@dataclasses.dataclass(frozen=True)
class Config:
    #: columns whose host size exceeds this are fed to the engine one
    #: partition block at a time instead of being memoized whole on device —
    #: bounds HBM use for frames larger than device memory
    #: (consumed by engine/ops.py and parallel/distributed.py).
    device_cache_bytes: int = 4 << 30
    #: upper bound on rows per vmapped device call in ``map_rows`` shape
    #: buckets; a bucket larger than this executes in chunks so activation
    #: memory stays bounded (conv/attention programs can blow up HBM far
    #: beyond the input bytes). Consumed by engine/ops.py.
    max_rows_per_device_call: int = 8192
    #: the device-resident ``map_rows`` fast path may RAISE its chunk above
    #: ``max_rows_per_device_call`` until a chunk's input+output bytes
    #: reach this bound — tiny rows (scalars, small vectors) dispatch in a
    #: few large calls instead of hundreds of row-capped ones (each
    #: dispatch costs link latency; an OOM on a raised chunk halves it
    #: back toward the row cap without leaving the device-resident path).
    #: Consumed by engine/ops.py.
    max_bytes_per_device_call: int = 64 << 20
    #: retries for transient device-runtime failures (UNAVAILABLE /
    #: DEADLINE_EXCEEDED / dropped tunnel); see utils/failures.py. The
    #: reference rode Spark's task retry instead (SURVEY §5).
    max_retries: int = 2
    #: base of the exponential retry backoff, seconds.
    retry_backoff_s: float = 0.5


_lock = threading.Lock()
_config = Config()


def get_config() -> Config:
    return _config


def set_config(**kwargs) -> Config:
    global _config
    with _lock:
        _config = dataclasses.replace(_config, **kwargs)
    return _config


_x64_done = False


def ensure_x64() -> None:
    """Enable jax 64-bit types on demand.

    The reference's parity dtype set includes float64/int64
    (``datatypes.scala:265-267``) and its README examples round-trip doubles;
    JAX disables x64 by default, so the engine flips it lazily the first time
    a 64-bit column reaches a device computation."""
    global _x64_done
    if _x64_done:
        return
    with _lock:
        if not _x64_done:
            import jax

            jax.config.update("jax_enable_x64", True)
            _x64_done = True
