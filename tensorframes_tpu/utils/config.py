"""Precision / device configuration helpers.

The reference has no config system at all (SURVEY §5: per-op configuration is
the ``ShapeDescription`` hint object; the UDAF buffer size is a hard-coded
``10``, ``DebugRowOps.scala:573``). Engine knobs will be added here as they
gain consumers; today the only global switch is 64-bit precision.
"""

from __future__ import annotations

import threading

__all__ = ["ensure_x64"]

_lock = threading.Lock()
_x64_done = False


def ensure_x64() -> None:
    """Enable jax 64-bit types on demand.

    The reference's parity dtype set includes float64/int64
    (``datatypes.scala:265-267``) and its README examples round-trip doubles;
    JAX disables x64 by default, so the engine flips it lazily the first time
    a 64-bit column reaches a device computation."""
    global _x64_done
    if _x64_done:
        return
    with _lock:
        if not _x64_done:
            import jax

            jax.config.update("jax_enable_x64", True)
            _x64_done = True
