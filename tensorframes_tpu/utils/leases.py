"""Epoch-stamped filesystem leases: the reusable coordination primitive.

``engine/dist_jobs.py`` introduced the trick — a lease for key ``k`` at
epoch ``e`` is the file ``<key>.e{epoch:06d}.lease``, created by
hard-linking a fully written temp file, so claiming any (key, epoch)
pair is atomic create-if-absent with exactly one winner and **no lock
server**: the directory is the membership table, the epoch in the
filename is the monotonic fencing token, and "current lease" is simply
the key's highest-epoch file. PR 18 needs the same machinery for a
second tenant — the serving fleet's member registry
(:mod:`tensorframes_tpu.serve.membership`) — so the mechanics live
here as :class:`LeaseStore` and both planes subclass it rather than
duplicating 300 lines of carefully ordered filesystem races:

- **atomic claim** (:meth:`LeaseStore.acquire`) — exclusive create of
  the next epoch file; reclaiming an expired lease is an exclusive
  race for ``epoch + 1``.
- **heartbeats** — a daemon thread rewrites every held lease with a
  fresh deadline every ``heartbeat_s`` (default ``ttl / 3``); each
  renewal *re-validates ownership first* (the current file must still
  carry our worker + epoch), because a blind ``os.replace`` would
  re-create a superseded file a reclaimer already unlinked — a phantom
  stale lease renewed forever. A lease found stolen is dropped and
  reported through the ``on_lost`` hook (how a fenced serving member
  learns it has been presumed dead).
- **write fencing** (:meth:`LeaseStore.publish`) — every mutation of a
  held lease re-validates ownership immediately before the rewrite and
  raises :class:`~tensorframes_tpu.utils.failures.StaleLeaseError`
  when superseded: a zombie process that wakes after its lease was
  stolen cannot silently re-assert itself.
- **tombstones** (:meth:`LeaseStore.steal`) — a third party fences a
  presumed-dead owner by winning the ``epoch + 1`` race with a
  terminal state (``"fenced"``/``"done"``), exactly the dist-jobs
  reclaim but with a marker instead of a recompute.

Payloads are JSON — ``{worker, epoch, state, deadline_unix,
written_unix}`` plus an optional free-form ``meta`` dict (how a
serving member advertises its URL and model shape). Liveness vs
safety: ``deadline_unix`` compares against the *local* clock, so the
TTL must comfortably exceed heartbeat jitter + filesystem latency +
inter-host clock skew.

Subclass policy lives with the subclass: :class:`LeaseManager` keeps
the journal/block handshake, job metrics and ``jobs.*`` chaos sites;
the member registry adds lifecycle metadata and ``fleet.*`` chaos
sites. This module stays dependency-free below :mod:`..utils`.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import get_logger
from .failures import StaleLeaseError

__all__ = ["LeaseStore", "LeaseView"]

logger = get_logger("leases")

_LEASE_DIR = "leases"


@dataclass
class LeaseView:
    """Parsed view of one lease key's CURRENT (highest-epoch) file."""

    key: str
    epoch: int
    worker: str
    deadline_unix: float
    state: str  # "live" (held or expired — check the deadline) | terminal
    fname: str
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def expired(self) -> bool:
        return self.state == "live" and self.deadline_unix <= time.time()

    @property
    def terminal(self) -> bool:
        """A non-"live" state is a tombstone — never reclaimable at
        this epoch ("done" for recorded job blocks, "fenced" for
        presumed-dead serving members)."""
        return self.state != "live"


class LeaseStore:
    """Filesystem lease table under ``<path>/leases/``.

    Epoch-in-the-filename is the whole trick: creating
    ``<key>.e{epoch:06d}.lease`` is atomic create-if-absent (hard link
    of a fully written temp file), so claiming any (key, epoch) pair
    has exactly one winner, reclamation is an exclusive race for
    ``epoch + 1``, and the epoch doubles as the monotonic **fencing
    token** stamped into every downstream write. The current lease for
    a key is simply its highest-epoch file."""

    def __init__(
        self,
        path: str,
        worker_id: str,
        ttl_s: float,
        heartbeat_s: float = 0.0,
        create: bool = True,
    ):
        if ttl_s <= 0:
            raise ValueError(f"lease ttl must be > 0; got {ttl_s}")
        self.root = path
        self.dir = os.path.join(path, _LEASE_DIR)
        if create:
            os.makedirs(self.dir, exist_ok=True)
        self.worker_id = worker_id
        self.ttl_s = float(ttl_s)
        self.heartbeat_s = float(heartbeat_s) or self.ttl_s / 3.0
        self._lock = threading.Lock()
        #: key -> (epoch, fname) for leases this store holds live
        self._held: Dict[str, Tuple[int, str]] = {}
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        #: called (key, epoch, current_view_or_None) when a heartbeat
        #: sweep discovers a held lease was stolen underneath us — the
        #: "you were presumed dead and fenced" signal
        self.on_lost: Optional[
            Callable[[str, int, Optional[LeaseView]], None]
        ] = None

    # -- scanning ----------------------------------------------------------

    def _scan(self, key: str) -> Optional[LeaseView]:
        """The key's current lease: its highest-epoch file, parsed. An
        unreadable file (a crash artifact — every write here is a
        link/rename of complete content, so this should not happen)
        reads as an expired live lease, i.e. reclaimable."""
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return None
        prefix = key + ".e"
        best: Optional[Tuple[int, str]] = None
        for n in names:
            if not (n.startswith(prefix) and n.endswith(".lease")):
                continue
            try:
                epoch = int(n[len(prefix):-len(".lease")])
            except ValueError:
                continue
            if best is None or epoch > best[0]:
                best = (epoch, n)
        if best is None:
            return None
        return self._read_view(key, best[0], best[1])

    def _read_view(self, key: str, epoch: int, fname: str) -> LeaseView:
        try:
            with open(os.path.join(self.dir, fname), "r") as f:
                d = json.load(f)
        except (OSError, ValueError):
            d = {}
        meta = d.get("meta")
        return LeaseView(
            key=key,
            epoch=epoch,
            worker=str(d.get("worker", "")),
            deadline_unix=float(d.get("deadline_unix", 0.0)),
            state=str(d.get("state", "live")),
            fname=fname,
            meta=dict(meta) if isinstance(meta, dict) else {},
        )

    def scan_all(self) -> List[LeaseView]:
        """Current lease view of every key: ONE directory listing,
        grouped by key with the max epoch kept, then one file read per
        key — not a per-key re-listing (O(keys²) on big tables)."""
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        best: Dict[str, Tuple[int, str]] = {}
        for n in names:
            if not n.endswith(".lease"):
                continue
            key, sep, rest = n[: -len(".lease")].rpartition(".e")
            if not sep:
                continue
            try:
                epoch = int(rest)
            except ValueError:
                continue
            cur = best.get(key)
            if cur is None or epoch > cur[0]:
                best[key] = (epoch, n)
        return [
            self._read_view(key, epoch, fname)
            for key, (epoch, fname) in sorted(best.items())
        ]

    def held_epoch(self, key: str) -> Optional[int]:
        """The epoch this store holds ``key`` at, or ``None``."""
        with self._lock:
            held = self._held.get(key)
        return None if held is None else held[0]

    # -- claiming ----------------------------------------------------------

    def _payload(
        self,
        epoch: int,
        state: str = "live",
        meta: Optional[Dict[str, Any]] = None,
    ) -> bytes:
        d: Dict[str, Any] = {
            "worker": self.worker_id,
            "epoch": epoch,
            "state": state,
            "deadline_unix": time.time() + self.ttl_s,
            "written_unix": time.time(),
        }
        if meta:
            d["meta"] = meta
        return json.dumps(d).encode("utf-8")

    def _create_excl(self, fname: str, payload: bytes) -> bool:
        """Atomically create ``fname`` with ``payload`` iff absent:
        write a private temp file completely, then hard-link it to the
        target — EEXIST means another worker won the epoch."""
        target = os.path.join(self.dir, fname)
        tmp = os.path.join(
            self.dir, f".tmp-{self.worker_id}-{uuid.uuid4().hex[:8]}"
        )
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
        try:
            os.link(tmp, target)
            return True
        except FileExistsError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def acquire(
        self, key: str, meta: Optional[Dict[str, Any]] = None
    ) -> Optional[int]:
        """Claim (or reclaim) ``key``; one attempt, no retries (policy
        subclasses wrap with ``run_with_retries`` and their chaos
        sites). Returns the held epoch, or ``None`` when the key is
        terminal at its current epoch, live-leased elsewhere, or the
        exclusive race was lost."""
        now = time.time()
        with self._lock:
            held = self._held.get(key)
        cur = self._scan(key)
        if held is not None:
            if cur is not None and cur.epoch == held[0]:
                return held[0]  # still ours (epoch files are exclusive)
            # superseded or deleted underneath us: we lost it (and our
            # old epoch file, if a heartbeat resurrected it, is dead
            # weight — drop it so it cannot linger as a phantom stale
            # lease)
            self._drop_held(key, held[0], held[1])
        if cur is None:
            epoch = 0
        elif cur.terminal:
            return None  # tombstoned at this epoch
        elif cur.deadline_unix > now:
            return None  # live, someone else's
        else:
            epoch = cur.epoch + 1
        fname = f"{key}.e{epoch:06d}.lease"
        if not self._create_excl(fname, self._payload(epoch, meta=meta)):
            return None  # lost the exclusive race for this epoch
        with self._lock:
            self._held[key] = (epoch, fname)
        self._ensure_heartbeat()
        if epoch > 0:
            self._unlink_superseded(key, epoch)
        return epoch

    def steal(
        self,
        key: str,
        state: str = "fenced",
        meta: Optional[Dict[str, Any]] = None,
    ) -> Optional[int]:
        """Fence ``key``'s current owner: win the exclusive race for
        ``epoch + 1`` with a terminal ``state`` tombstone. The stolen
        lease is NOT held (no heartbeat — tombstones carry no
        liveness); the loser's next fenced write raises
        :class:`StaleLeaseError` and its heartbeat drops the lease.
        Returns the tombstone epoch, or ``None`` when the key is
        unknown, already terminal, or the race was lost."""
        cur = self._scan(key)
        if cur is None or cur.terminal:
            return None
        epoch = cur.epoch + 1
        fname = f"{key}.e{epoch:06d}.lease"
        if not self._create_excl(
            fname, self._payload(epoch, state=state, meta=meta)
        ):
            return None
        self._unlink_superseded(key, epoch)
        return epoch

    def _unlink_superseded(self, key: str, epoch: int) -> None:
        """Housekeeping: epoch files below ``epoch`` are dead weight."""
        for old in range(epoch):
            try:
                os.unlink(
                    os.path.join(self.dir, f"{key}.e{old:06d}.lease")
                )
            except OSError:
                pass

    # -- renewal / publication / release -----------------------------------

    def _rewrite(self, fname: str, payload: bytes) -> None:
        target = os.path.join(self.dir, fname)
        tmp = target + f".w-{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
        os.replace(tmp, target)

    def publish(self, key: str, meta: Dict[str, Any]) -> int:
        """Fenced metadata write: re-validate ownership, then rewrite
        the held lease with ``meta`` and a fresh deadline. Raises
        :class:`StaleLeaseError` when the lease is not held or was
        stolen — the zombie-write rejection: a process that wakes after
        being fenced cannot re-assert its registration."""
        with self._lock:
            held = self._held.get(key)
        cur = self._scan(key)
        if (
            held is None
            or cur is None
            or cur.epoch != held[0]
            or cur.worker != self.worker_id
        ):
            if held is not None:
                self._drop_held(key, held[0], held[1])
            if cur is None:
                detail = "the lease file is gone"
            else:
                detail = (
                    f"superseded by epoch {cur.epoch} "
                    f"(worker {cur.worker!r}, state {cur.state})"
                )
            raise StaleLeaseError(
                f"worker {self.worker_id}: lease {key!r} is stale — "
                f"{detail}; dropping the late write"
            )
        epoch, fname = held
        with self._lock:
            if self._held.get(key) != (epoch, fname):
                raise StaleLeaseError(
                    f"worker {self.worker_id}: lease {key!r} released "
                    f"during publish"
                )
            self._rewrite(fname, self._payload(epoch, meta=meta))
        return epoch

    def renew_all(
        self, meta_for: Optional[Callable[[str], Optional[dict]]] = None
    ) -> int:
        """One heartbeat sweep: rewrite every held lease with a fresh
        deadline (and, via ``meta_for``, refreshed metadata). Each
        renewal re-validates ownership BEFORE rewriting — ``_rewrite``
        is an ``os.replace``, which would re-CREATE a superseded file
        the reclaimer's housekeeping already unlinked, a phantom stale
        lease this worker would then renew forever. Returns the number
        of leases actually renewed."""
        renewed = 0
        for key, (epoch, fname) in list(self._held.items()):
            cur = self._scan(key)
            if (
                cur is None
                or cur.epoch != epoch
                or cur.worker != self.worker_id
            ):
                self._drop_held(key, epoch, fname)
                if self.on_lost is not None:
                    try:
                        self.on_lost(key, epoch, cur)
                    except Exception:
                        logger.warning(
                            "worker %s: on_lost hook failed for %s",
                            self.worker_id, key, exc_info=True,
                        )
                continue
            meta = meta_for(key) if meta_for is not None else None
            if meta is None and cur.meta:
                meta = cur.meta  # carry registration metadata forward
            with self._lock:
                if self._held.get(key) != (epoch, fname):
                    continue  # finished/released between snapshot and now
                self._rewrite(fname, self._payload(epoch, meta=meta))
            renewed += 1
        return renewed

    def _drop_held(self, key: str, epoch: int, fname: str) -> None:
        """Forget a lease we no longer own and unlink our (now
        superseded) epoch file if it still exists — never the current
        one, which has a different epoch in its name."""
        with self._lock:
            if self._held.get(key) == (epoch, fname):
                self._held.pop(key, None)
        try:
            os.unlink(os.path.join(self.dir, fname))
        except OSError:
            pass

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                self._heartbeat_sweep()
            except Exception:
                # a failed sweep is survivable until the TTL runs out;
                # the next tick retries. Never kill the thread.
                logger.warning(
                    "worker %s: lease heartbeat sweep failed",
                    self.worker_id, exc_info=True,
                )

    def _heartbeat_sweep(self) -> None:
        """The per-tick body of the heartbeat thread; subclasses wrap
        it with their chaos site + renewal metrics."""
        self.renew_all()

    def _ensure_heartbeat(self) -> None:
        if self._hb is None or not self._hb.is_alive():
            self._hb = threading.Thread(
                target=self._hb_loop,
                name=f"tft-lease-hb-{self.worker_id}",
                daemon=True,
            )
            self._hb.start()

    def mark_state(self, key: str, state: str) -> None:
        """Terminal marker: rewrite a held lease as ``state`` (a
        tombstone — "done" for recorded blocks, "resigned" for cleanly
        departing members) and stop heartbeating it."""
        with self._lock:
            held = self._held.pop(key, None)
            if held is not None:
                self._rewrite(held[1], self._payload(held[0], state=state))

    def release_key(self, key: str) -> None:
        """Drop a lease and unlink its file — the key becomes claimable
        again at the same epoch lineage."""
        with self._lock:
            held = self._held.pop(key, None)
            if held is not None:
                try:
                    os.unlink(os.path.join(self.dir, held[1]))
                except OSError:
                    pass

    def stop(self, unlink_held: bool = True) -> None:
        """Stop heartbeats and (by default) release everything held so
        other workers need not wait out the TTL."""
        self._stop.set()
        if self._hb is not None:
            self._hb.join(timeout=self.heartbeat_s + 5.0)
        if unlink_held:
            for key in list(self._held):
                self.release_key(key)
