"""Utilities: logging, config, profiling hooks."""

from .config import Config, get_config, set_config, ensure_x64
from .logging import get_logger
from . import profiling

__all__ = [
    "Config",
    "get_config",
    "set_config",
    "ensure_x64",
    "get_logger",
    "profiling",
]
