"""Utilities: logging, config, profiling hooks."""

from .config import (
    Config,
    get_config,
    set_config,
    ensure_x64,
    enable_compilation_cache,
)
from .logging import get_logger
from .failures import (
    DeadlineExceededError,
    DeviceOOMError,
    QuarantinedBlocksError,
    StaleLeaseError,
    is_oom,
    is_transient,
    retry_deadline,
    run_with_retries,
    seed_backoff_jitter,
)
from . import chaos
from . import profiling

__all__ = [
    "Config",
    "get_config",
    "set_config",
    "ensure_x64",
    "enable_compilation_cache",
    "get_logger",
    "DeadlineExceededError",
    "DeviceOOMError",
    "QuarantinedBlocksError",
    "StaleLeaseError",
    "is_oom",
    "is_transient",
    "retry_deadline",
    "run_with_retries",
    "seed_backoff_jitter",
    "chaos",
    "profiling",
]
