"""Utilities: logging, config, profiling hooks."""

from .config import ensure_x64
from .logging import get_logger

__all__ = ["ensure_x64", "get_logger"]
