"""Logging shim (analog of the reference's ``Logging`` trait over slf4j,
``/root/reference/src/main/scala/org/tensorframes/Logging.scala:5-9``)."""

import logging

_ROOT = "tensorframes_tpu"


def get_logger(name: str = "") -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)
