"""Columnar in-memory table: the frame the engine operates on.

The reference operates on Spark DataFrames — distributed, partitioned,
row-oriented with columnar metadata. The TPU-native analog is a *columnar*
table: one contiguous host array per column (dense case), partitioned along
the row axis. Partitions play the same role Spark partitions do in the
reference (``DebugRowOps.scala:377-391``): ``map_blocks`` runs once per
partition block, ``reduce_blocks`` produces one partial per partition then
merges. On device, a partition block maps 1:1 onto a TPU chip's shard (see
``tensorframes_tpu.parallel``).

Storage forms per column:
- dense: one ``np.ndarray`` of shape ``[n_rows, *cell_shape]`` — the fast
  path; feeds the MXU directly after ``device_put``.
- ragged: a Python list of per-row ``np.ndarray`` cells with a common rank
  but varying dims (reference supports this in row ops only,
  ``TFDataOps.scala:90-103``).
- binary: a Python list of ``bytes`` (reference ``datatypes.scala:571-599``,
  row ops on single cells only).

Laziness matches the reference: map ops are lazy (``Operations.scala:30-33``,
materialized by ``collect``/``cache``), reduces are eager.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..obs import span as _span
from ..schema import (
    BINARY,
    ColumnInfo,
    FrameInfo,
    Shape,
    Unknown,
    for_numpy_dtype,
)

__all__ = ["Row", "TensorFrame", "GroupedFrame", "frame_from_pandas"]

# link-traffic metrics (frame.h2d_bytes_total / frame.d2h_bytes_total,
# per-chunk latency histograms, the inflight-chunks gauge) live with the
# transfer machinery itself in ``frame/transfer.py``


class Row(dict):
    """A result row: dict with attribute access, printed like the reference's
    PySpark rows (``README.md:81-90``)."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k) from None

    def __repr__(self):
        inner = ", ".join(f"{k}={_fmt_cell(v)}" for k, v in self.items())
        return f"Row({inner})"


def _fmt_cell(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    return v


def _as_cell(v) -> Any:
    """Normalize one cell value to numpy scalar / ndarray / bytes."""
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, np.ndarray):
        return v
    if isinstance(v, (list, tuple)):
        return np.asarray(v)
    return np.asarray(v)[()]  # python scalar -> numpy scalar


def _is_device_array(a) -> bool:
    """True for jax device arrays (host numpy otherwise)."""
    return not isinstance(a, np.ndarray) and hasattr(a, "addressable_shards")


class _ColumnData:
    """One column's storage. ``dense`` is a [n, *cell] array — host numpy
    *or* a device-resident jax array (engine results stay on device so
    chained ops never round-trip through the host; the reference re-marshals
    every Session.run, ``TFDataOps.scala:27-59``). ``cells`` is a list of
    per-row payloads (ragged / binary). ``device()``/``host()`` memoize the
    other-side copy — columns are immutable, so each transfer happens once.
    Transfers go through the streaming layer (``frame/transfer.py``):
    chunked, concurrent, retried, and chaos-injectable; ``device_stream()``
    exposes the in-flight chunks so block loops can compute on chunk *i*
    while chunk *i+1* is still crossing the link.
    """

    __slots__ = (
        "dense", "cells", "is_binary", "_device_arr", "_host_arr",
        "_sharded_cache", "_stream",
    )

    def __init__(self, dense=None, cells=None, is_binary=False):
        self.dense = dense  # np.ndarray | jax.Array | None
        self.cells: Optional[List[Any]] = cells
        self.is_binary = is_binary
        self._device_arr = None
        self._host_arr = None
        #: per-(mesh, split) device-sharded copies (parallel engine)
        self._sharded_cache = None
        #: in-flight chunked upload (transfer.StreamingUpload), kept until
        #: device() memoizes its assembled column
        self._stream = None

    def device_stream(self):
        """Streaming handle over this column's device form: ``slice(lo,
        hi)`` waits only for the chunks covering that row range (compute
        overlaps the rest of the upload), ``assembled()`` is the whole
        column. Memoized — repeated calls reuse landed chunks, and a
        column already on device streams trivially."""
        from . import transfer as _transfer

        if self.dense is None:
            raise ValueError("only dense columns have a device form")
        if _is_device_array(self.dense):
            return _transfer._Resident(self.dense)
        if self._device_arr is not None and (
            self._device_arr.dtype == self.dense.dtype
        ):
            return _transfer._Resident(self._device_arr)
        want = _transfer.wire_dtype(self.dense.dtype)
        if self._stream is None or self._stream.wire != want:
            self._stream = _transfer.StreamingUpload(
                self.dense, what="column"
            )
        return self._stream

    def device(self):
        """The dense column as a device-resident jax array (memoized)."""
        stream = self.device_stream()
        arr = stream.assembled()
        if not _is_device_array(self.dense):
            self._device_arr = arr
            self._stream = None
        return arr

    def host(self) -> np.ndarray:
        """The dense column as a host numpy array (memoized; this is the
        point where a device-resident result synchronizes — chunked and
        concurrent through ``frame/transfer.py``)."""
        if self.dense is None:
            raise ValueError("only dense columns have a host block form")
        if not _is_device_array(self.dense):
            return self.dense
        if self._host_arr is None:
            from . import transfer as _transfer

            self._host_arr = _transfer.d2h(self.dense, what="column")
        return self._host_arr

    @property
    def num_rows(self) -> int:
        if self.dense is not None:
            return int(self.dense.shape[0])
        return len(self.cells)

    def slice(self, lo: int, hi: int) -> "_ColumnData":
        if self.dense is not None:
            return _ColumnData(dense=self.dense[lo:hi])
        return _ColumnData(cells=self.cells[lo:hi], is_binary=self.is_binary)

    def take(self, idx: np.ndarray) -> "_ColumnData":
        if self.dense is not None:
            return _ColumnData(dense=self.dense[idx])
        return _ColumnData(
            cells=[self.cells[i] for i in idx], is_binary=self.is_binary
        )

    def cell(self, i: int):
        if self.dense is not None:
            return self.host()[i]
        return self.cells[i]

    def iter_cells(self):
        if self.dense is not None:
            return iter(self.host())
        return iter(self.cells)


def _build_column(name: str, data) -> Tuple[_ColumnData, ColumnInfo]:
    """Ingest arbitrary user data into column storage + minimal schema info."""
    if isinstance(data, _ColumnData):
        raise TypeError("internal type passed to _build_column")
    if isinstance(data, np.ndarray):
        st = for_numpy_dtype(data.dtype)
        # copy: frames own their storage. Aliasing the caller's buffer would
        # make later in-place mutation silently desync the memoized device
        # copy (and any lazy results) from host data.
        return _ColumnData(dense=np.array(data, order="C")), ColumnInfo(
            name, st, nesting=data.ndim - 1
        )
    if _is_device_array(data):
        # jax arrays are immutable: keep them device-resident, no copy
        st = for_numpy_dtype(np.dtype(data.dtype))
        return _ColumnData(dense=data), ColumnInfo(
            name, st, nesting=data.ndim - 1
        )
    data = list(data)
    if not data:
        raise ValueError(f"Column {name!r} is empty; cannot infer its type")
    cells = [_as_cell(v) for v in data]
    n_binary = sum(isinstance(c, bytes) for c in cells)
    if n_binary:
        if n_binary != len(cells):
            raise TypeError(f"Column {name!r} mixes binary and numeric cells")
        return _ColumnData(cells=cells, is_binary=True), ColumnInfo(
            name, BINARY, nesting=0
        )
    ranks = {c.ndim for c in cells}
    if len(ranks) != 1:
        raise ValueError(
            f"Column {name!r} has cells of mixed rank {sorted(ranks)}; "
            f"all cells in a column must have the same tensor order"
        )
    rank = ranks.pop()
    dtype = np.result_type(*[c.dtype for c in cells])
    st = for_numpy_dtype(dtype)
    shapes = {c.shape for c in cells}
    if len(shapes) == 1:
        dense = np.stack([c.astype(dtype, copy=False) for c in cells])
        return _ColumnData(dense=np.ascontiguousarray(dense)), ColumnInfo(
            name, st, nesting=rank
        )
    # ragged: keep per-row cells
    cells = [np.ascontiguousarray(c.astype(dtype, copy=False)) for c in cells]
    return _ColumnData(cells=cells), ColumnInfo(name, st, nesting=rank)


class TensorFrame:
    """An immutable columnar table with row-axis partitions.

    Construction: :meth:`from_columns`, :meth:`from_rows`,
    :meth:`from_pandas`, :meth:`from_arrow`.
    """

    def __init__(
        self,
        columns: Dict[str, _ColumnData],
        info: FrameInfo,
        num_partitions: int = 1,
        offsets: Optional[np.ndarray] = None,
        _thunk: Optional[Callable[[], "TensorFrame"]] = None,
    ):
        self._columns = columns
        self._info = info
        self._thunk = _thunk  # lazy map pending; None once concrete
        self._thunk_lock = threading.Lock()
        if _thunk is not None:
            self._num_rows = None
            self._offsets = None
            self._num_partitions = num_partitions
            return
        nrows = {c.num_rows for c in columns.values()}
        if len(nrows) > 1:
            raise ValueError(f"Columns have differing lengths: {nrows}")
        self._num_rows = nrows.pop() if nrows else 0
        if offsets is not None:
            self._offsets = np.asarray(offsets, dtype=np.int64)
            self._num_partitions = len(self._offsets) - 1
        else:
            self._num_partitions = max(1, min(num_partitions, max(self._num_rows, 1)))
            self._offsets = np.linspace(
                0, self._num_rows, self._num_partitions + 1, dtype=np.int64
            )

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_columns(
        data: Dict[str, Any], num_partitions: int = 1
    ) -> "TensorFrame":
        cols: Dict[str, _ColumnData] = {}
        infos: List[ColumnInfo] = []
        for name, v in data.items():
            cd, ci = _build_column(name, v)
            cols[name] = cd
            infos.append(ci)
        return TensorFrame(cols, FrameInfo(infos), num_partitions=num_partitions)

    @staticmethod
    def from_rows(
        rows: Sequence[Dict[str, Any]], num_partitions: int = 1
    ) -> "TensorFrame":
        if not rows:
            raise ValueError("from_rows requires at least one row")
        names = list(rows[0].keys())
        data = {n: [r[n] for r in rows] for n in names}
        return TensorFrame.from_columns(data, num_partitions=num_partitions)

    @staticmethod
    def from_pandas(pdf, num_partitions: int = 1) -> "TensorFrame":
        # numeric dtypes come over as one dense array; object columns cell-wise
        data = {
            str(c): pdf[c].to_numpy() if pdf[c].dtype != object else list(pdf[c])
            for c in pdf.columns
        }
        return TensorFrame.from_columns(data, num_partitions=num_partitions)

    @staticmethod
    def from_arrow(table, num_partitions: int = 1) -> "TensorFrame":
        """Ingest a pyarrow Table (interop edge; reference reads Spark
        DataFrames, we read Arrow — the common interchange). Delegates to
        :func:`tensorframes_tpu.interop.arrow.from_arrow` (null rejection,
        dense/FixedSizeList fast paths)."""
        from ..interop.arrow import from_arrow

        return from_arrow(table, num_partitions=num_partitions)

    # -- laziness ----------------------------------------------------------

    def _force(self) -> "TensorFrame":
        """Materialize a lazy frame (one level; thunks may chain)."""
        if self._thunk is None:
            return self
        with self._thunk_lock:
            if self._thunk is not None:
                # no span here: every engine thunk opens its own op span
                # (engine.map_blocks / engine.map_rows), so a force span
                # would only duplicate the tree one level up — and _force
                # sits on every data access
                concrete = self._thunk()._force()
                self._columns = concrete._columns
                self._num_rows = concrete._num_rows
                self._offsets = concrete._offsets
                self._num_partitions = concrete._num_partitions
                self._thunk = None
        return self

    @property
    def is_lazy(self) -> bool:
        return self._thunk is not None

    def cache(self) -> "TensorFrame":
        """Force materialization (Spark ``cache()``-ish)."""
        return self._force()

    # -- schema ------------------------------------------------------------

    @property
    def schema(self) -> FrameInfo:
        return self._info

    @property
    def columns(self) -> List[str]:
        return self._info.names

    @property
    def num_rows(self) -> int:
        self._force()
        return self._num_rows

    def __len__(self) -> int:
        return self.num_rows

    @property
    def num_partitions(self) -> int:
        return self._num_partitions

    def explain_tensors(self) -> str:
        """Schema + tensor metadata string (reference ``tfs.print_schema`` /
        ``explain``, ``DebugRowOps.scala:528-545``)."""
        return self._info.explain()

    # -- data access -------------------------------------------------------

    def column_data(self, name: str) -> _ColumnData:
        self._force()
        if name not in self._columns:
            raise KeyError(f"No column {name!r}; columns: {self.columns}")
        return self._columns[name]

    def column_block(self, name: str, partition: Optional[int] = None):
        """The dense block for a column (whole frame or one partition).
        Raises for ragged/binary columns — those are row-op only, matching
        the reference (``core.py:287-288``: 'does not work when rows contain
        vectors of different sizes')."""
        self._force()
        cd = self.column_data(name)
        if cd.dense is None:
            kind = "binary" if cd.is_binary else "ragged"
            raise ValueError(
                f"Column {name!r} is {kind}; block operations require "
                f"uniform dense columns — use map_rows instead"
            )
        if partition is None:
            return cd.dense
        lo, hi = self._offsets[partition], self._offsets[partition + 1]
        return cd.dense[lo:hi]

    def partition_bounds(self) -> List[Tuple[int, int]]:
        self._force()
        return [
            (int(self._offsets[i]), int(self._offsets[i + 1]))
            for i in range(self._num_partitions)
        ]

    def collect(self) -> List[Row]:
        """Materialize to a list of rows (reference ``df.collect()``)."""
        self._force()
        with _span("frame.collect", rows=self._num_rows):
            names = self.columns
            iters = [self._columns[n].iter_cells() for n in names]
            out = []
            for vals in zip(*iters):
                out.append(Row(zip(names, vals)))
            return out

    def to_pandas(self):
        import pandas as pd

        self._force()
        data = {}
        for c in self._info:
            cd = self._columns[c.name]
            if cd.dense is not None and cd.dense.ndim == 1:
                data[c.name] = cd.host()
            else:
                data[c.name] = list(cd.iter_cells())
        return pd.DataFrame(data)

    # -- relational-ish ops ------------------------------------------------

    def _planned_lazy(self) -> bool:
        """True when this frame is a pending logical-plan node: relational
        ops on it record plan nodes instead of forcing (``engine/plan.py``
        — ``select`` is what gives column pruning its demand signal)."""
        if self._thunk is None or getattr(self, "_plan_node", None) is None:
            return False
        from ..engine import plan as _plan

        return _plan.enabled()

    def select(self, *cols: Union[str, Tuple[str, str]]) -> "TensorFrame":
        """Project columns; a ``(src, alias)`` tuple renames — the analog of
        the reference's ``df.select(df.y, df.y.alias('z'))``
        (``README.md:113``). On a pending planned frame the projection is
        recorded lazily (it drives the pruning pass) instead of forcing."""
        if self._planned_lazy():
            from ..engine import plan as _plan

            return _plan.record_select(self, cols)
        self._force()
        new_cols: Dict[str, _ColumnData] = {}
        new_infos: List[ColumnInfo] = []
        for c in cols:
            src, dst = (c, c) if isinstance(c, str) else c
            new_cols[dst] = self.column_data(src)
            new_infos.append(self._info[src].with_name(dst))
        return TensorFrame(
            new_cols, FrameInfo(new_infos), offsets=self._offsets
        )

    def with_column(self, name: str, data) -> "TensorFrame":
        self._force()
        cd, ci = _build_column(name, data)
        if cd.num_rows != self._num_rows:
            raise ValueError(
                f"with_column({name!r}): {cd.num_rows} rows != {self._num_rows}"
            )
        cols = dict(self._columns)
        cols[name] = cd
        infos = [c for c in self._info if c.name != name]
        infos.append(ci)
        return TensorFrame(cols, FrameInfo(infos), offsets=self._offsets)

    def repartition(self, n: int) -> "TensorFrame":
        self._force()
        return TensorFrame(self._columns, self._info, num_partitions=n)

    def unpersist_device(self) -> "TensorFrame":
        """Release the memoized device (HBM) copies of this frame's columns.

        Column storage is shared by derived frames (``select`` etc.), so
        this frees the device buffers for all of them; the next engine op
        re-transfers on demand. Host data is unaffected. Device-resident
        result columns are pulled to the host first so their data survives
        the release. THIS frame's multihost registry of globally-sharded
        arrays (``parallel.multihost``) is dropped too (its data survives,
        as this process's rows, via the same host pull) — but the
        registry is per-frame: frames derived by chained multihost ops
        hold their own references, so to fully free a chain's device
        arrays, unpersist (or drop) each frame in it."""
        self._force()
        for cd in self._columns.values():
            if cd.dense is not None and _is_device_array(cd.dense):
                cd.dense = cd.host()
                cd._host_arr = None
            cd._device_arr = None
            cd._sharded_cache = None
            cd._stream = None
        self._mh_global = None
        return self

    def slice_rows(self, lo: int, hi: int) -> "TensorFrame":
        """Contiguous row slice as a single-partition frame."""
        self._force()
        cols = {n: cd.slice(lo, hi) for n, cd in self._columns.items()}
        return TensorFrame(cols, self._info)

    def filter_rows(self, mask: np.ndarray) -> "TensorFrame":
        if self._planned_lazy():
            from ..engine import plan as _plan

            return _plan.record_filter(self, mask)
        self._force()
        idx = np.nonzero(np.asarray(mask))[0]
        cols = {n: cd.take(idx) for n, cd in self._columns.items()}
        return TensorFrame(cols, self._info, num_partitions=self._num_partitions)

    def decode_column(
        self,
        col: str,
        fn: Callable[[Any], Any],
        dst: Optional[str] = None,
        num_threads: Optional[int] = None,
    ) -> "TensorFrame":
        """Lazy host decode stage: map ``fn`` over one column's cells.

        This is the TPU-native shape of the reference's decode-inside-the-
        graph binary scoring (``read_image.py:147-167``, where a string
        tensor of file bytes feeds ``decode_jpeg`` inside the TF graph):
        the decode runs on the *host* — in a thread pool, since real codecs
        release the GIL — and the decoded numeric column then feeds the
        device in batches. Uniform decoded shapes form a dense column
        (``map_blocks``/MXU path); varying shapes stay ragged and feed
        ``map_rows``'s shape buckets. Either way the device sees batched
        work, never the reference's one-``Session.run``-per-row loop
        (``DebugRowOps.scala:819-857``).

        ``dst`` names the decoded column (default: replace ``col``). The
        decoded dtype/rank is probed from row 0; later cells are cast to
        the probed dtype so the declared schema holds.
        """
        self._force()
        if col not in self._info:
            raise KeyError(f"decode_column: no column {col!r}; columns: {self.columns}")
        dst = dst or col
        if dst != col and dst in self._info:
            raise ValueError(f"decode_column: destination column {dst!r} already exists")
        if self._num_rows == 0:
            raise ValueError("decode_column on an empty frame (no row to probe)")
        src = self._columns[col]
        probe = _as_cell(fn(src.cell(0)))
        if isinstance(probe, bytes):
            info = ColumnInfo(dst, BINARY, nesting=0)
            probe_dtype = None
        else:
            info = ColumnInfo(dst, for_numpy_dtype(probe.dtype), nesting=probe.ndim)
            probe_dtype = probe.dtype
        infos: List[ColumnInfo] = []
        for c in self._info:
            infos.append(info if c.name == dst else c)
        if dst != col:
            infos.append(info)
        result_info = FrameInfo(infos)
        offsets = self._offsets
        parent_cols = self._columns

        def thunk() -> "TensorFrame":
            cells = list(src.iter_cells())
            n = len(cells)

            def decode_span(span):
                """Decode one chunk; uniform-shape chunks come back as one
                stacked dense block (C-level assembly, no 100k-element
                Python cell list), varying shapes as a cell list."""
                out = [_as_cell(fn(c)) for c in span]
                if probe_dtype is None:
                    return out  # binary decode output: stays cell-wise
                for i, d in enumerate(out):
                    if isinstance(d, bytes):
                        raise TypeError(
                            f"decode_column({col!r}): row 0 decoded to an "
                            f"array but a later row decoded to bytes"
                        )
                    if not isinstance(d, np.ndarray):
                        out[i] = np.asarray(d, dtype=probe_dtype)[()]
                if all(
                    isinstance(d, np.ndarray) and d.shape == probe.shape
                    for d in out
                ):
                    return np.stack(out).astype(probe_dtype, copy=False)
                return [
                    d.astype(probe_dtype, copy=False)
                    if isinstance(d, np.ndarray)
                    else d
                    for d in out
                ]

            # row 0 was already decoded by the schema probe; reuse it (a
            # stateful or expensive codec must not run twice per row)
            if num_threads == 0 or (num_threads is None and n < 64):
                parts = [decode_span(cells[1:])] if n > 1 else []
            else:
                import os
                from concurrent.futures import ThreadPoolExecutor

                workers = num_threads or min(32, os.cpu_count() or 1)
                # one task per CHUNK, not per cell: futures machinery costs
                # ~15us/task, which dominates cheap codecs at 100k rows
                # (measured 1.6s -> 0.1s for a frombuffer codec); real
                # codecs release the GIL inside the chunk loop just as well
                chunk = max(64, n // (workers * 4))
                spans = [
                    cells[lo : lo + chunk] for lo in range(1, n, chunk)
                ]
                with ThreadPoolExecutor(workers) as ex:
                    parts = list(ex.map(decode_span, spans))
            if (
                probe_dtype is not None
                and all(isinstance(p, np.ndarray) for p in parts)
            ):
                # uniform decodes: concatenate chunk blocks straight into
                # the dense column buffer — one memcpy, no per-cell work
                first = probe[None].astype(probe_dtype, copy=False)
                dense = np.concatenate([first] + parts, axis=0)
                cd = _ColumnData(dense=np.ascontiguousarray(dense))
            else:
                decoded = [probe]
                for p in parts:
                    decoded.extend(
                        p if isinstance(p, list) else list(p)
                    )
                cd, _ = _build_column(dst, decoded)
            cols: Dict[str, _ColumnData] = {}
            for c in result_info:
                cols[c.name] = cd if c.name == dst else parent_cols[c.name]
            return TensorFrame(cols, result_info, offsets=offsets)

        return TensorFrame(
            {}, result_info, num_partitions=self._num_partitions, _thunk=thunk
        )

    def group_by(self, *keys: str) -> "GroupedFrame":
        # key validation needs only the (eagerly known) schema — a
        # pending planned frame stays lazy so a following ``aggregate``
        # can prune/fuse its chain (engine/plan.py)
        if not self._planned_lazy():
            self._force()
        for k in keys:
            if k not in self._info:
                raise KeyError(f"group_by: no column {k!r}")
        return GroupedFrame(self, list(keys))

    # alias matching Spark naming
    groupBy = group_by

    # -- method-style op sugar (reference ``DFImplicits``: the Scala DSL
    # adds df.mapBlocks(...)/df.reduceRows(...) directly on DataFrames,
    # ``dsl/Implicits.scala:25-116``) --------------------------------------

    def map_blocks(
        self, fetches, trim: bool = False, feed_dict=None, constants=None
    ) -> "TensorFrame":
        from ..engine import map_blocks

        return map_blocks(
            fetches, self, trim=trim, feed_dict=feed_dict, constants=constants
        )

    def map_rows(self, fetches, feed_dict=None) -> "TensorFrame":
        from ..engine import map_rows

        return map_rows(fetches, self, feed_dict=feed_dict)

    def reduce_blocks(self, fetches):
        from ..engine import reduce_blocks

        return reduce_blocks(fetches, self)

    def reduce_rows(self, fetches):
        from ..engine import reduce_rows

        return reduce_rows(fetches, self)

    def block(self, col: str, tft_name: Optional[str] = None):
        """Auto-placeholder from this frame's column metadata (reference
        ``df.block(col)``, ``dsl/Implicits.scala:89-93``)."""
        from ..capture import dsl as _dsl

        return _dsl.block(self, col, tft_name=tft_name)

    def row(self, col: str, tft_name: Optional[str] = None):
        from ..capture import dsl as _dsl

        return _dsl.row(self, col, tft_name=tft_name)

    # camelCase aliases matching the reference DSL surface
    mapBlocks = map_blocks
    mapRows = map_rows
    reduceBlocks = reduce_blocks
    reduceRows = reduce_rows

    def mapBlocksTrimmed(self, fetches, feed_dict=None, constants=None):
        return self.map_blocks(
            fetches, trim=True, feed_dict=feed_dict, constants=constants
        )

    # -- analysis (reference ``tfs.analyze``) ------------------------------

    def analyze(self) -> "TensorFrame":
        """Deep per-cell shape analysis; embeds analyzed block shapes in the
        schema. Mirrors ``ExtraOperations.deepAnalyzeDataFrame``
        (``ExperimentalOperations.scala:68-111``): per-partition cell-shape
        merge (mismatched dims -> Unknown), partition size prepended, then a
        cross-partition merge."""
        self._force()
        per_part: List[Optional[List[Optional[Shape]]]] = []
        for lo, hi in self.partition_bounds():
            n = hi - lo
            if n == 0:
                per_part.append(None)  # empty partitions don't pollute
                continue
            col_shapes: List[Optional[Shape]] = []
            for c in self._info:
                cd = self._columns[c.name]
                if cd.is_binary:
                    col_shapes.append(Shape(n))
                    continue
                if cd.dense is not None:
                    col_shapes.append(Shape((n,) + cd.dense.shape[1:]))
                    continue
                merged: Optional[Shape] = None
                for i in range(lo, hi):
                    s = Shape(cd.cells[i].shape)
                    merged = s if merged is None else merged.merge(s)
                    if merged is None:
                        break
                col_shapes.append(
                    merged.prepend(n) if merged is not None else None
                )
            per_part.append(col_shapes)
        parts = [p for p in per_part if p is not None]
        if parts:
            agg = parts[0]
            for p in parts[1:]:
                agg = [
                    (a.merge(b) if a is not None and b is not None else None)
                    for a, b in zip(agg, p)
                ]
        else:
            agg = [None] * len(self._info)
        infos = []
        for c, s in zip(self._info, agg):
            infos.append(c if s is None else c.with_analyzed(s))
        return TensorFrame(self._columns, FrameInfo(infos), offsets=self._offsets)

    def __repr__(self):
        if self._thunk is not None:
            return f"TensorFrame(lazy, cols={self._info.names})"
        return (
            f"TensorFrame(rows={self._num_rows}, parts={self._num_partitions}, "
            f"cols={self._info.names})"
        )


class GroupedFrame:
    """Result of ``df.group_by(keys)``; consumed by ``tfs.aggregate``
    (analog of Spark's ``RelationalGroupedDataset``,
    reference ``DebugRowOps.scala:547-592``)."""

    def __init__(self, frame: TensorFrame, keys: List[str]):
        self.frame = frame
        self.keys = keys

    def aggregate(self, fetches) -> TensorFrame:
        """Method-style aggregate (reference
        ``RichRelationalGroupedDataset.aggregate``,
        ``dsl/Implicits.scala:107-116``)."""
        from ..engine import aggregate

        return aggregate(fetches, self)

    def __repr__(self):
        return f"GroupedFrame(keys={self.keys}, frame={self.frame!r})"


def frame_from_pandas(pdf, num_partitions: int = 1) -> TensorFrame:
    return TensorFrame.from_pandas(pdf, num_partitions=num_partitions)
