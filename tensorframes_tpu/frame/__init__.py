"""Columnar table layer: the TPU-native analog of Spark DataFrames."""

from .table import TensorFrame, GroupedFrame, Row, frame_from_pandas

__all__ = ["TensorFrame", "GroupedFrame", "Row", "frame_from_pandas"]
