"""Streaming host↔device transfers: chunked, concurrent, retried.

The round-5 bench exposed the dominant system cost: a 3.1 GB column
crossed the tunnel as ONE blocking ``jax.device_put`` (313.9 s at
0.01 GB/s) while the scoring compute took 0.49 s — the chip starved on
ingest by ~600×. The reference pays the same per-session marshaling
(``TFDataOps.scala``); the TPU-performance literature (Kaufman et al.,
arXiv:2008.01040) makes the general point that end-to-end throughput is
gated by *feeding* the chip, not the MXU. This module is the fix: every
column-sized transfer is split into row chunks that move concurrently on
a small thread pool, so

- multiple chunks are in flight at once (a single stream cannot fill a
  high-latency link; N streams pipeline against each other),
- consumers can start computing on chunk *i* while chunk *i+1* is still
  in the air (:class:`StreamingUpload` hands out per-chunk device
  arrays; ``engine/ops.py`` feeds block loops from them),
- each chunk crosses inside its own ``run_with_retries`` window with a
  ``frame.h2d`` / ``frame.d2h`` chaos site, so a transient tunnel error
  retries one chunk instead of killing the whole ingest (the monolithic
  path had **no** retry at all).

Knobs (:class:`~tensorframes_tpu.utils.config.Config`):
``transfer_chunk_bytes`` (chunk size; ``<= 0`` restores the monolithic
path — still retried and counted), ``transfer_streams`` (pool width),
and ``transfer_dtype="bf16"`` — a WIRE cast: float32 payloads cross the
link as bfloat16 (half the tunnel bytes) and are upcast back to float32
on device, so schemas, programs, and device dtypes are untouched; the
values are bf16-rounded, the same precision loss the bf16 bench mode
measures (≥98% argmax agreement on the scoring workload). An accuracy
trade the caller opts into.

Byte-identity is the hard contract: with no wire cast configured, a
chunked transfer produces exactly the bytes the monolithic one would,
in both directions (tests/test_transfer.py holds the greedy matrix).

Telemetry: ``frame.h2d_bytes_total`` / ``frame.d2h_bytes_total``
(moved here from ``frame/table.py`` — still real link bytes, now
including the engine's per-block feed uploads), per-chunk
``frame.h2d_seconds`` / ``frame.d2h_seconds`` histograms, and an
``ingest.inflight_chunks`` gauge. See docs/ingest.md for tuning
guidance and docs/observability.md for the catalog.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import numpy as np

from ..obs import span as _span
from ..obs.metrics import counter as _counter
from ..obs.metrics import gauge as _gauge
from ..obs.metrics import histogram as _histogram
from ..utils import get_logger

__all__ = [
    "StreamingUpload",
    "d2h",
    "d2h_async",
    "h2d",
    "wire_dtype",
]

logger = get_logger("transfer")

#: link-traffic accounting (moved from ``frame/table.py``): bytes that
#: actually cross the host↔device link — memoized column transfers AND
#: the engine's per-block feed uploads, each counted once where the
#: transfer happens
_m_h2d = _counter(
    "frame.h2d_bytes_total", "Host-to-device transfer bytes over the link"
)
_m_d2h = _counter(
    "frame.d2h_bytes_total", "Device-to-host transfer bytes over the link"
)
#: per-CHUNK transfer latency: throughput is visible as bytes/seconds
#: per scrape window; a fat tail here is the tunnel hiccuping
_h_h2d = _histogram(
    "frame.h2d_seconds", "Per-chunk host-to-device transfer seconds"
)
_h_d2h = _histogram(
    "frame.d2h_seconds", "Per-chunk device-to-host transfer seconds"
)
_g_inflight = _gauge(
    "ingest.inflight_chunks",
    "Transfer chunks currently in flight (both directions)",
)
#: the live transfer knobs as gauges: a /varz reader (or the future
#: autotuner) correlating a throughput dip with a retune needs the knob
#: values IN the series, not in a config file somewhere else
_g_chunk_bytes = _gauge(
    "ingest.chunk_bytes",
    "Configured transfer chunk size in bytes (<= 0 = monolithic)",
)
_g_streams = _gauge(
    "ingest.streams", "Configured transfer pool width (chunks in flight)"
)


def _refresh_knob_gauges() -> None:
    from ..utils.config import get_config

    cfg = get_config()
    _g_chunk_bytes.set(float(cfg.transfer_chunk_bytes))
    _g_streams.set(float(max(1, int(cfg.transfer_streams))))


from ..utils.config import register_on_change as _register_on_change  # noqa: E402

_register_on_change(_refresh_knob_gauges)

#: hard cap on chunks per transfer: a pathological chunk-bytes setting
#: (1 byte) must not mint a million thread-pool tasks
_MAX_CHUNKS = 1024


# ---------------------------------------------------------------------------
# tuned link knobs
# ---------------------------------------------------------------------------

#: cap on the autotuner's trial payload for this link (bytes;
#: ``TFT_TUNE_TRIAL_BYTES`` overrides — tests shrink it, operators on a
#: fast link may grow it for higher-fidelity trials)
_TRIAL_BYTES_DEFAULT = 64 << 20


def _link_knobs() -> Tuple[int, int]:
    """The effective ``(chunk_bytes, streams)`` for this link: the
    Config statics, overridden by the autotuner's winner for the
    ``transfer.link`` surface when one is installed (the per-pool-retune
    re-read the r05 link-weather sensitivity asked for — winners key on
    device kind, and ``tune.mode()`` gates everything). Chunking
    disabled by config (``transfer_chunk_bytes <= 0``) is an operator
    opt-out the tuner respects."""
    from ..utils import get_config

    cfg = get_config()
    default_cb = int(cfg.transfer_chunk_bytes)
    default_st = max(1, int(cfg.transfer_streams))
    if default_cb <= 0:
        return default_cb, default_st
    try:
        from .. import tune

        if tune.mode() == "off":
            return default_cb, default_st
        grid, feats, trial = _link_search(default_cb, default_st)
        win = tune.lookup(
            "transfer.link", "link",
            {"chunk_bytes": default_cb, "streams": default_st},
            grid=grid, feats=feats, trial=trial,
        )
        cb = int(win.get("chunk_bytes", default_cb))
        st = int(win.get("streams", default_st))
        return (cb if cb > 0 else default_cb), max(1, min(st, 64))
    except Exception:
        logger.warning(
            "transfer knob tuning lookup failed; using Config statics",
            exc_info=True,
        )
        return default_cb, default_st


def _link_search(default_cb: int, default_st: int):
    """(grid, feats, trial) for the transfer-knob search. The trial
    moves a seeded payload host→device as concurrent row chunks on a
    PRIVATE pool (raw ``device_put`` — no recursion into this layer,
    and the re-entrancy guard covers stray lookups). Payload is capped
    (``TFT_TUNE_TRIAL_BYTES``), and chunk candidates are capped at half
    the payload so every candidate genuinely exercises chunking at
    trial scale — a fidelity trade documented in docs/tuning.md."""
    import os as _os

    cap = int(
        _os.environ.get("TFT_TUNE_TRIAL_BYTES", "")
        or _TRIAL_BYTES_DEFAULT
    )
    payload = max(4096, min(2 * default_cb, cap))
    chunk_cands = sorted(
        {
            c
            for c in (
                payload // 8, payload // 4, payload // 2, default_cb,
            )
            if 0 < c <= payload // 2
        }
    )
    if not chunk_cands:
        chunk_cands = [max(1, payload // 2)]
    stream_cands = sorted({2, default_st, 8})
    grid = [
        {"chunk_bytes": int(c), "streams": int(s)}
        for c in chunk_cands
        for s in stream_cands
    ]
    state: dict = {}

    def _payload() -> np.ndarray:
        buf = state.get("buf")
        if buf is None:
            rows = max(1, payload // 4096)
            buf = state["buf"] = (
                np.random.default_rng(0)
                .integers(0, 255, size=(rows, 1024), dtype=np.int64)
                .astype(np.float32)
            )
        return buf

    def feats(cand):
        chunks = max(1, -(-payload // max(1, int(cand["chunk_bytes"]))))
        waves = -(-chunks // max(1, int(cand["streams"])))
        # flops 0 (pure data movement); the bytes term prices the link,
        # the dispatch term prices per-chunk submission/latency waves
        return 0.0, float(payload), float(chunks + waves)

    def trial(cand):
        import jax

        buf = _payload()
        row_bytes = buf.itemsize * buf.shape[1]
        rows = max(1, int(cand["chunk_bytes"]) // row_bytes)
        bounds = [
            (lo, min(lo + rows, buf.shape[0]))
            for lo in range(0, buf.shape[0], rows)
        ]
        with ThreadPoolExecutor(
            max_workers=max(1, int(cand["streams"])),
            thread_name_prefix="tft-tune-link",
        ) as pool:
            futs = [
                pool.submit(jax.device_put, buf[lo:hi])
                for lo, hi in bounds
            ]
            for f in futs:
                jax.block_until_ready(f.result())

    return grid, feats, trial


# ---------------------------------------------------------------------------
# pool + plan
# ---------------------------------------------------------------------------

_pool_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_width = 0


def _get_pool(width: Optional[int] = None) -> ThreadPoolExecutor:
    """The shared transfer pool, sized to ``Config.transfer_streams``
    (or the autotuner's winner for this link — ``_link_knobs``; rebuilt
    when the effective width changes; in-flight work on the old pool
    drains, it is never cancelled). Callers that already resolved the
    link knobs pass ``width`` so one transfer op sees ONE consistent
    (chunk, streams) pair instead of re-resolving per helper."""
    global _pool, _pool_width
    if width is None:
        _, width = _link_knobs()
    width = max(1, int(width))
    with _pool_lock:
        if _pool is None or _pool_width != width:
            # the old pool is NOT shut down: an in-flight transfer that
            # grabbed its reference may still submit chunks to it, and
            # submit-after-shutdown raises. Its idle workers linger until
            # process exit — retunes are rare operator actions, and a few
            # parked threads beat crashing a 3 GB upload mid-flight.
            _pool = ThreadPoolExecutor(
                max_workers=width, thread_name_prefix="tft-transfer"
            )
            _pool_width = width
        return _pool


def wire_dtype(host_dtype) -> np.dtype:
    """The dtype a payload crosses the link with: the host dtype, or
    bfloat16 when ``Config.transfer_dtype="bf16"`` and the payload is
    float32 (the halve-the-tunnel-bytes cast; upcast back to float32 on
    device, so only the *values* round — dtypes never change)."""
    from ..utils import get_config

    host_dtype = np.dtype(host_dtype)
    td = get_config().transfer_dtype
    if not td:
        return host_dtype
    if td != "bf16":
        raise ValueError(
            f"unknown Config.transfer_dtype {td!r}; expected '' or 'bf16'"
        )
    if host_dtype == np.float32:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return host_dtype


def _chunk_bounds(
    n_rows: int, row_bytes: int, chunk_bytes: Optional[int] = None
) -> List[Tuple[int, int]]:
    """Row-range chunks for an ``[n_rows, ...]`` transfer. One chunk when
    chunking is off (``transfer_chunk_bytes <= 0``), the payload fits a
    single chunk, or the array is empty/rowless. Chunk size is the
    tuned link value when the autotuner has a winner (``_link_knobs``;
    pass ``chunk_bytes`` when the caller already resolved it)."""
    if chunk_bytes is None:
        chunk_bytes, _ = _link_knobs()
    if n_rows <= 1 or chunk_bytes <= 0 or row_bytes <= 0:
        return [(0, n_rows)]
    rows = max(1, int(chunk_bytes // row_bytes))
    n_chunks = -(-n_rows // rows)
    if n_chunks > _MAX_CHUNKS:
        rows = -(-n_rows // _MAX_CHUNKS)
    if rows >= n_rows:
        return [(0, n_rows)]
    return [(lo, min(lo + rows, n_rows)) for lo in range(0, n_rows, rows)]


def chunk_rows(row_bytes: int) -> int:
    """Rows per transfer chunk for a payload of ``row_bytes`` per row —
    the alignment quantum for consumers that plan their own block loops
    (``engine/ops.py``'s journaled ``map_rows`` caps its block plan at
    this so a journal block never spans transfer chunks and a resumed
    job re-uploads only its own unfinished blocks' bytes). Effectively
    unbounded when chunking is off."""
    chunk_bytes, _ = _link_knobs()
    if chunk_bytes <= 0 or row_bytes <= 0:
        return 1 << 62
    return max(1, int(chunk_bytes // row_bytes))


def _submit(pool, fn, *args):
    """Submit a transfer task carrying the submitting thread's retry
    deadline into the pool thread. The distributed-job worker clips
    each block's retry budget below its lease TTL via a thread-local
    ``retry_deadline`` window (``utils/failures.py``); chunk transfers
    run their ``run_with_retries`` windows on pool threads, where that
    thread-local would otherwise be unset — i.e. unbounded, letting a
    transient burst on the link retry past the TTL while the worker is
    alive and mid-block (presumed dead, fenced)."""
    from ..utils.failures import (
        adopt_retry_deadline,
        current_retry_deadline,
    )

    deadline = current_retry_deadline()
    if deadline is None:
        return pool.submit(fn, *args)

    def run(*a):
        with adopt_retry_deadline(deadline):
            return fn(*a)

    return pool.submit(run, *args)


def _observed(direction: str, fn, what: str):
    """Run one chunk transfer inside its retry window with the chaos
    site, inflight gauge, latency histogram, and byte counter applied.
    ``fn`` must SYNCHRONIZE (return only once the bytes have crossed)
    so retries see transfer failures and the histogram is honest."""
    from ..utils import run_with_retries
    from ..utils.chaos import site as _chaos_site

    site = "frame." + direction
    hist = _h_h2d if direction == "h2d" else _h_d2h
    ctr = _m_h2d if direction == "h2d" else _m_d2h

    def attempt():
        _chaos_site(site)
        return fn()

    _g_inflight.inc()
    try:
        t0 = time.perf_counter()
        out, nbytes = run_with_retries(attempt, what=what)
        hist.observe(time.perf_counter() - t0)
        ctr.inc(nbytes)
        return out
    finally:
        _g_inflight.dec()


# ---------------------------------------------------------------------------
# host -> device
# ---------------------------------------------------------------------------


def _put_chunk(piece: np.ndarray, wire: np.dtype, what: str):
    import jax

    host_dtype = piece.dtype
    if host_dtype != wire:
        # host-side cast BEFORE the link: this is the whole point of
        # transfer_dtype — half the f32 bytes ever enter the tunnel;
        # the upcast back to the host dtype runs on DEVICE below
        piece = piece.astype(wire)

    def go():
        dev = jax.device_put(piece)
        if dev.dtype != host_dtype:
            dev = dev.astype(host_dtype)
        # sync inside the retry window: device_put is async on real
        # runtimes, and an un-synced failure would surface far away
        return jax.block_until_ready(dev), piece.nbytes

    return _observed("h2d", go, what)


class _Resident:
    """Stream interface over an already-device-resident array (the
    degenerate :class:`StreamingUpload`): everything has 'landed'."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def slice(self, lo: int, hi: int):
        a = self.arr
        return a if lo == 0 and hi == a.shape[0] else a[lo:hi]

    def assembled(self):
        return self.arr


class StreamingUpload:
    """One host column crossing the link as concurrent row chunks.

    Construction submits every chunk to the transfer pool immediately
    (``Config.transfer_streams`` in flight at once). Consumers pull
    results at whatever granularity they need:

    - :meth:`slice` ``(lo, hi)`` waits only for the chunks covering that
      row range — a block loop computing on rows [0, c) runs while rows
      [c, 2c) are still in the air (upload/compute overlap);
    - :meth:`assembled` waits for everything and returns the full column
      as one device array (a jit-cached on-device concat), memoized — the
      drop-in replacement for the old monolithic ``device_put``.

    Byte-identity with the monolithic path holds whenever no
    ``transfer_dtype`` wire cast applies (device_put of row slices
    followed by an on-device concat moves exactly the same bytes).
    """

    __slots__ = ("arr", "wire", "bounds", "what", "_futs", "_chunks",
                 "_assembled", "_lock")

    def __init__(self, arr: np.ndarray, what: str = "column"):
        self.arr = arr
        self.wire = wire_dtype(arr.dtype)
        # resolve the link knobs ONCE per upload: bounds and pool width
        # must come from the same (chunk, streams) pair even if a tuned
        # winner lands mid-transfer
        chunk_bytes, streams = _link_knobs()
        if arr.ndim == 0:
            # scalars cross whole (they cannot be row-sliced); d2h has
            # the symmetric case
            self.bounds = [(0, 1)]
        else:
            row_bytes = self.wire.itemsize * int(
                np.prod(arr.shape[1:], initial=1)
            )
            self.bounds = _chunk_bounds(
                int(arr.shape[0]), row_bytes, chunk_bytes
            )
        self.what = what
        self._chunks: List[Any] = [None] * len(self.bounds)
        self._assembled = None
        self._lock = threading.Lock()
        pool = _get_pool(streams)
        self._futs = [
            _submit(
                pool,
                _put_chunk,
                arr[lo:hi] if arr.ndim else arr,
                self.wire,
                f"frame.h2d {what} chunk {i}/{len(self.bounds)}",
            )
            for i, (lo, hi) in enumerate(self.bounds)
        ]

    @property
    def num_chunks(self) -> int:
        return len(self.bounds)

    def chunk(self, i: int):
        """Device array for chunk ``i`` (blocks until it has landed), or
        ``None`` once :meth:`assembled` has collapsed the chunks (the
        caller falls back to slicing the assembled column). Future waits
        happen OUTSIDE the lock so concurrent consumers overlap."""
        with self._lock:
            if self._assembled is not None:
                return None
            c = self._chunks[i]
            fut = self._futs[i]
        if c is not None:
            return c
        c = fut.result()
        with self._lock:
            if self._assembled is None:
                self._chunks[i] = c
        return c

    def slice(self, lo: int, hi: int):
        """Device array for rows ``[lo, hi)``, waiting only on the
        chunks that cover the range. Matches the ``_block_feeder``
        slicer contract: the full range returns the assembled column
        itself (no extra on-device copy)."""
        if self.arr.ndim == 0:
            return self.assembled()
        n = int(self.arr.shape[0])
        if lo == 0 and hi == n:
            return self.assembled()
        with self._lock:
            asm = self._assembled
            futs = self._futs
        if asm is None and futs and all(f.done() for f in futs):
            # everything has landed: assemble once so chained passes
            # slice one array instead of re-concatenating chunks
            asm = self.assembled()
        if asm is not None:
            return asm[lo:hi]
        pieces = []
        for i, (clo, chi) in enumerate(self.bounds):
            if chi <= lo or clo >= hi:
                continue
            dev = self.chunk(i)
            if dev is None:  # a concurrent assembled() collapsed chunks
                return self.assembled()[lo:hi]
            a, b = max(lo - clo, 0), min(hi, chi) - clo
            pieces.append(
                dev if (a == 0 and b == chi - clo) else dev[a:b]
            )
        if len(pieces) == 1:
            return pieces[0]
        import jax.numpy as jnp

        return jnp.concatenate(pieces, axis=0)

    def assembled(self):
        """The whole column on device (memoized). Waits for every chunk;
        multi-chunk uploads concatenate once on device."""
        with self._lock:
            if self._assembled is not None:
                return self._assembled
        # collect OUTSIDE the lock (future waits can be long); chunks are
        # still present because only the winner below drops them
        chunks = [self.chunk(i) for i in range(len(self.bounds))]
        with self._lock:
            if self._assembled is None:
                if None in chunks:  # another thread won and collapsed
                    raise AssertionError("assembled state torn")
                if len(chunks) == 1:
                    self._assembled = chunks[0]
                else:
                    import jax.numpy as jnp

                    self._assembled = jnp.concatenate(chunks, axis=0)
                # drop per-chunk refs (futures included — a future pins
                # its result): once assembled exists the chunk buffers
                # would otherwise hold 2x the column in HBM
                self._chunks = [None] * len(self.bounds)
                self._futs = []
            return self._assembled


def h2d(arr: np.ndarray, what: str = "feed"):
    """Move one host array to device: chunked + concurrent when it
    exceeds ``transfer_chunk_bytes``, monolithic otherwise — either way
    retried per chunk, chaos-injectable at ``frame.h2d``, and counted.
    Synchronous (returns once every byte has crossed)."""
    with _span("frame.h2d", bytes=int(arr.nbytes)):
        return StreamingUpload(arr, what=what).assembled()


# ---------------------------------------------------------------------------
# device -> host
# ---------------------------------------------------------------------------


class _PendingFetch:
    """Handle for an in-flight chunked d2h: ``result()`` waits for every
    chunk and returns the assembled host array."""

    __slots__ = ("_out", "_futs")

    def __init__(self, out, futs):
        self._out = out
        self._futs = futs

    def result(self) -> np.ndarray:
        for f in self._futs:
            f.result()
        return self._out


class _WholeFetch:
    """Handle for an un-chunked d2h (scalar / single-chunk / sharded)."""

    __slots__ = ("_fut",)

    def __init__(self, fut):
        self._fut = fut

    def result(self) -> np.ndarray:
        return self._fut.result()


def d2h_async(dev, what: str = "column"):
    """Start fetching a device array to host as concurrent chunks;
    returns immediately with a handle whose ``result()`` blocks. The
    caller can keep dispatching compute while the fetch drains — the
    streaming replacement for ``copy_to_host_async`` double-buffering
    (which the round-5 bench measured costing more than it overlapped)."""
    import jax

    dtype = np.dtype(dev.dtype)
    shape = tuple(dev.shape)
    multi_device = False
    try:
        multi_device = len(dev.devices()) > 1
    except Exception:
        pass
    # one knob resolution per fetch (bounds + pool width stay a
    # consistent pair; see StreamingUpload)
    chunk_bytes, streams = _link_knobs()
    bounds = (
        [(0, 0)]
        if not shape
        else _chunk_bounds(
            shape[0],
            dtype.itemsize * int(np.prod(shape[1:], initial=1)),
            chunk_bytes,
        )
    )
    if not shape or multi_device or len(bounds) == 1:
        # scalars and single-chunk payloads fetch whole; sharded arrays
        # (virtual meshes, multihost) keep the single gather — per-chunk
        # slicing of a distributed array would route every chunk through
        # a cross-device gather
        def fetch_whole():
            arr = np.asarray(dev)
            return arr, arr.nbytes

        return _WholeFetch(
            _submit(
                _get_pool(streams),
                _observed, "d2h", fetch_whole, f"frame.d2h {what}",
            )
        )
    out = np.empty(shape, dtype)

    def fetch(i, lo, hi):
        def go():
            piece = np.asarray(jax.block_until_ready(dev[lo:hi]))
            return piece, piece.nbytes

        out[lo:hi] = _observed(
            "d2h", go, f"frame.d2h {what} chunk {i}/{len(bounds)}"
        )

    pool = _get_pool(streams)
    futs = [
        _submit(pool, fetch, i, lo, hi)
        for i, (lo, hi) in enumerate(bounds)
    ]
    return _PendingFetch(out, futs)


def d2h(dev, what: str = "column") -> np.ndarray:
    """Fetch a device array to host (chunked + concurrent + retried);
    blocks until complete. Byte-identical to ``np.asarray(dev)``."""
    with _span("frame.d2h", bytes=int(np.dtype(dev.dtype).itemsize
                                      * int(np.prod(dev.shape, initial=1)))):
        return d2h_async(dev, what=what).result()
