"""Scalar-type registry.

TPU-native analog of the reference's ``SupportedOperations`` registry
(``/root/reference/src/main/scala/org/tensorframes/impl/datatypes.scala:27-52,
265-324``), which maps every supported scalar between four type systems
(Spark SQL, protobuf, TF-Java, an internal ADT). Here the systems are simpler:
Python scalars / numpy dtypes / JAX dtypes / an internal :class:`ScalarType`.

Reference parity set: float64, float32, int32, int64, binary
(``datatypes.scala:265-267``) — binary supports row ops on single cells only
(``datatypes.scala:578-599``). TPU-first extras beyond the reference:
bfloat16 (the MXU-native dtype), float16, bool, int8/uint8 — these exist so
user programs can down-cast into the fast path without leaving the framework.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "ScalarType",
    "FLOAT64",
    "FLOAT32",
    "BFLOAT16",
    "FLOAT16",
    "INT64",
    "INT32",
    "INT8",
    "UINT8",
    "BOOL",
    "BINARY",
    "REFERENCE_PARITY_TYPES",
    "supported_types",
    "for_numpy_dtype",
    "for_any",
    "for_name",
    "has_ops",
]


@dataclasses.dataclass(frozen=True)
class ScalarType:
    """One supported scalar type (analog of ``ScalarTypeOperation[T]``,
    reference ``datatypes.scala:60-152``).

    Attributes:
        name: canonical short name (also the SQL-ish name used in ``explain``).
        np_dtype: the numpy dtype backing host buffers, or ``None`` for binary.
        supports_blocks: False for types that only work in row ops on single
            cells (binary; reference ``datatypes.scala:578-581``).
        is_64bit: needs ``jax_enable_x64`` on device.
        sql_name: pretty name used by the schema printer, matching the
            reference's Spark-SQL names in ``print_schema`` output.
    """

    name: str
    np_dtype: Optional[np.dtype]
    supports_blocks: bool = True
    is_64bit: bool = False
    sql_name: str = ""

    def __post_init__(self):
        if not self.sql_name:
            object.__setattr__(self, "sql_name", self.name)

    @property
    def jax_dtype(self):
        """The on-device dtype. Import is deferred so the schema core stays
        importable without initializing a JAX backend."""
        if self.np_dtype is None:
            raise TypeError(f"{self.name} has no device dtype")
        if self.name == "bfloat16":
            import jax.numpy as jnp

            return jnp.bfloat16
        return self.np_dtype

    def zero(self) -> Any:
        if self.np_dtype is None:
            return b""
        return self.np_dtype.type(0)

    def __repr__(self) -> str:
        return f"ScalarType({self.name})"


def _np(x) -> np.dtype:
    return np.dtype(x)


FLOAT64 = ScalarType("float64", _np(np.float64), is_64bit=True, sql_name="DoubleType")
FLOAT32 = ScalarType("float32", _np(np.float32), sql_name="FloatType")
# np.dtype for bfloat16 comes from ml_dtypes (vendored by jax); fall back to
# float32 host storage if unavailable.
try:
    import ml_dtypes as _ml_dtypes

    _BF16_NP: Optional[np.dtype] = _np(_ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16_NP = None
BFLOAT16 = ScalarType("bfloat16", _BF16_NP or _np(np.float32), sql_name="BFloat16Type")
FLOAT16 = ScalarType("float16", _np(np.float16), sql_name="HalfType")
INT64 = ScalarType("int64", _np(np.int64), is_64bit=True, sql_name="LongType")
INT32 = ScalarType("int32", _np(np.int32), sql_name="IntegerType")
INT8 = ScalarType("int8", _np(np.int8), sql_name="ByteType")
UINT8 = ScalarType("uint8", _np(np.uint8), sql_name="UByteType")
BOOL = ScalarType("bool", _np(np.bool_), sql_name="BooleanType")
BINARY = ScalarType("binary", None, supports_blocks=False, sql_name="BinaryType")

#: The exact set the reference supports (``datatypes.scala:265-267``).
REFERENCE_PARITY_TYPES = (FLOAT64, FLOAT32, INT32, INT64, BINARY)

_ALL = (
    FLOAT64,
    FLOAT32,
    BFLOAT16,
    FLOAT16,
    INT64,
    INT32,
    INT8,
    UINT8,
    BOOL,
    BINARY,
)

_BY_NAME: Dict[str, ScalarType] = {t.name: t for t in _ALL}
_BY_NP: Dict[np.dtype, ScalarType] = {}
for _t in _ALL:
    if _t.np_dtype is not None and _t.np_dtype not in _BY_NP:
        _BY_NP[_t.np_dtype] = _t


def supported_types():
    """All registered scalar types (analog of
    ``MetadataConstants.supportedTypes``, reference
    ``MetadataConstants.scala:23-33``)."""
    return _ALL


def for_name(name: str) -> ScalarType:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"Unknown scalar type {name!r}; supported: {sorted(_BY_NAME)}"
        ) from None


def for_numpy_dtype(dt) -> ScalarType:
    dt = np.dtype(dt)
    try:
        return _BY_NP[dt]
    except KeyError:
        raise KeyError(
            f"numpy dtype {dt} is not supported by tensorframes_tpu; "
            f"supported: {sorted(t.name for t in _ALL)}"
        ) from None


def for_any(x) -> ScalarType:
    """Resolve a ScalarType from any of: ScalarType, name, numpy dtype,
    python scalar/value (analog of the multi-keyed lookups in reference
    ``datatypes.scala:275-315``)."""
    if isinstance(x, ScalarType):
        return x
    if isinstance(x, str):
        # may be a type name or a numpy dtype string
        if x in _BY_NAME:
            return _BY_NAME[x]
        return for_numpy_dtype(x)
    if isinstance(x, (bytes, bytearray)):
        return BINARY
    if isinstance(x, bool):
        return BOOL
    if isinstance(x, int):
        return INT64
    if isinstance(x, float):
        return FLOAT64
    # guard against dtype *classes* (np.float64 etc.), whose `dtype` attr is
    # a descriptor, not a dtype — for_numpy_dtype handles them directly
    if hasattr(x, "dtype") and not isinstance(x, type):
        return for_numpy_dtype(x.dtype)
    return for_numpy_dtype(x)


def has_ops(x) -> bool:
    """True if ``x`` is a scalar value of a supported type (analog of
    ``SupportedOperations.hasOps``, reference ``datatypes.scala:292-298``)."""
    try:
        for_any(x)
        return True
    except (KeyError, TypeError):
        return False
