"""Whole-frame schema view + pretty printer.

Analog of the reference's ``DataFrameInfo``
(``/root/reference/src/main/scala/org/tensorframes/DataFrameInfo.scala:7-39``)
and the ``explain`` output consumed by ``tfs.print_schema``
(``DebugRowOps.scala:528-545``, ``core.py:351-360``).
"""

from __future__ import annotations

from typing import List, Sequence

from .column_info import ColumnInfo

__all__ = ["FrameInfo"]


class FrameInfo:
    """Ordered collection of :class:`ColumnInfo` for one frame."""

    def __init__(self, cols: Sequence[ColumnInfo]):
        self.cols: List[ColumnInfo] = list(cols)
        names = [c.name for c in self.cols]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate column names: {names}")

    def __iter__(self):
        return iter(self.cols)

    def __len__(self):
        return len(self.cols)

    def __getitem__(self, name: str) -> ColumnInfo:
        for c in self.cols:
            if c.name == name:
                return c
        raise KeyError(name)

    def __contains__(self, name: str) -> bool:
        return any(c.name == name for c in self.cols)

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.cols]

    def explain(self) -> str:
        """Schema string in the reference's ``print_schema`` format
        (cf. ``README.md:105-108``)::

            root
             |-- y: array (nullable = false) DoubleType[?,2]
        """
        lines = ["root"] + [c.explain_line() for c in self.cols]
        return "\n".join(lines)

    def __repr__(self) -> str:
        return f"FrameInfo({', '.join(f'{c.name}:{c.scalar_type.name}{c.block_shape}' for c in self.cols)})"
