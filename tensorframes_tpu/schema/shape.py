"""Tensor shapes with unknown dimensions.

TPU-native analog of the reference's shape core
(``/root/reference/src/main/scala/org/tensorframes/Shape.scala:16-109``).
The reference models every column as a tensor whose leading dimension is the
(unknown) number of rows; unknown dims are encoded as ``-1``.

On TPU the distinction matters more than it did on the reference's CPU path:
XLA compiles one program per concrete shape, so ``Unknown`` dims mark exactly
the axes the engine must bucket/pad (see ``tensorframes_tpu.engine``) or make
symbolic (see ``tensorframes_tpu.capture.serialize``).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple, Union

from numpy import integer as _np_integer

__all__ = ["Unknown", "Shape", "HighDimException"]

#: Sentinel for an unknown dimension (reference ``Shape.scala:88-89``).
Unknown: int = -1


class HighDimException(ValueError):
    """Raised when a tensor of unsupported order is requested.

    Mirrors ``HighDimException`` (reference ``Shape.scala:129-130``): cell
    payloads are limited to order <= 2 (scalars, vectors, matrices), matching
    the reference's converter support (``datatypes.scala:123-124``,
    ``DataOps.scala:162-165``).
    """

    def __init__(self, shape: "Shape"):
        self.shape = shape
        super().__init__(
            f"Shape {shape} is too high-dimensional - tensorframes_tpu only "
            f"supports cell tensors of order <= 2 (matrices)"
        )


class Shape:
    """An N-d tensor shape where each dim is a non-negative int or ``Unknown``.

    Immutable and hashable. Analog of reference ``Shape``
    (``Shape.scala:16-109``), with the same operations: ``prepend``, ``tail``,
    ``drop_inner``, ``num_elements``, ``check_more_precise_than``.
    """

    __slots__ = ("_dims",)

    def __init__(self, *dims: Union[int, Iterable[int]]):
        if len(dims) == 1 and not isinstance(dims[0], (int, _np_integer)):
            dims = tuple(dims[0])  # Shape([1, 2]) / Shape((1, 2))
        ds = []
        for d in dims:
            d = int(d)
            if d < -1:
                raise ValueError(f"Shape dims must be >= -1, got {d} in {dims}")
            ds.append(d)
        self._dims: Tuple[int, ...] = tuple(ds)

    # -- accessors ---------------------------------------------------------

    @property
    def dims(self) -> Tuple[int, ...]:
        return self._dims

    @property
    def num_dims(self) -> int:
        return len(self._dims)

    @property
    def has_unknown(self) -> bool:
        return Unknown in self._dims

    @property
    def num_elements(self) -> Optional[int]:
        """Total element count, or ``None`` if any dim is unknown
        (reference ``Shape.scala:28``)."""
        if self.has_unknown:
            return None
        n = 1
        for d in self._dims:
            n *= d
        return n

    # -- transforms --------------------------------------------------------

    def prepend(self, x: int) -> "Shape":
        """Shape with an extra leading dimension (``Shape.scala:37-39``)."""
        return Shape((int(x),) + self._dims)

    def tail(self) -> "Shape":
        """Shape with the first dimension dropped (``Shape.scala:49``)."""
        return Shape(self._dims[1:])

    def drop_inner(self) -> "Shape":
        """Shape with the innermost dimension dropped (``Shape.scala:44``)."""
        return Shape(self._dims[:-1])

    def with_lead(self, x: int) -> "Shape":
        """Shape with the leading dimension replaced by ``x``."""
        if not self._dims:
            raise ValueError("cannot replace lead dim of a scalar shape")
        return Shape((int(x),) + self._dims[1:])

    # -- predicates --------------------------------------------------------

    def check_more_precise_than(self, other: "Shape") -> bool:
        """True if ``self`` is a valid refinement of ``other``: same rank, and
        every dim of ``other`` is either ``Unknown`` or equal
        (reference ``Shape.scala:54-59``)."""
        if self.num_dims != other.num_dims:
            return False
        return all(b == Unknown or b == a for a, b in zip(self._dims, other._dims))

    def merge(self, other: "Shape") -> Optional["Shape"]:
        """Dim-wise merge used by ``analyze``: equal dims kept, mismatched dims
        become ``Unknown``; rank mismatch yields ``None``
        (reference ``ExperimentalOperations.scala:147-157``)."""
        if self.num_dims != other.num_dims:
            return None
        return Shape(
            a if a == b else Unknown for a, b in zip(self._dims, other._dims)
        )

    # -- conversions -------------------------------------------------------

    def to_concrete(self, fill: int = 1) -> Tuple[int, ...]:
        """Concrete tuple with unknowns replaced by ``fill`` (for probing)."""
        return tuple(fill if d == Unknown else d for d in self._dims)

    def to_jax(self) -> Tuple[Optional[int], ...]:
        """JAX/numpy convention: unknowns become ``None``."""
        return tuple(None if d == Unknown else d for d in self._dims)

    @staticmethod
    def from_jax(dims: Sequence[Optional[int]]) -> "Shape":
        """From the ``None``-for-unknown convention (numpy/TF/JAX style)."""
        return Shape(Unknown if d is None else int(d) for d in dims)

    @staticmethod
    def empty() -> "Shape":
        """The scalar shape (rank 0; reference ``Shape.scala:91``)."""
        return Shape()

    # -- dunder ------------------------------------------------------------

    def __iter__(self):
        return iter(self._dims)

    def __len__(self) -> int:
        return len(self._dims)

    def __getitem__(self, i):
        return self._dims[i]

    def __eq__(self, other) -> bool:
        if isinstance(other, Shape):
            return self._dims == other._dims
        if isinstance(other, (tuple, list)):
            return self._dims == tuple(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self._dims)

    def __repr__(self) -> str:
        inner = ",".join("?" if d == Unknown else str(d) for d in self._dims)
        return f"[{inner}]"
