"""Schema core: shapes, scalar types, column/frame metadata.

TPU-native analog of the reference's L1 schema layer
(``/root/reference/src/main/scala/org/tensorframes/{Shape,ColumnInformation,
DataFrameInfo,MetadataConstants}.scala``).
"""

from .shape import Shape, Unknown, HighDimException
from .dtypes import (
    ScalarType,
    FLOAT64,
    FLOAT32,
    BFLOAT16,
    FLOAT16,
    INT64,
    INT32,
    INT8,
    UINT8,
    BOOL,
    BINARY,
    REFERENCE_PARITY_TYPES,
    supported_types,
    for_numpy_dtype,
    for_any,
    for_name,
    has_ops,
)
from .column_info import ColumnInfo, TensorInfo
from .frame_info import FrameInfo

__all__ = [
    "Shape",
    "Unknown",
    "HighDimException",
    "ScalarType",
    "FLOAT64",
    "FLOAT32",
    "BFLOAT16",
    "FLOAT16",
    "INT64",
    "INT32",
    "INT8",
    "UINT8",
    "BOOL",
    "BINARY",
    "REFERENCE_PARITY_TYPES",
    "supported_types",
    "for_numpy_dtype",
    "for_any",
    "for_name",
    "has_ops",
    "ColumnInfo",
    "TensorInfo",
    "FrameInfo",
]
