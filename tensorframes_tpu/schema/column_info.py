"""Per-column tensor metadata.

Analog of the reference's ``ColumnInformation`` + ``SparkTFColInfo``
(``/root/reference/src/main/scala/org/tensorframes/ColumnInformation.scala:8-139``,
``Shape.scala:120-123``). The reference smuggles tensor info through Spark's
``StructField.metadata`` under the keys ``org.spartf.shape`` /
``org.sparktf.type`` (``MetadataConstants.scala:9-21``); here columns are
first-class objects so the info lives directly on :class:`ColumnInfo`.

Conventions (identical to the reference):
- ``block_shape`` always includes the leading row dimension, usually
  ``Unknown`` (number of rows in a block is not statically known).
- ``cell_shape`` is ``block_shape.tail()``: the shape of one row's payload.
- a column with no analyzed info still has a *minimal* shape inferred from
  its storage nesting: each ragged/list nesting level contributes an
  ``Unknown`` dim (``ColumnInformation.scala:99-126``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .dtypes import ScalarType
from .shape import Shape, Unknown

__all__ = ["ColumnInfo", "TensorInfo"]

#: metadata keys, kept for (de)serialization parity with the reference
#: (``MetadataConstants.scala:15-21``).
SHAPE_KEY = "tfs_tpu.shape"
TYPE_KEY = "tfs_tpu.type"


@dataclasses.dataclass(frozen=True)
class TensorInfo:
    """shape + scalar type of a column's tensor content (analog of
    ``SparkTFColInfo``, reference ``Shape.scala:120-123``). ``shape`` is the
    block shape (lead dim = rows)."""

    shape: Shape
    scalar_type: ScalarType


@dataclasses.dataclass(frozen=True)
class ColumnInfo:
    """A named column plus (optionally analyzed) tensor info."""

    name: str
    scalar_type: ScalarType
    #: analyzed block shape; ``None`` when only the storage-level minimal
    #: shape is known (reference: absent metadata).
    analyzed_shape: Optional[Shape] = None
    #: number of list-nesting levels in the storage (0 = scalar column,
    #: 1 = vector column, ...); determines the minimal shape.
    nesting: int = 0
    nullable: bool = False

    @property
    def block_shape(self) -> Shape:
        """The best-known block shape: analyzed if available, else minimal
        from storage nesting with all dims Unknown
        (reference ``ColumnInformation.scala:99-126``)."""
        if self.analyzed_shape is not None:
            return self.analyzed_shape
        return Shape([Unknown] * (self.nesting + 1))

    @property
    def cell_shape(self) -> Shape:
        return self.block_shape.tail()

    @property
    def tensor_info(self) -> TensorInfo:
        return TensorInfo(self.block_shape, self.scalar_type)

    def with_analyzed(self, shape: Shape) -> "ColumnInfo":
        return dataclasses.replace(self, analyzed_shape=shape)

    def with_name(self, name: str) -> "ColumnInfo":
        return dataclasses.replace(self, name=name)

    # -- explain formatting (matches reference print_schema output style,
    # -- e.g. " |-- y: array (nullable = false) DoubleType[?,2]") ----------

    def sql_kind(self) -> str:
        if self.scalar_type.name == "binary":
            return "binary"
        if self.nesting == 0:
            return self.scalar_type.sql_name.replace("Type", "").lower()
        return "array"

    def explain_line(self) -> str:
        shape = self.block_shape
        return (
            f" |-- {self.name}: {self.sql_kind()} "
            f"(nullable = {str(self.nullable).lower()}) "
            f"{self.scalar_type.sql_name}{shape}"
        )

    # -- metadata round-trip (parity with the reference's metadata embed,
    # -- ``ColumnInformation.scala:35-56``) --------------------------------

    def to_metadata(self) -> dict:
        md = {TYPE_KEY: self.scalar_type.name, "nesting": self.nesting}
        if self.analyzed_shape is not None:
            md[SHAPE_KEY] = list(self.analyzed_shape.dims)
        return md

    @staticmethod
    def from_metadata(name: str, md: dict) -> "ColumnInfo":
        from .dtypes import for_name

        shape = Shape(md[SHAPE_KEY]) if SHAPE_KEY in md else None
        return ColumnInfo(
            name=name,
            scalar_type=for_name(md[TYPE_KEY]),
            analyzed_shape=shape,
            nesting=int(md.get("nesting", 0)),
        )
