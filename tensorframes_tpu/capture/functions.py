"""DSL function library.

Analog of the reference's TF-python-lookalike package object
(``/root/reference/src/main/scala/org/tensorframes/dsl/package.scala:16-132``:
``placeholder, constant, zeros, ones, fill, identity, add, div, reduce_min,
reduce_sum``) — extended well beyond it, since each entry here is one line
over ``jax.numpy`` instead of a hand-built NodeDef emitter. Anything not
listed is reachable via :func:`tensorframes_tpu.capture.dsl.apply_op`.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from .dsl import Node, apply_op, constant, _lift

__all__ = [
    "identity",
    "add",
    "sub",
    "mul",
    "div",
    "minimum",
    "maximum",
    "matmul",
    "exp",
    "log",
    "sqrt",
    "square",
    "abs_",
    "neg",
    "tanh",
    "sigmoid",
    "relu",
    "softmax",
    "cast",
    "reshape",
    "transpose",
    "concat",
    "stack",
    "reduce_sum",
    "reduce_min",
    "reduce_max",
    "reduce_mean",
    "reduce_prod",
    "argmin",
    "argmax",
    "greater",
    "less",
    "equal",
    "where",
    "zeros",
    "ones",
    "fill",
    "unsorted_segment_sum",
    "expand_dims",
    "squeeze",
]


def _axis_tuple(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def identity(x, name: Optional[str] = None) -> Node:
    return apply_op(lambda a: a, x, op_name="identity", name=name)


def add(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a + b, x, y, op_name="add", name=name)


def sub(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a - b, x, y, op_name="sub", name=name)


def mul(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a * b, x, y, op_name="mul", name=name)


def div(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a / b, x, y, op_name="div", name=name)


def minimum(x, y, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(jnp.minimum, x, y, op_name="minimum", name=name)


def maximum(x, y, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(jnp.maximum, x, y, op_name="maximum", name=name)


def matmul(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a @ b, x, y, op_name="matmul", name=name)


def _unary(jnp_name: str, op_name: str):
    def f(x, name: Optional[str] = None) -> Node:
        import jax.numpy as jnp

        return apply_op(getattr(jnp, jnp_name), x, op_name=op_name, name=name)

    f.__name__ = op_name
    return f


exp = _unary("exp", "exp")
log = _unary("log", "log")
sqrt = _unary("sqrt", "sqrt")
square = _unary("square", "square")
abs_ = _unary("abs", "abs")
neg = _unary("negative", "neg")
tanh = _unary("tanh", "tanh")


def sigmoid(x, name: Optional[str] = None) -> Node:
    import jax

    return apply_op(jax.nn.sigmoid, x, op_name="sigmoid", name=name)


def relu(x, name: Optional[str] = None) -> Node:
    import jax

    return apply_op(jax.nn.relu, x, op_name="relu", name=name)


def softmax(x, axis: int = -1, name: Optional[str] = None) -> Node:
    import jax

    return apply_op(
        lambda a: jax.nn.softmax(a, axis=axis), x, op_name="softmax", name=name
    )


def cast(x, dtype, name: Optional[str] = None) -> Node:
    from ..schema import for_any

    st = for_any(dtype)
    return apply_op(
        lambda a: a.astype(st.jax_dtype), x, op_name="cast", name=name
    )


def reshape(x, shape: Sequence[int], name: Optional[str] = None) -> Node:
    shp = tuple(int(s) for s in shape)
    return apply_op(lambda a: a.reshape(shp), x, op_name="reshape", name=name)


def transpose(x, axes=None, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda a: jnp.transpose(a, axes), x, op_name="transpose", name=name
    )


def concat(xs: Sequence, axis: int = 0, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda *vs: jnp.concatenate(vs, axis=axis),
        *xs,
        op_name="concat",
        name=name,
    )


def stack(xs: Sequence, axis: int = 0, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda *vs: jnp.stack(vs, axis=axis), *xs, op_name="stack", name=name
    )


def _reducer(jnp_name: str, op_name: str):
    def f(x, axis=None, keepdims: bool = False, name: Optional[str] = None) -> Node:
        import jax.numpy as jnp

        ax = _axis_tuple(axis)
        return apply_op(
            lambda a: getattr(jnp, jnp_name)(a, axis=ax, keepdims=keepdims),
            x,
            op_name=op_name,
            name=name,
        )

    f.__name__ = op_name
    return f


reduce_sum = _reducer("sum", "reduce_sum")
reduce_min = _reducer("min", "reduce_min")
reduce_max = _reducer("max", "reduce_max")
reduce_mean = _reducer("mean", "reduce_mean")
reduce_prod = _reducer("prod", "reduce_prod")


def argmin(x, axis: int = 0, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda a: jnp.argmin(a, axis=axis).astype(jnp.int32),
        x,
        op_name="argmin",
        name=name,
    )


def argmax(x, axis: int = 0, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda a: jnp.argmax(a, axis=axis).astype(jnp.int32),
        x,
        op_name="argmax",
        name=name,
    )


def greater(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a > b, x, y, op_name="greater", name=name)


def less(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a < b, x, y, op_name="less", name=name)


def equal(x, y, name: Optional[str] = None) -> Node:
    return apply_op(lambda a, b: a == b, x, y, op_name="equal", name=name)


def where(cond, x, y, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(jnp.where, cond, x, y, op_name="where", name=name)


def zeros(shape: Sequence[int], dtype=np.float64, name: Optional[str] = None) -> Node:
    return constant(np.zeros(tuple(shape), dtype=np.dtype(dtype)), name=name)


def ones(shape: Sequence[int], dtype=np.float64, name: Optional[str] = None) -> Node:
    return constant(np.ones(tuple(shape), dtype=np.dtype(dtype)), name=name)


def fill(shape: Sequence[int], value, name: Optional[str] = None) -> Node:
    arr = np.full(tuple(shape), value)
    return constant(arr, name=name)


def unsorted_segment_sum(
    data, segment_ids, num_segments: int, name: Optional[str] = None
) -> Node:
    """Segment sum with a static segment count — the op the reference's
    optimized k-means uses to pre-aggregate inside the graph
    (``kmeans_demo.py:128-146``). Lowers to ``jax.ops.segment_sum``."""
    import jax

    return apply_op(
        lambda d, s: jax.ops.segment_sum(d, s, num_segments=num_segments),
        data,
        segment_ids,
        op_name="unsorted_segment_sum",
        name=name,
    )


def expand_dims(x, axis: int = 0, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda a: jnp.expand_dims(a, axis), x, op_name="expand_dims", name=name
    )


def squeeze(x, axis=None, name: Optional[str] = None) -> Node:
    import jax.numpy as jnp

    return apply_op(
        lambda a: jnp.squeeze(a, axis=axis), x, op_name="squeeze", name=name
    )
