"""Program capture: DSL, function frontend, analysis, serialization.

TPU-native analog of the reference's graph layer — GraphDef construction
(``dsl/``), driver-side analysis (``TensorFlowOps.analyzeGraphTF``) and
serialized interchange (``SerializedGraph``).
"""

from .graph import CapturedGraph, TensorSpec, GraphNodeSummary, analysis_specs
from .dsl import (
    Node,
    graph,
    scope,
    placeholder,
    block,
    row,
    constant,
    build_graph,
    apply_op,
)
from .serialize import serialize_graph, deserialize_graph, save_graph, load_graph
from . import functions

__all__ = [
    "CapturedGraph",
    "TensorSpec",
    "GraphNodeSummary",
    "analysis_specs",
    "Node",
    "graph",
    "scope",
    "placeholder",
    "block",
    "row",
    "constant",
    "build_graph",
    "apply_op",
    "serialize_graph",
    "deserialize_graph",
    "save_graph",
    "load_graph",
    "functions",
]
