"""Lazy op-builder DSL.

Analog of the reference's Scala DSL
(``/root/reference/src/main/scala/org/tensorframes/dsl/``): users build a
small graph of named nodes from frame columns, then hand fetches to
``map_blocks``/``reduce_blocks``/etc. Nodes here lower to ``jax.numpy``
calls evaluated inside one jitted program, so the "graph" is only a naming
and wiring layer — XLA does the real graph work.

Naming follows the reference (``dsl/Paths.scala:40-55``): per-graph
auto-numbered op names (``add``, ``add_1``, ...) with ``/``-joined scopes;
unlike the reference's explicitly non-thread-safe global state
(``Paths.scala:10-12``), graph state here is thread-local.

The auto-placeholder helpers ``block(df, col)`` / ``row(df, col)`` mirror
``tfs.block``/``tfs.row`` (reference ``core.py:397-450``): shape inferred
from column metadata; block lead dim is always Unknown (``core.py:446-449``).
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..schema import ScalarType, Shape, Unknown, for_any
from .graph import CapturedGraph, TensorSpec

__all__ = [
    "Node",
    "graph",
    "scope",
    "placeholder",
    "block",
    "row",
    "constant",
    "build_graph",
    "apply_op",
]


class _GraphState:
    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.scopes: List[str] = []

    def fresh(self, base: str) -> str:
        path = "/".join(self.scopes + [base])
        n = self.counters.get(path, 0)
        self.counters[path] = n + 1
        return path if n == 0 else f"{path}_{n}"

    def scoped(self, name: str) -> str:
        return "/".join(self.scopes + [name])


_tls = threading.local()


def _state() -> _GraphState:
    st = getattr(_tls, "state", None)
    if st is None:
        st = _GraphState()
        _tls.state = st
    return st


@contextlib.contextmanager
def graph():
    """Fresh name-counter scope (analog of ``tf.withGraph``,
    reference ``dsl/package.scala:31-35``). Recommended around each op to
    keep auto-numbering deterministic."""
    old = getattr(_tls, "state", None)
    _tls.state = _GraphState()
    try:
        yield
    finally:
        _tls.state = old


@contextlib.contextmanager
def scope(name: str):
    """Name scope (reference ``dsl/package.scala:22-28``)."""
    st = _state()
    st.scopes.append(name)
    try:
        yield
    finally:
        st.scopes.pop()


class Node:
    """One lazy op. ``fn`` consumes the parents' values (jnp arrays) and
    produces this node's value; placeholders/constants carry metadata
    instead (analog of reference ``dsl/Operation.scala:15-58``)."""

    __slots__ = ("name", "op_name", "parents", "fn", "ph_spec", "value", "__weakref__")

    #: numpy must defer to Node's reflected operators instead of
    #: broadcasting elementwise into an object array of Nodes
    __array_ufunc__ = None

    def __init__(
        self,
        op_name: str,
        parents: Sequence["Node"],
        fn: Optional[Callable],
        name: Optional[str] = None,
        ph_spec: Optional[TensorSpec] = None,
        value: Optional[np.ndarray] = None,
    ):
        self.op_name = op_name
        self.parents = list(parents)
        self.fn = fn
        self.ph_spec = ph_spec
        self.value = value
        self.name = _state().scoped(name) if name else _state().fresh(op_name)

    # -- naming ------------------------------------------------------------

    def named(self, name: str) -> "Node":
        """Rename (reference ``named``, ``dsl/Operation.scala:44-47``).
        Placeholder renames also rebind the placeholder name; the column
        binding (original column) is preserved via inputs_map at capture."""
        self.name = _state().scoped(name)
        return self

    @property
    def is_placeholder(self) -> bool:
        return self.ph_spec is not None

    # -- operators ---------------------------------------------------------

    def __add__(self, o):
        return _binop("add", self, o, lambda a, b: a + b)

    def __radd__(self, o):
        return _binop("add", o, self, lambda a, b: a + b)

    def __sub__(self, o):
        return _binop("sub", self, o, lambda a, b: a - b)

    def __rsub__(self, o):
        return _binop("sub", o, self, lambda a, b: a - b)

    def __mul__(self, o):
        return _binop("mul", self, o, lambda a, b: a * b)

    def __rmul__(self, o):
        return _binop("mul", o, self, lambda a, b: a * b)

    def __truediv__(self, o):
        return _binop("div", self, o, lambda a, b: a / b)

    def __rtruediv__(self, o):
        return _binop("div", o, self, lambda a, b: a / b)

    def __pow__(self, o):
        return _binop("pow", self, o, lambda a, b: a**b)

    def __neg__(self):
        return apply_op(lambda a: -a, self, op_name="neg")

    def __matmul__(self, o):
        return _binop("matmul", self, o, lambda a, b: a @ b)

    def __getitem__(self, idx):
        return apply_op(lambda a: a[idx], self, op_name="slice")

    def __repr__(self):
        kind = "ph" if self.is_placeholder else self.op_name
        return f"Node({self.name}: {kind})"


#: Python/numpy scalars stay *literals* closed over by the op function, so
#: JAX weak-type promotion applies (``int32_col * 2`` stays int32) — the
#: same no-implicit-widening behavior the reference gets from TF constants.
_LITERAL_TYPES = (int, float, bool, np.integer, np.floating, np.bool_)


def _lift(x) -> Node:
    if isinstance(x, Node):
        return x
    return constant(x)


def apply_op(
    f: Callable, *parents: Union[Node, Any], op_name: str = "op", name: Optional[str] = None
) -> Node:
    """Escape hatch: any jnp-traceable function of the parent values becomes
    a node. This is how the DSL stays small while XLA's op set stays fully
    reachable (the reference instead hand-maintains NodeDef builders,
    ``dsl/DslImpl.scala:143-200``)."""
    node_parents: List[Node] = []
    slots: List = []  # per-arg: (True, node_index) or (False, literal)
    for p in parents:
        if isinstance(p, Node):
            slots.append((True, len(node_parents)))
            node_parents.append(p)
        elif isinstance(p, _LITERAL_TYPES):
            slots.append((False, p))
        else:
            slots.append((True, len(node_parents)))
            node_parents.append(constant(p))

    def g(*vals):
        args = [vals[s[1]] if s[0] else s[1] for s in slots]
        return f(*args)

    return Node(op_name, node_parents, g, name=name)


def _binop(op_name: str, a, b, f: Callable) -> Node:
    return apply_op(f, a, b, op_name=op_name)


# -- placeholders & constants ---------------------------------------------


def placeholder(
    dtype, shape: Union[Shape, Sequence[int]], name: Optional[str] = None
) -> Node:
    """Explicit placeholder with a declared (block or cell) shape; dims may
    be Unknown/-1/None (reference ``dsl/package.scala:60-66``)."""
    st = for_any(dtype)
    if not isinstance(shape, Shape):
        shape = Shape.from_jax(tuple(shape))
    n = Node("placeholder", [], None, name=name)
    n.ph_spec = TensorSpec(n.name, st, shape)
    return n


def block(df, col_name: str, tft_name: Optional[str] = None) -> Node:
    """Placeholder bound to a column, with *block* shape (lead dim Unknown —
    reference ``core.py:446-449``: lead is always None so empty/variable
    partitions are accepted)."""
    info = df.schema[col_name]
    shape = info.block_shape.with_lead(Unknown)
    n = placeholder(info.scalar_type, shape, name=tft_name or col_name)
    _set_bound_column(n, col_name)  # renames keep binding to the column
    return n


def row(df, col_name: str, tft_name: Optional[str] = None) -> Node:
    """Placeholder bound to a column with *cell* (one-row) shape
    (reference ``core.py:412-425``)."""
    info = df.schema[col_name]
    n = placeholder(info.scalar_type, info.cell_shape, name=tft_name or col_name)
    _set_bound_column(n, col_name)
    return n


def constant(value, dtype=None, name: Optional[str] = None) -> Node:
    """Embedded constant (reference ``dsl/package.scala:68-75``,
    ``DenseTensor.scala:18-116``); becomes an XLA constant after jit."""
    arr = np.asarray(value, dtype=None if dtype is None else np.dtype(dtype))
    return Node("constant", [], None, name=name, value=arr)


# Node uses __slots__; the optional column binding lives in a side table.
_bound_columns: "weakref.WeakKeyDictionary[Node, str]" = weakref.WeakKeyDictionary()


def _set_bound_column(node: Node, col: str) -> None:
    _bound_columns[node] = col


def bound_column(node: Node) -> Optional[str]:
    return _bound_columns.get(node)


# -- capture ---------------------------------------------------------------


def build_graph(fetches: Union[Node, Sequence[Node]]) -> CapturedGraph:
    """Freeze a DSL DAG into a :class:`CapturedGraph` (analog of
    ``DslImpl.buildGraph``, reference ``dsl/DslImpl.scala:38-75``).

    Placeholders become named inputs; fetch node names become output/column
    names; a placeholder created via ``block``/``row`` keeps its original
    column binding in ``inputs_map`` even if renamed."""
    if isinstance(fetches, Node):
        fetches = [fetches]
    fetches = list(fetches)

    # transitive closure, deterministic order
    seen: Dict[int, Node] = {}
    order: List[Node] = []

    def visit(n: Node):
        stack = [(n, False)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen[id(node)] = node
            stack.append((node, True))
            for p in reversed(node.parents):
                stack.append((p, False))

    for f in fetches:
        visit(f)

    placeholders: List[TensorSpec] = []
    inputs_map: Dict[str, str] = {}
    for n in order:
        if n.is_placeholder:
            spec = TensorSpec(n.name, n.ph_spec.scalar_type, n.ph_spec.shape)
            placeholders.append(spec)
            col = bound_column(n)
            inputs_map[n.name] = col if col is not None else n.name

    node_list = list(order)

    def fn(feed: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp

        memo: Dict[int, Any] = {}
        for n in node_list:
            if n.is_placeholder:
                memo[id(n)] = feed[n.name]
            elif n.value is not None:
                memo[id(n)] = jnp.asarray(n.value)
            else:
                memo[id(n)] = n.fn(*[memo[id(p)] for p in n.parents])
        return {f.name: memo[id(f)] for f in fetches}

    return CapturedGraph(
        fn, placeholders, [f.name for f in fetches], inputs_map
    )
