"""Program serialization: the interchange/load path.

Analog of the reference's ``SerializedGraph`` byte-array graphs
(``/root/reference/src/main/scala/org/tensorframes/impl/TensorFlowOps.scala:21-74``)
and the graph-file load path (``PythonInterface.scala:110-118``,
``core.py:57-68``). The artifact here is a StableHLO program produced by
``jax.export`` with a symbolic batch dimension, plus a JSON header carrying
the placeholder/fetch specs and input map — everything an executor needs to
run the program without the Python that built it.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from ..schema import Shape, for_name
from ..utils import ensure_x64
from .graph import CapturedGraph, TensorSpec, _symbolic_shapes

__all__ = ["serialize_graph", "deserialize_graph", "save_graph", "load_graph"]

_MAGIC = b"TFSTPU1\x00"


def serialize_graph(
    graph: CapturedGraph,
    input_shapes: Optional[Dict[str, Shape]] = None,
) -> bytes:
    """Export to bytes. Unknown lead dims become one shared symbolic size,
    so the artifact runs on any block length without recompilation at the
    StableHLO level (XLA still specializes per concrete shape at run time)."""
    import jax
    from jax import export

    specs = []
    for ph in graph.placeholders.values():
        shape = (input_shapes or {}).get(ph.name, ph.shape)
        specs.append(TensorSpec(ph.name, ph.scalar_type, shape))
    if any(s.scalar_type.is_64bit for s in specs):
        ensure_x64()
    shapes = _symbolic_shapes(specs, share_lead=True)
    feed = {
        s.name: jax.ShapeDtypeStruct(shp, s.scalar_type.jax_dtype)
        for s, shp in zip(specs, shapes)
    }
    exported = export.export(jax.jit(graph.fn))(feed)
    payload = exported.serialize()
    header = json.dumps(
        {
            "version": 1,
            "placeholders": [
                [s.name, s.scalar_type.name, list(s.shape.dims)] for s in specs
            ],
            "fetches": graph.fetch_names,
            "inputs_map": graph.inputs_map,
            "shape_hints": {
                k: list(v.dims) for k, v in graph.shape_hints.items()
            },
        }
    ).encode("utf-8")
    return _MAGIC + len(header).to_bytes(8, "little") + header + bytes(payload)


def deserialize_graph(data: bytes) -> CapturedGraph:
    """Rebuild a :class:`CapturedGraph` whose ``fn`` calls the deserialized
    StableHLO program."""
    from jax import export

    if not data.startswith(_MAGIC):
        raise ValueError("Not a tensorframes_tpu serialized graph")
    off = len(_MAGIC)
    hlen = int.from_bytes(data[off : off + 8], "little")
    header = json.loads(data[off + 8 : off + 8 + hlen].decode("utf-8"))
    payload = data[off + 8 + hlen :]
    exported = export.deserialize(bytearray(payload))
    phs = [
        TensorSpec(name, for_name(st), Shape(dims))
        for name, st, dims in header["placeholders"]
    ]
    if any(p.scalar_type.is_64bit for p in phs):
        ensure_x64()

    def fn(feed: Dict[str, object]) -> Dict[str, object]:
        return exported.call(feed)

    hints = {k: Shape(v) for k, v in header.get("shape_hints", {}).items()}
    return CapturedGraph(
        fn, phs, header["fetches"], header["inputs_map"], hints
    )


def save_graph(graph: CapturedGraph, path: str, **kw) -> None:
    with open(path, "wb") as f:
        f.write(serialize_graph(graph, **kw))


def load_graph(path: str) -> CapturedGraph:
    with open(path, "rb") as f:
        return deserialize_graph(f.read())
