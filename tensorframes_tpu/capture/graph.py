"""Captured tensor programs and their analysis.

Analog of the reference's graph runtime
(``/root/reference/src/main/scala/org/tensorframes/impl/TensorFlowOps.scala``):
where the reference ships a protobuf ``GraphDef`` and asks the TF C++ runtime
for per-node dtypes/shapes on the driver (``analyzeGraphTF``,
``TensorFlowOps.scala:101-141``), this build captures a JAX-traceable
function plus named input specs, and derives output dtypes/shapes with
``jax.eval_shape`` — abstract tracing, no device work, no data.

Unknown dimensions are handled with JAX shape polymorphism: all block lead
dims share one symbolic size (they are the same physical row count), other
unknown dims get fresh symbols. This replaces the reference's
``ShapeDescription`` hint side-channel (``ShapeDescription.scala:12-20``),
which existed because TF >= 1.0 pruned dynamic shapes from serialized graphs.
Hints remain supported as overrides for programs XLA cannot trace
polymorphically.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import ColumnInfo, ScalarType, Shape, Unknown, for_numpy_dtype
from ..utils import ensure_x64, get_logger

__all__ = [
    "TensorSpec",
    "GraphNodeSummary",
    "CapturedGraph",
    "analysis_specs",
]

logger = get_logger("capture")


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """A named tensor endpoint: placeholder (input) or fetch (output)."""

    name: str
    scalar_type: ScalarType
    shape: Shape

    def __repr__(self):
        return f"{self.name}:{self.scalar_type.name}{self.shape}"


@dataclasses.dataclass(frozen=True)
class GraphNodeSummary:
    """Driver-side node summary (analog of ``GraphNodeSummary``, reference
    ``TensorFlowOps.scala:163-169``)."""

    is_input: bool
    is_output: bool
    scalar_type: ScalarType
    shape: Shape
    name: str


def _sds(shape: Tuple, dtype) -> Any:
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _symbolic_shapes(
    specs: Sequence[TensorSpec], share_lead: bool
) -> List[Tuple]:
    """Build concrete-or-symbolic dim tuples for eval_shape.

    All Unknown *lead* dims share one symbol (the block row count) when
    ``share_lead``; every other Unknown dim gets a fresh symbol. All symbols
    are created in a single ``symbolic_shape`` call so they share one JAX
    symbolic scope (mixing scopes is an error)."""
    from jax import export

    # first pass: plan symbol names per (spec, axis)
    plan: List[List[Any]] = []
    names: List[str] = []
    lead_name: Optional[str] = None
    for spec in specs:
        dims: List[Any] = []
        for axis, d in enumerate(spec.shape.dims):
            if d != Unknown:
                dims.append(int(d))
            elif axis == 0 and share_lead:
                if lead_name is None:
                    lead_name = "_tfs_b"
                    names.append(lead_name)
                dims.append(lead_name)
            else:
                nm = f"_tfs_d{len(names)}"
                names.append(nm)
                dims.append(nm)
        plan.append(dims)
    if not names:
        return [tuple(dims) for dims in plan]
    syms = export.symbolic_shape(", ".join(names))
    by_name = dict(zip(names, syms))
    return [
        tuple(by_name[d] if isinstance(d, str) else d for d in dims)
        for dims in plan
    ]


def _shape_from_abstract(dims: Tuple) -> Shape:
    """Map eval_shape output dims back to Shape (symbolic -> Unknown)."""
    out = []
    for d in dims:
        if isinstance(d, (int, np.integer)):
            out.append(int(d))
        else:
            out.append(Unknown)  # symbolic expression
    return Shape(out)


class CapturedGraph:
    """A user tensor program captured for the engine.

    Attributes:
        fn: ``fn(feed: dict[placeholder_name, array]) -> dict[fetch, array]``,
            JAX-traceable (pure, jnp ops, static shapes inside).
        placeholders: ordered input specs, by placeholder name.
        fetch_names: requested output names (become result column names,
            matching the reference's rule that fetches name the new columns,
            ``Operations.scala:29-31``).
        inputs_map: placeholder name -> frame column name (the reference's
            feed_dict / ``builder.inputs``, ``PythonInterface.scala:120-127``).
        shape_hints: optional fetch-name -> Shape overrides
            (``ShapeDescription`` analog).
    """

    def __init__(
        self,
        fn: Callable[[Dict[str, Any]], Dict[str, Any]],
        placeholders: Sequence[TensorSpec],
        fetch_names: Sequence[str],
        inputs_map: Optional[Dict[str, str]] = None,
        shape_hints: Optional[Dict[str, Shape]] = None,
    ):
        self.fn = fn
        self.placeholders: Dict[str, TensorSpec] = {p.name: p for p in placeholders}
        if len(self.placeholders) != len(placeholders):
            raise ValueError(
                f"Duplicate placeholder names: {[p.name for p in placeholders]}"
            )
        self.fetch_names = list(fetch_names)
        if len(set(self.fetch_names)) != len(self.fetch_names):
            # reference: core.py:105-107
            raise ValueError(
                f"Could not infer a list of unique names for the columns: "
                f"{self.fetch_names}"
            )
        self.inputs_map = dict(inputs_map or {})
        for ph in self.placeholders:
            self.inputs_map.setdefault(ph, ph)  # core.py:134-136
        self.shape_hints = dict(shape_hints or {})

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_callable(
        fn: Callable,
        input_specs: Dict[str, Tuple[ScalarType, Shape]],
        fetch_names: Optional[Sequence[str]] = None,
        inputs_map: Optional[Dict[str, str]] = None,
        shape_hints: Optional[Dict[str, Shape]] = None,
        probe_feed: Optional[Dict[str, Any]] = None,
    ) -> "CapturedGraph":
        """Capture a plain Python function whose keyword args are placeholder
        names and whose return value is a dict of named outputs (or a single
        array when exactly one fetch name is given).

        ``probe_feed``: concrete sample inputs used to discover output names
        when abstract tracing is impossible (binary/host-path programs)."""
        phs = [TensorSpec(n, st, sh) for n, (st, sh) in input_specs.items()]

        def wrapped(feed: Dict[str, Any]) -> Dict[str, Any]:
            out = fn(**{n: feed[n] for n in input_specs})
            if isinstance(out, dict):
                return out
            if fetch_names is not None and len(fetch_names) == 1:
                return {fetch_names[0]: out}
            raise TypeError(
                "captured function must return a dict of named outputs "
                "(or pass fetch_names=[single_name])"
            )

        if fetch_names is not None:
            fetch_names_ = list(fetch_names)
        elif probe_feed is not None:
            out = wrapped(probe_feed)
            if not isinstance(out, dict):
                raise TypeError(
                    "captured function must return a dict of named outputs"
                )
            fetch_names_ = list(out.keys())
        else:
            fetch_names_ = _probe_fetch_names(wrapped, phs)
        return CapturedGraph(
            wrapped, phs, fetch_names_, inputs_map, shape_hints
        )

    # -- analysis (analog of analyzeGraphTF) -------------------------------

    def analyze(
        self,
        input_shapes: Optional[Dict[str, Shape]] = None,
        share_lead: bool = True,
    ) -> Dict[str, TensorSpec]:
        """Infer fetch dtypes/shapes by abstract tracing.

        ``input_shapes`` refines placeholder shapes (e.g. with a frame's
        analyzed block shapes). Returns fetch name -> TensorSpec. Shape hints
        override inference, mirroring how the reference lets hint shapes win
        (``TensorFlowOps.scala:126-133``).

        Memoized per input-shape signature: repeated ops on frames with the
        same block shapes (the steady state of any iterative pipeline) skip
        the abstract trace entirely — the reference re-runs ``analyzeGraphTF``
        on the driver per call."""
        import jax

        specs = []
        for ph in self.placeholders.values():
            shape = (input_shapes or {}).get(ph.name, ph.shape)
            specs.append(TensorSpec(ph.name, ph.scalar_type, shape))
        if any(s.scalar_type.is_64bit for s in specs):
            ensure_x64()
        cache_key = (
            share_lead,
            # x64 is process-global and flips lazily (ensure_x64), changing
            # result dtypes for the same inputs — it must key the cache;
            # read it AFTER the flip above so the entry reflects the state
            # the trace actually runs under
            bool(jax.config.jax_enable_x64),
            tuple(
                sorted((k, v.dims) for k, v in (input_shapes or {}).items())
            ),
        )
        cache = getattr(self, "_analyze_cache", None)
        if cache is None:
            cache = self._analyze_cache = {}
        if cache_key in cache:
            return cache[cache_key]
        try:
            shapes = _symbolic_shapes(specs, share_lead)
            feed = {
                s.name: _sds(shp, s.scalar_type.jax_dtype)
                for s, shp in zip(specs, shapes)
            }
            out = jax.eval_shape(self.fn, feed)
        except Exception as e:
            logger.debug("symbolic analysis failed (%s); concrete probe", e)
            out = self._concrete_probe(specs)
        result: Dict[str, TensorSpec] = {}
        for name in self.fetch_names:
            if name not in out:
                raise KeyError(
                    f"Fetch {name!r} not among program outputs {sorted(out)}"
                )
            o = out[name]
            shape = (
                self.shape_hints[name]
                if name in self.shape_hints
                else _shape_from_abstract(o.shape)
            )
            result[name] = TensorSpec(name, for_numpy_dtype(o.dtype), shape)
        cache[cache_key] = result
        return result

    def _concrete_probe(self, specs: Sequence[TensorSpec]):
        """Fallback when polymorphic tracing fails: trace twice with two
        disjoint sets of large co-prime stand-in sizes for Unknown dims.
        Output dims that change between the probes inherited an Unknown
        input dim and are re-marked Unknown; dims that stay put are genuine
        constants — even if they coincide with a fill value."""
        import jax

        def probe(fills):
            it = iter(fills)
            lead_fill: Optional[int] = None  # Unknown lead dims share a size
            feed = {}
            for s in specs:
                dims = []
                for axis, d in enumerate(s.shape.dims):
                    if d != Unknown:
                        dims.append(d)
                    elif axis == 0:
                        if lead_fill is None:
                            lead_fill = next(it)
                        dims.append(lead_fill)
                    else:
                        dims.append(next(it))
                feed[s.name] = _sds(tuple(dims), s.scalar_type.jax_dtype)
            return jax.eval_shape(self.fn, feed)

        out_a = probe([1013, 1019, 1021, 1031, 1033, 1039, 1049, 1051])
        out_b = probe([2003, 2011, 2017, 2027, 2029, 2039, 2053, 2063])

        class _O:
            def __init__(self, shape, dtype):
                self.shape = shape
                self.dtype = dtype

        # None is the non-int marker _shape_from_abstract maps to Unknown.
        return {
            k: _O(
                tuple(
                    da if da == db else None
                    for da, db in zip(va.shape, out_b[k].shape)
                ),
                va.dtype,
            )
            for k, va in out_a.items()
        }

    def node_summaries(
        self, input_shapes: Optional[Dict[str, Shape]] = None
    ) -> List[GraphNodeSummary]:
        """Input+output summaries (reference ``analyzeGraphTF`` result,
        ``TensorFlowOps.scala:101-141``)."""
        outs = self.analyze(input_shapes)
        res = [
            GraphNodeSummary(True, False, p.scalar_type, p.shape, p.name)
            for p in self.placeholders.values()
        ]
        res += [
            GraphNodeSummary(False, True, o.scalar_type, o.shape, o.name)
            for o in outs.values()
        ]
        return res

    # -- helpers -----------------------------------------------------------

    def with_inputs(self, feed_dict: Dict[str, str]) -> "CapturedGraph":
        """Merge a user feed_dict (placeholder -> column), analog of
        ``_add_inputs`` (reference ``core.py:127-141``)."""
        merged = dict(self.inputs_map)
        for k, v in feed_dict.items():
            if k not in self.placeholders:
                raise KeyError(
                    f"feed_dict names unknown placeholder {k!r}; "
                    f"placeholders: {sorted(self.placeholders)}"
                )
            merged[k] = v
        return CapturedGraph(
            self.fn,
            list(self.placeholders.values()),
            self.fetch_names,
            merged,
            self.shape_hints,
        )

    def with_hints(self, hints: Dict[str, Shape]) -> "CapturedGraph":
        return CapturedGraph(
            self.fn,
            list(self.placeholders.values()),
            self.fetch_names,
            self.inputs_map,
            {**self.shape_hints, **hints},
        )

    def __repr__(self):
        return (
            f"CapturedGraph(inputs={list(self.placeholders)}, "
            f"fetches={self.fetch_names})"
        )


def _probe_fetch_names(
    wrapped: Callable, phs: Sequence[TensorSpec]
) -> List[str]:
    """Discover output names by abstract-tracing once with stand-in shapes."""
    import jax

    if any(p.scalar_type.is_64bit for p in phs):
        ensure_x64()
    feed = {
        p.name: _sds(p.shape.to_concrete(fill=2), p.scalar_type.jax_dtype)
        for p in phs
    }
    out = jax.eval_shape(wrapped, feed)
    if not isinstance(out, dict):
        raise TypeError("captured function must return a dict of named outputs")
    return list(out.keys())


def analysis_specs(
    cols: Sequence[ColumnInfo], block: bool
) -> Dict[str, Tuple[ScalarType, Shape]]:
    """Input specs for a frame's columns: block shape (lead Unknown) for
    block ops, cell shape for row ops (reference ``_auto_placeholder``,
    ``core.py:427-450``)."""
    specs = {}
    for c in cols:
        shape = c.block_shape.with_lead(Unknown) if block else c.cell_shape
        specs[c.name] = (c.scalar_type, shape)
    return specs
