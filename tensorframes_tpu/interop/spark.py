"""Spark interop (optional; gated on pyspark being installed).

The reference *is* a Spark package; here Spark is one possible table source
at the edge, two ways:

- small frames: collect to Arrow and ingest (:func:`from_spark`), results
  back via pandas (:func:`to_spark`);
- datasets beyond one host: PARTITION STREAMING via ``mapInArrow``
  (:func:`map_in_arrow` / :func:`arrow_batch_mapper`) — the captured
  program runs inside each executor over its partition's Arrow batches,
  like the reference's per-task sessions (``DebugRowOps.scala:377-391``);
  the driver never sees the table.
"""

from __future__ import annotations

from ..frame import TensorFrame

__all__ = [
    "spark_available",
    "from_spark",
    "to_spark",
    "arrow_batch_mapper",
    "map_in_arrow",
]


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def _require_spark():
    if not spark_available():
        raise ImportError(
            "pyspark is not installed; Spark interop is optional — install "
            "pyspark or ingest via TensorFrame.from_arrow/from_pandas"
        )


def from_spark(spark_df, num_partitions: int = 0) -> TensorFrame:
    """Spark DataFrame -> TensorFrame (via Arrow collect). ``num_partitions``
    defaults to the Spark frame's partition count."""
    _require_spark()
    from .arrow import from_arrow

    nparts = num_partitions
    if not nparts:
        try:  # Spark Connect sessions have no RDD API
            nparts = spark_df.rdd.getNumPartitions()
        except Exception:
            nparts = 1
    table = spark_df.toArrow() if hasattr(spark_df, "toArrow") else None
    if table is None:
        import pyarrow as pa

        table = pa.Table.from_pandas(spark_df.toPandas())
    return from_arrow(table, num_partitions=nparts)


def to_spark(df: TensorFrame, spark):
    """TensorFrame -> Spark DataFrame via pandas."""
    _require_spark()
    return spark.createDataFrame(df.to_pandas())


# ---------------------------------------------------------------------------
# partition streaming: compute goes to the executors (no driver collect)
# ---------------------------------------------------------------------------


def arrow_batch_mapper(
    fetches,
    trim: bool = False,
    feed_dict=None,
    decoders=None,
    constants=None,
    batch_rows: int = 0,
    streaming: bool = False,
):
    """Build the executor-side function for ``DataFrame.mapInArrow``:
    ``fn(iterator[pyarrow.RecordBatch]) -> iterator[pyarrow.RecordBatch]``.

    This is the partition-streaming path the reference gets from running
    inside Spark tasks (``DebugRowOps.scala:377-391``: compute goes to the
    partitions): each executor ingests ITS partition's Arrow batches,
    runs the captured program through the local engine (on whatever
    accelerator the executor has), and streams result batches back —
    the driver never materializes the table.

    The iterator Spark hands this function covers exactly ONE partition,
    in row order — so the batches are concatenated and the program runs
    ONCE over the whole partition. Cross-row block ops (means, softmaxes,
    anything whose result depends on which rows share a block) therefore
    see the partition, not Spark's arbitrary Arrow chunking
    (``spark.sql.execution.arrow.maxRecordsPerBatch`` would otherwise leak
    into results). This matches the reference, which materializes each
    partition as one tensor per column before the session runs
    (``TFDataOps.scala:27-59``); like the reference, the whole partition
    is resident during the call — size partitions accordingly.

    The returned function depends only on pyarrow + this package, so it
    runs under plain pyspark workers; ``batch_rows`` > 0 re-chunks output
    batches (0 = pyarrow's default chunking). Testable without a Spark
    cluster by feeding it RecordBatch iterators — which is exactly the
    contract Spark executes.

    Column-type caveat: string columns ingest as BINARY (the frame model
    has bytes cells, not utf8), so declare carried-through string fields
    as ``binary`` in the Spark output schema (or drop them with
    ``trim=True``). Numeric columns round-trip exactly.

    ``streaming=True`` runs the program per INCOMING BATCH instead of
    buffering the partition, so executor memory stays bounded at one
    batch — use it only for ROW-LOCAL programs (elementwise maps, where no
    result depends on which rows share a block): cross-row block ops
    would see Spark's arbitrary Arrow chunking instead of the partition.
    """
    from .. import engine
    from .arrow import from_arrow, to_arrow

    def run(table):
        # analyze() pins vector/tensor cell shapes before capture: a
        # FixedSizeList column ingested without it leaves Unknown cell
        # dims, and the capture probe would trace the program at a
        # placeholder width (wrong shapes or a confusing trace error).
        # Dense columns analyze from shape metadata — no cell scan.
        df = from_arrow(table).analyze()
        out = engine.map_blocks(
            fetches,
            df,
            trim=trim,
            feed_dict=feed_dict,
            decoders=decoders,
            constants=constants,
        )
        result = to_arrow(out)
        if batch_rows > 0:
            yield from result.to_batches(max_chunksize=batch_rows)
        else:
            yield from result.to_batches()

    def fn(batches):
        import pyarrow as pa

        if streaming:
            for batch in batches:
                if batch.num_rows:
                    yield from run(pa.Table.from_batches([batch]))
            return
        batches = list(batches)
        if not batches:
            return
        table = pa.Table.from_batches(batches)
        if table.num_rows == 0:
            return
        yield from run(table)

    return fn


def map_in_arrow(
    spark_df,
    fetches,
    output_schema: str,
    trim: bool = False,
    feed_dict=None,
    decoders=None,
    constants=None,
    batch_rows: int = 0,
    streaming: bool = False,
):
    """Partition-wise ``map_blocks`` over a Spark DataFrame via
    ``DataFrame.mapInArrow`` — no driver collect; each executor scores its
    partitions through :func:`arrow_batch_mapper`. ``output_schema`` is
    the Spark DDL schema string of the RESULT rows (fetch columns plus
    the input columns, or just the fetches with ``trim=True``; declare
    carried-through string columns as ``binary`` — see
    :func:`arrow_batch_mapper`). ``streaming=True`` bounds executor
    memory at one Arrow batch; row-local programs only."""
    _require_spark()
    return spark_df.mapInArrow(
        arrow_batch_mapper(
            fetches,
            trim=trim,
            feed_dict=feed_dict,
            decoders=decoders,
            constants=constants,
            batch_rows=batch_rows,
            streaming=streaming,
        ),
        output_schema,
    )
