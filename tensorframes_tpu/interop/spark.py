"""Spark interop (optional; gated on pyspark being installed).

The reference *is* a Spark package; here Spark is one possible table source
at the edge: a Spark DataFrame is collected to Arrow and ingested, results
go back as a Spark DataFrame. For datasets beyond one host, partition-wise
streaming via ``mapInArrow`` is the intended growth path.
"""

from __future__ import annotations

from ..frame import TensorFrame

__all__ = ["spark_available", "from_spark", "to_spark"]


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def _require_spark():
    if not spark_available():
        raise ImportError(
            "pyspark is not installed; Spark interop is optional — install "
            "pyspark or ingest via TensorFrame.from_arrow/from_pandas"
        )


def from_spark(spark_df, num_partitions: int = 0) -> TensorFrame:
    """Spark DataFrame -> TensorFrame (via Arrow collect). ``num_partitions``
    defaults to the Spark frame's partition count."""
    _require_spark()
    from .arrow import from_arrow

    nparts = num_partitions
    if not nparts:
        try:  # Spark Connect sessions have no RDD API
            nparts = spark_df.rdd.getNumPartitions()
        except Exception:
            nparts = 1
    table = spark_df.toArrow() if hasattr(spark_df, "toArrow") else None
    if table is None:
        import pyarrow as pa

        table = pa.Table.from_pandas(spark_df.toPandas())
    return from_arrow(table, num_partitions=nparts)


def to_spark(df: TensorFrame, spark):
    """TensorFrame -> Spark DataFrame via pandas."""
    _require_spark()
    return spark.createDataFrame(df.to_pandas())
