"""Frame persistence: Parquet + tensor-schema sidecar.

The reference has no persistence of its own — results are Spark DataFrames
and durability is the user's ``cache()``/write (SURVEY §5). Here frames
save/load directly: data as Parquet (via the Arrow interop), the analyzed
tensor metadata (shapes/dtypes the Parquet schema can't express) in the
Parquet key-value metadata, so ``load_frame`` restores exactly what
``analyze`` had inferred.
"""

from __future__ import annotations

import json

from ..frame import TensorFrame
from ..schema import ColumnInfo, FrameInfo

__all__ = ["save_frame", "load_frame", "map_parquet", "scan_parquet"]

_META_KEY = b"tensorframes_tpu.schema"


def _with_sidecar(table, schema: FrameInfo, num_partitions=None):
    """Attach the tensor-schema sidecar to an Arrow table's metadata —
    the one writer-side encoding (``load_frame`` is the reader)."""
    meta = {
        "columns": [{"name": c.name, **c.to_metadata()} for c in schema],
    }
    if num_partitions is not None:
        meta["num_partitions"] = num_partitions
    existing = table.schema.metadata or {}
    return table.replace_schema_metadata(
        {**existing, _META_KEY: json.dumps(meta).encode()}
    )


def save_frame(df: TensorFrame, path: str) -> None:
    import pyarrow.parquet as pq

    from .arrow import to_arrow

    table = _with_sidecar(
        to_arrow(df), df.schema, num_partitions=df.num_partitions
    )
    pq.write_table(table, path)


def load_frame(path: str) -> TensorFrame:
    import pyarrow.parquet as pq

    from .arrow import from_arrow

    table = pq.read_table(path)
    meta_raw = (table.schema.metadata or {}).get(_META_KEY)
    nparts = 1
    infos = None
    if meta_raw:
        meta = json.loads(meta_raw.decode())
        nparts = int(meta.get("num_partitions", 1))
        infos = {
            c["name"]: ColumnInfo.from_metadata(c["name"], c)
            for c in meta.get("columns", [])
        }
    df = from_arrow(table, num_partitions=nparts)
    if infos:
        merged = [
            infos.get(c.name, c).with_name(c.name) for c in df.schema
        ]
        df = TensorFrame(
            {n: df.column_data(n) for n in df.columns},
            FrameInfo(merged),
            num_partitions=nparts,
        )
    return df


# ---------------------------------------------------------------------------
# streaming: row groups are the file-based partition
# ---------------------------------------------------------------------------


def scan_parquet(path: str, row_groups_per_block: int = 1, prefetch: int = 2):
    """Iterate a Parquet file as TensorFrames, one per ``row_groups_per_
    block`` row groups, with a read-ahead thread keeping ``prefetch``
    blocks in flight — host memory stays bounded at ~prefetch blocks
    regardless of file size. The file-based analog of the reference's
    per-partition iterators (``DebugRowOps.scala:766-803``: Spark hands
    each task one partition at a time)."""
    import concurrent.futures as cf

    import pyarrow.parquet as pq

    from .arrow import from_arrow

    pf = pq.ParquetFile(path)
    try:
        ngroups = pf.num_row_groups
        spans = [
            list(range(lo, min(lo + row_groups_per_block, ngroups)))
            for lo in range(0, ngroups, row_groups_per_block)
        ]

        def read(span):
            return pf.read_row_groups(span)

        with cf.ThreadPoolExecutor(max_workers=1) as pool:
            pending = [
                pool.submit(read, s) for s in spans[: max(1, prefetch)]
            ]
            nxt = len(pending)
            for _ in spans:
                table = pending.pop(0).result()
                if nxt < len(spans):
                    pending.append(pool.submit(read, spans[nxt]))
                    nxt += 1
                yield from_arrow(table)
    finally:
        # closes the handle even when the consumer abandons the generator
        # mid-stream (GeneratorExit runs this finally), so streaming many
        # files never accumulates open descriptors
        pf.close()


def map_parquet(
    fetches,
    src: str,
    dst: str,
    trim: bool = False,
    feed_dict=None,
    decoders=None,
    constants=None,
    row_groups_per_block: int = 1,
    analyze: bool = True,
) -> dict:
    """Streaming ``map_blocks`` over a Parquet file: each block of row
    groups reads, runs through the local engine, and appends to ``dst`` —
    datasets larger than host memory stream through with a bounded
    footprint (reads prefetch ahead of the device via :func:`scan_parquet`;
    binary-column ``decoders`` additionally overlap host decode with chip
    compute inside the engine). The output carries the tensor-schema
    sidecar, so ``load_frame(dst)`` restores the analyzed result schema.

    Returns ``{"rows": ..., "blocks": ...}``. ``analyze`` runs the deep
    shape scan per block (needed for vector cells; O(1) for dense
    columns). The write is atomic: output lands at ``dst`` only if every
    block succeeds (a temp file is cleaned up otherwise), so a partial
    stream can never masquerade as a complete result. Raises on an empty
    source — there is no block to derive the output schema from."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from .. import engine
    from .arrow import to_arrow

    def _variable_lists(table):
        # list columns emit as VARIABLE lists: a cell length uniform
        # within one row-group block may differ in a later block, and
        # FixedSizeList(k) cannot be cast across k — variable lists make
        # the writer schema stable for any cross-block raggedness
        for i, f in enumerate(table.schema):
            if pa.types.is_fixed_size_list(f.type):
                table = table.set_column(
                    i,
                    pa.field(f.name, pa.list_(f.type.value_type)),
                    table.column(i).cast(pa.list_(f.type.value_type)),
                )
        return table

    tmp = dst + ".inprogress"
    writer = None
    rows = 0
    blocks = 0
    try:
        for df in scan_parquet(src, row_groups_per_block):
            if analyze:
                df = df.analyze()
            out = engine.map_blocks(
                fetches,
                df,
                trim=trim,
                feed_dict=feed_dict,
                decoders=decoders,
                constants=constants,
            )
            table = _variable_lists(to_arrow(out))
            if writer is None:
                # no num_partitions in the sidecar: the block count isn't
                # known until the stream ends and Parquet footer metadata
                # is fixed at writer open; the row-group structure itself
                # is the partition record (scan_parquet recovers it)
                table = _with_sidecar(table, out.schema)
                writer = pq.ParquetWriter(tmp, table.schema)
            else:
                table = table.cast(writer.schema)
            writer.write_table(table)
            rows += out.num_rows
            blocks += 1
        if writer is None:
            raise ValueError(
                f"map_parquet source {src!r} has no row groups; an empty "
                f"stream has no block to derive the output schema from"
            )
        writer.close()
        writer = None
        os.replace(tmp, dst)
    finally:
        if writer is not None:
            writer.close()
        if os.path.exists(tmp):
            os.remove(tmp)
    return {"rows": rows, "blocks": blocks}
