"""Frame persistence: Parquet + tensor-schema sidecar.

The reference has no persistence of its own — results are Spark DataFrames
and durability is the user's ``cache()``/write (SURVEY §5). Here frames
save/load directly: data as Parquet (via the Arrow interop), the analyzed
tensor metadata (shapes/dtypes the Parquet schema can't express) in the
Parquet key-value metadata, so ``load_frame`` restores exactly what
``analyze`` had inferred.
"""

from __future__ import annotations

import json

from ..frame import TensorFrame
from ..schema import ColumnInfo, FrameInfo

__all__ = ["save_frame", "load_frame"]

_META_KEY = b"tensorframes_tpu.schema"


def save_frame(df: TensorFrame, path: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .arrow import to_arrow

    table = to_arrow(df)
    meta = {
        "columns": [
            {"name": c.name, **c.to_metadata()} for c in df.schema
        ],
        "num_partitions": df.num_partitions,
    }
    existing = table.schema.metadata or {}
    table = table.replace_schema_metadata(
        {**existing, _META_KEY: json.dumps(meta).encode()}
    )
    pq.write_table(table, path)


def load_frame(path: str) -> TensorFrame:
    import pyarrow.parquet as pq

    from .arrow import from_arrow

    table = pq.read_table(path)
    meta_raw = (table.schema.metadata or {}).get(_META_KEY)
    nparts = 1
    infos = None
    if meta_raw:
        meta = json.loads(meta_raw.decode())
        nparts = int(meta.get("num_partitions", 1))
        infos = {
            c["name"]: ColumnInfo.from_metadata(c["name"], c)
            for c in meta.get("columns", [])
        }
    df = from_arrow(table, num_partitions=nparts)
    if infos:
        merged = [
            infos.get(c.name, c).with_name(c.name) for c in df.schema
        ]
        df = TensorFrame(
            {n: df.column_data(n) for n in df.columns},
            FrameInfo(merged),
            num_partitions=nparts,
        )
    return df
