"""Arrow interchange.

Dense numeric columns move zero-copy-ish (one ``to_numpy`` per column);
list columns become ragged/dense vector columns and round-trip through the
same (flat, offsets) layout the native packer uses.
"""

from __future__ import annotations

import numpy as np

from ..frame import TensorFrame

__all__ = ["from_arrow", "to_arrow"]


def from_arrow(table, num_partitions: int = 1) -> TensorFrame:
    """pyarrow.Table -> TensorFrame."""
    import pyarrow as pa

    data = {}
    for name in table.column_names:
        col = table.column(name).combine_chunks()
        if isinstance(col, pa.ChunkedArray):
            col = col.chunk(0) if col.num_chunks else pa.array([])
        if col.null_count:
            # same contract as the reference: "nullable fields are not
            # accepted" (core.py:368)
            raise ValueError(
                f"Column {name!r} contains {col.null_count} null(s); "
                f"nullable columns are not supported — fill or drop them "
                f"before ingesting"
            )
        if pa.types.is_fixed_size_list(col.type):
            # the dense-vector fast path to_arrow writes: one flat buffer
            k = col.type.list_size
            values = col.flatten()
            if values.null_count:
                raise ValueError(
                    f"Column {name!r} contains {values.null_count} null "
                    f"element(s) inside its vectors; nullable columns are "
                    f"not supported — fill or drop them before ingesting"
                )
            flat = values.to_numpy(zero_copy_only=False)
            data[name] = flat.reshape(len(col), k)
        elif pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
            data[name] = [np.asarray(v) for v in col.to_pylist()]
        elif pa.types.is_binary(col.type) or pa.types.is_string(col.type):
            vals = col.to_pylist()
            data[name] = [
                v.encode() if isinstance(v, str) else v for v in vals
            ]
        else:
            data[name] = col.to_numpy(zero_copy_only=False)
    return TensorFrame.from_columns(data, num_partitions=num_partitions)


def to_arrow(df: TensorFrame):
    """TensorFrame -> pyarrow.Table."""
    import pyarrow as pa

    df.cache()
    arrays = {}
    for c in df.schema:
        cd = df.column_data(c.name)
        if cd.is_binary:
            arrays[c.name] = pa.array(cd.cells, type=pa.binary())
        elif cd.dense is not None and cd.dense.ndim == 1:
            arrays[c.name] = pa.array(cd.host())
        elif cd.dense is not None and cd.dense.ndim == 2:
            # uniform vector column: one flat buffer, no Python loop
            flat = pa.array(np.ascontiguousarray(cd.host()).reshape(-1))
            arrays[c.name] = pa.FixedSizeListArray.from_arrays(
                flat, cd.host().shape[1]
            )
        else:
            arrays[c.name] = pa.array(
                [np.asarray(v).tolist() for v in cd.iter_cells()]
            )
    return pa.table(arrays)
