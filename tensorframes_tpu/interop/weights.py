"""Import externally-produced model weights into scoring programs.

The reference's flagship production workload scores a REAL pre-trained
frozen VGG-16: it downloads a published checkpoint, freezes the variables
into the GraphDef (``convert_variables_to_constants``, reference
``core.py:41-55``), and runs that frozen graph over binary image rows
(``read_image.py:29-55,147-167``). The TPU-native equivalent: load a
published weight file (``.npz`` or ``.safetensors`` — the formats real
model hubs publish), convert it to a param pytree, and close a JAX scoring
function over it — tracing bakes the arrays into the XLA program as
constants, which is exactly the freezing step, and ``save_graph`` then
serializes the frozen program as a deployable artifact.

Layout conversion is the real work. Torch models are NCHW with OIHW conv
kernels and ``[out, in]`` linear weights; XLA:TPU wants NHWC/HWIO (the
layout it tiles onto the MXU — see ``models/cnn.py``). Kernels transpose
cleanly, but the first dense layer after a flatten is order-sensitive:
torch flattens ``C*H*W``, NHWC flattens ``H*W*C``, so that matrix's input
axis must be re-ordered, not just transposed. :func:`cnn_params_from_torch_state`
does all of this for VGG-style stacks.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "load_weights",
    "save_weights",
    "flatten_tree",
    "unflatten_tree",
    "torch_conv_kernel",
    "torch_linear_kernel",
    "cnn_params_from_torch_state",
]


def load_weights(path: str) -> Dict[str, np.ndarray]:
    """Load a flat ``name -> array`` weight dict from ``.npz`` or
    ``.safetensors`` (chosen by extension). The analog of the reference
    downloading a published checkpoint (``read_image.py:29-44``)."""
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    if ext == ".safetensors":
        from safetensors.numpy import load_file

        return dict(load_file(path))
    raise ValueError(
        f"unsupported weight format {ext!r} (expected .npz or .safetensors)"
    )


def save_weights(path: str, weights: Dict[str, Any]) -> None:
    """Write a flat or nested weight dict to ``.npz`` / ``.safetensors``.
    Nested pytrees are flattened with dotted names (see
    :func:`flatten_tree`), the convention both formats' ecosystems use."""
    flat = {
        k: np.ascontiguousarray(np.asarray(v))
        for k, v in flatten_tree(weights).items()
    }
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npz":
        np.savez(path, **flat)
        return
    if ext == ".safetensors":
        from safetensors.numpy import save_file

        save_file(flat, path)
        return
    raise ValueError(
        f"unsupported weight format {ext!r} (expected .npz or .safetensors)"
    )


def flatten_tree(tree: Any, sep: str = ".", _prefix: str = "") -> Dict[str, Any]:
    """Nested dict/list pytree -> flat dotted-name dict (lists index as
    ``name.0``, ``name.1``, ... — the torch ``state_dict`` convention)."""
    if isinstance(tree, dict):
        items = tree.items()
    elif isinstance(tree, (list, tuple)):
        items = ((str(i), v) for i, v in enumerate(tree))
    else:
        return {_prefix.rstrip(sep): tree}
    out: Dict[str, Any] = {}
    for k, v in items:
        out.update(flatten_tree(v, sep=sep, _prefix=f"{_prefix}{k}{sep}"))
    return out


def unflatten_tree(flat: Dict[str, Any], sep: str = ".") -> Any:
    """Inverse of :func:`flatten_tree`: dotted names -> nested dicts, with
    runs of contiguous integer keys ``0..n-1`` becoming lists."""
    nested: Dict[str, Any] = {}
    for name, v in flat.items():
        parts = name.split(sep)
        d = nested
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v

    def listify(d):
        if not isinstance(d, dict):
            return d
        d = {k: listify(v) for k, v in d.items()}
        if d and all(k.isdigit() for k in d):
            idx = sorted(int(k) for k in d)
            if idx == list(range(len(idx))):
                return [d[str(i)] for i in idx]
        return d

    return listify(nested)


def torch_conv_kernel(w: np.ndarray) -> np.ndarray:
    """Torch ``Conv2d.weight`` ``[O, I, kH, kW]`` -> XLA HWIO
    ``[kH, kW, I, O]``."""
    w = np.asarray(w)
    if w.ndim != 4:
        raise ValueError(f"conv kernel must be 4-D, got shape {w.shape}")
    return np.ascontiguousarray(w.transpose(2, 3, 1, 0))


def torch_linear_kernel(w: np.ndarray) -> np.ndarray:
    """Torch ``Linear.weight`` ``[out, in]`` -> matmul-ready ``[in, out]``."""
    w = np.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"linear kernel must be 2-D, got shape {w.shape}")
    return np.ascontiguousarray(w.T)


def torch_flatten_linear_kernel(
    w: np.ndarray, chw: Tuple[int, int, int]
) -> np.ndarray:
    """Convert the dense layer that directly follows a flatten.

    Torch flattens NCHW activations to ``C*H*W`` order; NHWC flattens to
    ``H*W*C``. A plain transpose of ``[out, C*H*W]`` would silently wire
    every unit to the wrong pixels — the import would "work" and score
    garbage. Re-order the input axis: ``[out, C, H, W]`` -> ``[H, W, C,
    out]`` -> ``[H*W*C, out]``."""
    c, h, w_ = chw
    w = np.asarray(w)
    if w.ndim != 2 or w.shape[1] != c * h * w_:
        raise ValueError(
            f"flatten-linear weight {w.shape} does not match C*H*W="
            f"{c}*{h}*{w_}={c * h * w_}"
        )
    return np.ascontiguousarray(
        w.reshape(w.shape[0], c, h, w_).transpose(2, 3, 1, 0).reshape(
            h * w_ * c, w.shape[0]
        )
    )


def cnn_params_from_torch_state(
    state: Dict[str, np.ndarray],
    input_hw: Tuple[int, int],
    channels: int,
    convs_per_block: int = 2,
) -> Dict[str, Any]:
    """Torch ``state_dict`` of a VGG-style stack -> :mod:`~tensorframes_tpu.models.cnn`
    params (the pytree :func:`~tensorframes_tpu.models.cnn.cnn_embed`
    scores with).

    Expected publisher architecture (the standard torch Sequential VGG
    pattern, matching the reference's VGG-16 shape): 3x3 ``Conv2d``
    (padding=1) + ReLU layers, a 2x2 ``MaxPool2d`` after every
    ``convs_per_block`` convs, flatten, then one or two ``Linear`` layers
    (embedding head, optional classifier head). ``weight``/``bias``
    tensors pair by their shared module prefix, and modules order by
    NATURAL name sort — not dict order, which ``.safetensors`` does not
    preserve (it sorts keys, putting ``10.weight`` before ``2.weight``).
    Every 4-D weight is a conv, every 2-D weight a linear; the first
    linear gets the NCHW->NHWC flatten re-ordering (see
    :func:`torch_flatten_linear_kernel`), using the post-conv spatial
    size derived from ``input_hw`` and the pool count.
    """
    import re

    def natural(s: str):
        return [
            int(t) if t.isdigit() else t for t in re.split(r"(\d+)", s)
        ]

    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for name, arr in state.items():
        prefix, _, leaf = name.rpartition(".")
        groups.setdefault(prefix, {})[leaf] = np.asarray(arr)

    convs: List[Dict[str, np.ndarray]] = []
    linears: List[Tuple[np.ndarray, np.ndarray]] = []
    for prefix in sorted(groups, key=natural):
        g = groups[prefix]
        if "weight" not in g:
            raise ValueError(
                f"module {prefix!r} has {sorted(g)} but no 'weight'"
            )
        w = g["weight"]
        b = g.get("bias")
        if w.ndim == 4:
            if b is None:
                b = np.zeros(w.shape[0], dtype=w.dtype)
            convs.append(
                {"k": torch_conv_kernel(w), "b": b.astype(w.dtype)}
            )
        elif w.ndim == 2:
            if b is None:
                b = np.zeros(w.shape[0], dtype=w.dtype)
            linears.append((w, b))
        else:
            raise ValueError(
                f"unexpected {w.ndim}-D weight at module {prefix!r}"
            )
    if not convs or not linears:
        raise ValueError(
            f"need conv and linear layers; got {len(convs)} convs, "
            f"{len(linears)} linears"
        )
    if len(convs) % convs_per_block:
        raise ValueError(
            f"{len(convs)} convs do not group into blocks of "
            f"{convs_per_block}"
        )
    h, w = input_hw
    n_pools = len(convs) // convs_per_block
    h_out, w_out = h >> n_pools, w >> n_pools
    if h_out < 1 or w_out < 1 or h % (1 << n_pools) or w % (1 << n_pools):
        raise ValueError(
            f"input {input_hw} does not survive {n_pools} 2x2 pools"
        )
    c_out = convs[-1]["k"].shape[-1]
    ew, eb = linears[0]
    params: Dict[str, Any] = {
        "convs": convs,
        "convs_per_block": convs_per_block,
        "embed": {
            "w": torch_flatten_linear_kernel(ew, (c_out, h_out, w_out)),
            "b": np.asarray(eb, dtype=ew.dtype),
        },
    }
    if len(linears) > 1:
        hw_, hb = linears[1]
        params["head"] = {
            "w": torch_linear_kernel(hw_),
            "b": np.asarray(hb, dtype=hw_.dtype),
        }
    if len(linears) > 2:
        raise ValueError(
            f"expected at most 2 linear layers (embed + head); got "
            f"{len(linears)}"
        )
    # sanity: conv chain must be channel-consistent and start at the image
    c_in = channels
    for i, cv in enumerate(convs):
        if cv["k"].shape[2] != c_in:
            raise ValueError(
                f"conv {i} expects {cv['k'].shape[2]} input channels, "
                f"chain provides {c_in}"
            )
        c_in = cv["k"].shape[-1]
    return params
