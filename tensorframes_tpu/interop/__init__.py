"""Interop edges: Arrow, pandas, Spark.

The reference lives *inside* Spark; this framework keeps Spark (and any
other table source) at the edge, speaking Arrow as the interchange — the
role protobuf GraphDef + Py4J played for programs is played for *data* by
Arrow record batches (SURVEY §2.4).
"""

from .arrow import from_arrow, to_arrow
from .serving import ScoringServer, remote_arrow_mapper, remote_map_in_arrow
from .spark import from_spark, to_spark, spark_available
from .weights import (
    load_weights,
    save_weights,
    flatten_tree,
    unflatten_tree,
    torch_conv_kernel,
    torch_linear_kernel,
    cnn_params_from_torch_state,
)

__all__ = [
    "from_arrow",
    "to_arrow",
    "from_spark",
    "to_spark",
    "spark_available",
    "ScoringServer",
    "remote_arrow_mapper",
    "remote_map_in_arrow",
    "load_weights",
    "save_weights",
    "flatten_tree",
    "unflatten_tree",
    "torch_conv_kernel",
    "torch_linear_kernel",
    "cnn_params_from_torch_state",
]
