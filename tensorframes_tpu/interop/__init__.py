"""Interop edges: Arrow, pandas, Spark.

The reference lives *inside* Spark; this framework keeps Spark (and any
other table source) at the edge, speaking Arrow as the interchange — the
role protobuf GraphDef + Py4J played for programs is played for *data* by
Arrow record batches (SURVEY §2.4).
"""

from .arrow import from_arrow, to_arrow
from .spark import from_spark, to_spark, spark_available

__all__ = [
    "from_arrow",
    "to_arrow",
    "from_spark",
    "to_spark",
    "spark_available",
]
