"""TPU-host scoring service: executors stream Arrow, the chip's host runs.

The reference ran its native engine INSIDE every Spark executor
(per-task sessions, ``DebugRowOps.scala:377-391``) — compute went to the
partitions because every executor had a CPU TensorFlow. On TPU the
hardware inverts that: executors don't have chips, so the partitions
come to the compute. This module is that pattern as a shim:

- :class:`ScoringServer` runs on the TPU host. Each client connection
  carries one partition as an Arrow IPC stream; the server runs the
  captured program through the local engine (same ``map_blocks``
  semantics as :func:`~tensorframes_tpu.interop.spark.arrow_batch_mapper`
  — the whole connection's rows form one logical partition, so cross-row
  block ops see the partition, not the wire chunking) and streams the
  result back as Arrow.
- :func:`remote_arrow_mapper` builds the EXECUTOR-side function for
  ``DataFrame.mapInArrow``: a self-contained closure over (host, port)
  that imports only ``socket`` and ``pyarrow`` — Spark workers need
  neither jax nor this package installed.
- :func:`remote_map_in_arrow` wires the two into a Spark DataFrame
  transform, completing the story: Spark-scale data reaches the TPU
  without a driver-side collect; the driver never materializes the
  table.

Wire protocol (deliberately boring): the client writes one Arrow IPC
stream and half-closes its send side; the server reads to end-of-stream,
computes, writes one Arrow IPC stream back, and closes. Results are
buffered host-side until the request stream ends — full-duplex streaming
would deadlock clients (like Spark's mapInArrow generator) that write
everything before reading anything. ``streaming=True`` still bounds the
server's FRAME memory by running row-local programs per incoming batch.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, Optional, Tuple

__all__ = ["ScoringServer", "remote_arrow_mapper", "remote_map_in_arrow"]


class ScoringServer:
    """Serve a captured program over Arrow IPC on the host that owns the
    accelerator.

    >>> with ScoringServer(lambda x: {"y": x * 2.0}) as addr:
    ...     # hand `addr` ("host:port") to executors / pipelines
    ...     df.mapInArrow(remote_arrow_mapper(addr), schema)

    One connection = one partition (the
    :func:`~tensorframes_tpu.interop.spark.arrow_batch_mapper` contract);
    concurrent connections are served by a bounded thread pool, and the
    engine's program caches are shared across them, so every partition
    after the first reuses the compiled XLA program. ``precompile`` +
    the persistent compile cache (docs/perf.md "Cold start") make the
    first one cheap too."""

    def __init__(
        self,
        fetches,
        *,
        trim: bool = False,
        feed_dict: Optional[Dict[str, str]] = None,
        decoders: Optional[Dict[str, Any]] = None,
        constants: Optional[Dict[str, Any]] = None,
        streaming: bool = False,
        batch_rows: int = 0,
        host: str = "127.0.0.1",
        port: int = 0,
        max_connections: int = 8,
    ):
        from .spark import arrow_batch_mapper

        #: the same executor-side mapper the in-Spark path uses — the
        #: server is "an executor that happens to own the chip"
        self._mapper = arrow_batch_mapper(
            fetches,
            trim=trim,
            feed_dict=feed_dict,
            decoders=decoders,
            constants=constants,
            batch_rows=batch_rows,
            streaming=streaming,
        )
        self._host = host
        self._requested_port = port  # 0 = ephemeral, fresh per start()
        self._port = port
        self._limit = threading.Semaphore(max_connections)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Bind and serve in a daemon thread; returns ``(host, port)``
        (port resolved when 0 was requested). A stopped server may be
        started again."""
        if self._sock is not None:
            raise RuntimeError("server already started")
        self._stopping.clear()  # restart after stop()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind the REQUESTED port: an ephemeral (0) server picks a fresh
        # port each start (re-binding the previous resolved port races
        # lingering connections; callers re-read start()'s return)
        s.bind((self._host, self._requested_port))
        s.listen()
        self._sock = s
        self._port = s.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self._host, self._port

    @property
    def address(self) -> str:
        if self._sock is None:
            raise RuntimeError("server not started")
        return f"{self._host}:{self._port}"

    def stop(self) -> None:
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    def __enter__(self) -> str:
        self.start()
        return self.address

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            sock = self._sock  # stop() may null the attribute mid-loop
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except OSError:  # socket closed by stop()
                return
            # bound concurrency without parking stop(): wake periodically
            # so a full pool cannot leave this thread (and a pending
            # connection) stranded across shutdown
            while not self._limit.acquire(timeout=0.5):
                if self._stopping.is_set():
                    conn.close()
                    return
            threading.Thread(
                target=self._serve_one, args=(conn,), daemon=True
            ).start()

    def _serve_one(self, conn: socket.socket) -> None:
        import pyarrow as pa

        from ..utils import get_logger

        try:
            with conn:
                wf = None
                try:
                    rf = conn.makefile("rb")
                    reader = pa.ipc.open_stream(rf)
                    # results buffer until the request stream ends: a
                    # client that writes its whole partition before
                    # reading (Spark's mapInArrow generator does) must
                    # never deadlock against our send buffer
                    out_batches = list(self._mapper(reader))
                    conn.shutdown(socket.SHUT_RD)
                    wf = conn.makefile("wb")
                    # response = 1 status byte, then the payload: \x00 +
                    # Arrow stream, or \x01 + utf-8 error text (the
                    # executor re-raises it as its task failure — engine
                    # errors must not look like wire corruption)
                    wf.write(b"\x00")
                    if out_batches:
                        with pa.ipc.new_stream(
                            wf, out_batches[0].schema
                        ) as w:
                            for b in out_batches:
                                w.write_batch(b)
                    else:
                        with pa.ipc.new_stream(wf, pa.schema([])):
                            pass
                    wf.flush()
                except Exception as e:
                    get_logger("interop.serving").warning(
                        "scoring connection failed", exc_info=True
                    )
                    try:
                        if wf is None:
                            wf = conn.makefile("wb")
                        wf.write(
                            b"\x01"
                            + f"{type(e).__name__}: {e}".encode(
                                "utf-8", "replace"
                            )
                        )
                        wf.flush()
                    except OSError:
                        pass  # client already gone
                finally:
                    # drain any unread request bytes BEFORE closing: a
                    # failure mid-stream leaves data in the receive
                    # buffer, and closing over it makes the kernel send
                    # RST — destroying the in-flight \x01 error reply
                    # (the client would see ConnectionReset instead of
                    # the engine error). Bounded by a timeout so a
                    # wedged client cannot pin the worker.
                    try:
                        conn.settimeout(10)
                        while conn.recv(1 << 16):
                            pass
                    except OSError:
                        pass
                    # then force the FIN at the TCP level: socket.close()
                    # defers while makefile handles are alive, and a
                    # captured log record (exc_info traceback frames —
                    # e.g. pytest's logging plugin) can pin them long
                    # after this thread exits, leaving the client
                    # blocked on read
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
        except Exception:
            get_logger("interop.serving").warning(
                "scoring connection teardown failed", exc_info=True
            )
        finally:
            self._limit.release()


def remote_arrow_mapper(address: str):
    """The executor-side function for ``DataFrame.mapInArrow`` against a
    :class:`ScoringServer` at ``"host:port"``.

    The returned closure captures only the address string and imports
    only ``socket``/``pyarrow`` inside — it pickles to Spark workers
    that have NO jax and NO tensorframes_tpu installed (the whole point:
    the engine lives on the TPU host, executors just move Arrow)."""
    host, port_s = address.rsplit(":", 1)
    port = int(port_s)

    def fn(batches):
        import socket as _socket

        import pyarrow as _pa

        it = iter(batches)
        first = next(it, None)
        if first is None:
            return
        conn = _socket.create_connection((host, port))
        try:
            wf = conn.makefile("wb")
            with _pa.ipc.new_stream(wf, first.schema) as w:
                w.write_batch(first)
                for b in it:
                    w.write_batch(b)
            wf.flush()
            conn.shutdown(_socket.SHUT_WR)  # end of request stream
            rf = conn.makefile("rb")
            status = rf.read(1)
            if status == b"\x01":  # server-side failure, text follows
                raise RuntimeError(
                    "remote scoring failed: "
                    + rf.read().decode("utf-8", "replace")
                )
            if status != b"\x00":
                raise RuntimeError(
                    "remote scoring connection closed without a response"
                )
            reader = _pa.ipc.open_stream(rf)
            for b in reader:
                yield b
        finally:
            conn.close()

    return fn


def remote_map_in_arrow(spark_df, address: str, output_schema):
    """``mapInArrow`` against a remote :class:`ScoringServer`: each Spark
    partition streams to the TPU host and back, no driver collect. Pair
    with repartitioning so partitions match the block sizes the scoring
    program wants (one connection = one partition = one logical block
    span)."""
    return spark_df.mapInArrow(remote_arrow_mapper(address), output_schema)
