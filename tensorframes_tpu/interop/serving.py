"""TPU-host scoring service: executors stream Arrow, the chip's host runs.

The reference ran its native engine INSIDE every Spark executor
(per-task sessions, ``DebugRowOps.scala:377-391``) — compute went to the
partitions because every executor had a CPU TensorFlow. On TPU the
hardware inverts that: executors don't have chips, so the partitions
come to the compute. This module is that pattern as a shim:

- :class:`ScoringServer` runs on the TPU host. Each client connection
  carries one partition as an Arrow IPC stream; the server runs the
  captured program through the local engine (same ``map_blocks``
  semantics as :func:`~tensorframes_tpu.interop.spark.arrow_batch_mapper`
  — the whole connection's rows form one logical partition, so cross-row
  block ops see the partition, not the wire chunking) and streams the
  result back as Arrow.
- :func:`remote_arrow_mapper` builds the EXECUTOR-side function for
  ``DataFrame.mapInArrow``: a self-contained closure over (host, port)
  that imports only ``socket`` and ``pyarrow`` — Spark workers need
  neither jax nor this package installed.
- :func:`remote_map_in_arrow` wires the two into a Spark DataFrame
  transform, completing the story: Spark-scale data reaches the TPU
  without a driver-side collect; the driver never materializes the
  table.

Wire protocol (deliberately boring): the client writes one Arrow IPC
stream and half-closes its send side; the server reads to end-of-stream,
computes, writes one Arrow IPC stream back, and closes. Results are
buffered host-side until the request stream ends — full-duplex streaming
would deadlock clients (like Spark's mapInArrow generator) that write
everything before reading anything. ``streaming=True`` still bounds the
server's FRAME memory by running row-local programs per incoming batch.

Observability: the same port doubles as a Prometheus scrape target. A
connection whose first bytes are ``GET `` or ``POST`` is answered as a
plain HTTP request — ``GET /metrics`` returns the process-wide registry
in exposition format (an Arrow IPC stream can never start with those
bytes, so the two protocols cannot be confused). Each scoring connection
increments ``serving.requests_total{kind,status}``, the byte counters,
and the ``serving.request_seconds`` latency histogram; concurrent load
shows up on the ``serving.active_connections`` gauge. See
``docs/observability.md``.

Generation: constructed with ``engine=`` (a
:class:`~tensorframes_tpu.serve.GenerationEngine` or a replicated
:class:`~tensorframes_tpu.serve.Fleet`), the same port also serves
``POST /generate`` — JSON in (``{"prompt": [ids],
"max_new_tokens": n, "temperature"?, "top_p"?, "seed"?, "session"?}``),
JSON out (``{"request_id": ..., "tokens": [ids]}``) — backed by the
engine's continuous-batching loop, so concurrent connections share one
decode batch and one page pool (see ``docs/serving_llm.md``). With a
fleet, each request is placed on a healthy replica and survives replica
deaths via request replay; ``"session"`` keys opt into replica affinity.
A full admission queue answers 503 (backpressure) with an ADAPTIVE
``Retry-After`` — queue depth × observed p50 inter-token latency,
clamped to [1, 30] seconds, 1 until latency samples exist — an
infeasible request 400. Unknown paths get 404; known paths with the
wrong verb get 405 + ``Allow``.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..obs import (
    TraceContext as _TraceContext,
    flight as _flight,
    new_trace as _new_trace,
    span as _span,
    use_trace as _use_trace,
)
from ..utils import chaos as _chaos
from ..obs.metrics import (
    counter as _counter,
    enabled as _obs_enabled,
    gauge as _gauge,
    histogram as _histogram,
    render_prometheus as _render_prometheus,
)

__all__ = ["ScoringServer", "remote_arrow_mapper", "remote_map_in_arrow"]

_m_requests = _counter(
    "serving.requests_total",
    "Connections served, by kind "
    "(score|metrics|healthz|statusz|varz|generate|http) and terminal "
    "status",
    labels=("kind", "status"),
)
_m_bytes_in = _counter(
    "serving.bytes_in_total", "Request payload bytes read off the wire"
)
_m_bytes_out = _counter(
    "serving.bytes_out_total", "Response payload bytes written to the wire"
)
_m_latency = _histogram(
    "serving.request_seconds",
    "Scoring request wall time, accept to response flush (seconds)",
)
_m_active = _gauge(
    "serving.active_connections", "Connections currently being served"
)
_m_stream_resumes = _counter(
    "serve.stream_resumes_total",
    "Generate requests served from the router WAL's tracker instead of "
    "a fresh generation: duplicate request_id dedupe, and client "
    "reconnects resuming a stream with from=<offset>",
)


def _adaptive_retry_after(engine) -> str:
    """The 503 ``Retry-After`` value: aggregate queue depth × observed
    p50 inter-token latency (how long the backlog ahead of a retry
    plausibly takes to drain one slot), clamped to [1, 30] seconds.
    Falls back to ``"1"`` while no latency samples exist (cold engine)
    or anything in the estimate is unavailable — a wrong hint must never
    break the shed path."""
    import math

    try:
        depth = 0
        if engine is not None:
            depth = int(engine.health().get("queue_depth", 0) or 0)
        from ..obs.metrics import registry

        p50 = registry().get("serve.inter_token_seconds").quantile(0.5)
        if p50 is None:
            return "1"
        return str(int(min(30, max(1, math.ceil(depth * p50)))))
    except Exception:
        return "1"


class _CountingFile:
    """File-object wrapper that counts bytes through ``read``/``write``
    into a counter; everything else delegates. pyarrow's IPC reader/writer
    drive Python file-likes through exactly these two calls."""

    def __init__(self, f, counter):
        self._f = f
        self._c = counter

    def read(self, *args, **kwargs):
        b = self._f.read(*args, **kwargs)
        if b:
            self._c.inc(len(b))
        return b

    def write(self, data):
        n = self._f.write(data)
        self._c.inc(len(data) if n is None else n)
        return n

    def __getattr__(self, name):
        return getattr(self._f, name)


class ScoringServer:
    """Serve a captured program over Arrow IPC on the host that owns the
    accelerator.

    >>> with ScoringServer(lambda x: {"y": x * 2.0}) as addr:
    ...     # hand `addr` ("host:port") to executors / pipelines
    ...     df.mapInArrow(remote_arrow_mapper(addr), schema)

    One connection = one partition (the
    :func:`~tensorframes_tpu.interop.spark.arrow_batch_mapper` contract);
    concurrent connections are served by a bounded thread pool, and the
    engine's program caches are shared across them, so every partition
    after the first reuses the compiled XLA program. ``precompile`` +
    the persistent compile cache (docs/perf.md "Cold start") make the
    first one cheap too."""

    def __init__(
        self,
        fetches=None,
        *,
        trim: bool = False,
        feed_dict: Optional[Dict[str, str]] = None,
        decoders: Optional[Dict[str, Any]] = None,
        constants: Optional[Dict[str, Any]] = None,
        streaming: bool = False,
        batch_rows: int = 0,
        host: str = "127.0.0.1",
        port: int = 0,
        max_connections: int = 8,
        engine=None,
        readiness=None,
        lifecycle=None,
        router_epoch_fn=None,
    ):
        if fetches is None and engine is None:
            raise ValueError(
                "ScoringServer needs a scoring program (fetches) and/or a "
                "generation engine (engine=)"
            )
        if fetches is not None:
            from .spark import arrow_batch_mapper

            #: the same executor-side mapper the in-Spark path uses — the
            #: server is "an executor that happens to own the chip"
            self._mapper = arrow_batch_mapper(
                fetches,
                trim=trim,
                feed_dict=feed_dict,
                decoders=decoders,
                constants=constants,
                batch_rows=batch_rows,
                streaming=streaming,
            )
        else:
            self._mapper = None
        #: optional continuous-batching generation engine backing
        #: ``POST /generate`` (tensorframes_tpu.serve.GenerationEngine)
        self._engine = engine
        self._engine_started_here = False
        #: readiness probe for ``GET /readyz``: ``() -> (ready, state)``
        #: — a serving member (serve/membership.py) reports not-ready
        #: while draining / probing / mid-weight-swap so rollouts can
        #: gate traffic WITHOUT touching /healthz's liveness meaning.
        #: ``None`` → readiness mirrors liveness.
        self._readiness = readiness
        #: member-side half of zombie-router fencing: ``() ->
        #: Optional[int]`` reading the router election lease's CURRENT
        #: epoch (serve/router_ha.py's ``router_epoch_from``). When set,
        #: a ``POST /generate`` whose ``x-router-epoch`` header is below
        #: it came from a router that already lost the lease — answered
        #: ``409 Conflict`` (kind ``StaleRouterEpochError``) instead of
        #: decoding tokens the new active is re-generating. ``None`` (or
        #: no header) → no fencing.
        self._router_epoch_fn = router_epoch_fn
        #: lifecycle actuator for ``POST /admin/lifecycle``:
        #: ``(action, spec) -> payload dict`` (drain / admit / restart /
        #: swap / rollback — serve/membership.py wires the member's
        #: state machine in). ``None`` → the endpoint answers 501.
        self._lifecycle = lifecycle
        self._host = host
        self._requested_port = port  # 0 = ephemeral, fresh per start()
        self._port = port
        self._limit = threading.Semaphore(max_connections)
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._sampler_held = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Bind and serve in a daemon thread; returns ``(host, port)``
        (port resolved when 0 was requested). A stopped server may be
        started again."""
        if self._sock is not None:
            raise RuntimeError("server already started")
        self._stopping.clear()  # restart after stop()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind the REQUESTED port: an ephemeral (0) server picks a fresh
        # port each start (re-binding the previous resolved port races
        # lingering connections; callers re-read start()'s return)
        s.bind((self._host, self._requested_port))
        s.listen()
        self._sock = s
        if self._engine is not None and self._engine._thread is None:
            # the generate endpoint needs the stepping loop; start it for
            # the server's lifetime (an engine the caller already started
            # is left under the caller's control)
            self._engine.start()
            self._engine_started_here = True
        # a live server holds the time-series sampler, so /varz and the
        # SLO monitors have history for exactly as long as traffic can
        # reach them (refcounted; released in stop())
        from ..obs import timeseries as _ts

        _ts.acquire_sampler()
        self._sampler_held = True
        try:
            # fleet telemetry identity: a server wrapping an engine is a
            # serve replica; a score-only server is just a driver process
            from ..obs import export as _obs_export

            _obs_export.set_identity(
                "serve-replica" if self._engine is not None else "driver"
            )
        except Exception:
            from ..utils import get_logger

            get_logger("interop.serving").warning(
                "telemetry identity failed", exc_info=True
            )
        self._port = s.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()
        return self._host, self._port

    @property
    def address(self) -> str:
        if self._sock is None:
            raise RuntimeError("server not started")
        return f"{self._host}:{self._port}"

    def stop(self) -> None:
        self._stopping.set()
        if getattr(self, "_sampler_held", False):
            from ..obs import timeseries as _ts

            self._sampler_held = False
            _ts.release_sampler()
        if self._engine_started_here:
            self._engine.stop()
            self._engine_started_here = False
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    def __enter__(self) -> str:
        self.start()
        return self.address

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            sock = self._sock  # stop() may null the attribute mid-loop
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except OSError:  # socket closed by stop()
                return
            # bound concurrency without parking stop(): wake periodically
            # so a full pool cannot leave this thread (and a pending
            # connection) stranded across shutdown
            while not self._limit.acquire(timeout=0.5):
                if self._stopping.is_set():
                    conn.close()
                    return
            threading.Thread(
                target=self._serve_one, args=(conn,), daemon=True
            ).start()

    #: HTTP verbs the Arrow port answers as plain HTTP (an Arrow IPC
    #: stream can never start with these bytes)
    _HTTP_PREFIXES = (b"GET ", b"POST")

    #: the HTTP routing table: path -> verbs it answers. Anything else is
    #: a crisp 404 (unknown path) or 405 + ``Allow`` (wrong verb) — note
    #: only GET/POST-prefixed requests reach HTTP handling at all (the
    #: peek above routes everything else to the Arrow parser)
    _ROUTES: Dict[str, Tuple[str, ...]] = {
        "/metrics": ("GET",),
        "/healthz": ("GET",),
        "/readyz": ("GET",),
        "/statusz": ("GET",),
        "/varz": ("GET",),
        "/generate": ("POST",),
        "/admin/tenants": ("GET", "POST"),
        "/admin/lifecycle": ("POST",),
    }

    @classmethod
    def _peek(cls, conn: socket.socket) -> bytes:
        """The request's first bytes without consuming them (so the Arrow
        reader still sees a whole stream). Blocks for the FIRST byte just
        like the pre-scrape server blocked in the Arrow parser — a slow
        client must not be dropped. Waits for more bytes ONLY while the
        prefix is still ambiguous with an HTTP verb (an Arrow stream's
        first byte is never ``G`` or ``P``, so Arrow clients route
        immediately); that disambiguation wait is bounded so a client
        wedged exactly at ``b"GE"`` falls through to the Arrow path — the
        same failure surface it would have hit before the scrape
        existed."""
        buf = conn.recv(4, socket.MSG_PEEK)  # blocking first-byte wait
        if not buf or not any(
            v.startswith(buf[:4]) for v in cls._HTTP_PREFIXES
        ):
            return buf
        deadline = time.monotonic() + 10.0
        while len(buf) < 4 and any(
            v.startswith(buf) for v in cls._HTTP_PREFIXES
        ):
            if time.monotonic() > deadline:
                break
            time.sleep(0.005)
            buf = conn.recv(4, socket.MSG_PEEK)
            if not buf:
                break
        return buf

    def _serve_http(self, conn: socket.socket) -> str:
        """Answer a plain-HTTP request on the Arrow port. Routes:

        - ``GET /metrics`` — the default registry in Prometheus
          exposition format, so ``curl http://host:port/metrics`` (or an
          actual scrape job) works against a live server with no sidecar;
        - ``GET /healthz`` — liveness JSON (engine watchdog age, queue
          depth, pages in use, SLO state); 200 while healthy (the
          ``status`` field says ``"degraded"`` under an SLO breach),
          503 once the serving supervisor marked the engine unhealthy
          or a stop wedged;
        - ``GET /readyz`` — readiness JSON (``{"ready", "state"}``):
          503 while a fleet member is draining / probing /
          mid-weight-swap even though it is perfectly alive — the
          traffic gate rollouts and balancers act on (liveness and
          readiness are deliberately separate probes);
        - ``GET /varz`` — the time-series store as JSON (sampled
          gauges, counter rates, histogram quantiles; ``prefix=`` /
          ``window=`` query params);
        - ``POST /generate`` (``engine=`` configured) — JSON
          ``{"prompt": [ids], "max_new_tokens": n, "temperature"?,
          "top_p"?, "seed"?, "deadline_s"?, "session"?}`` submitted to
          the continuous-batching engine (or placed by the fleet
          router); responds ``{"request_id", "tokens"}`` when the
          stream completes. 503 + adaptive ``Retry-After`` on a full
          admission queue or an unhealthy engine / all-fenced fleet
          (shed, don't block), 504 on a missed deadline, 400 on an
          infeasible request, 429 + ``Retry-After`` when the tenant's
          QoS policy refuses it (quota / rate / SLO shed);
        - ``GET|POST /admin/tenants`` — the QoS policy registry
          (``serve/tenancy.py``): read or update per-tenant quotas,
          rate limits, and priority classes at runtime;
        - ``POST /admin/lifecycle`` — the fleet-member lifecycle
          actuator (drain / admit / restart / swap / rollback /
          commit; ``serve/membership.py``).

        ``POST /generate`` with ``"stream": true`` answers NDJSON: one
        ``{"t": token}`` line per emission and a terminal ``{"done":
        ...}`` / ``{"error": ..., "kind": ...}`` line — the wire the
        fleet router's remote replicas relay token-by-token.

        Unknown paths answer 404; known paths with the wrong verb 405
        with an ``Allow`` header. Returns the request kind for the
        metrics label."""
        import json

        conn.settimeout(10)
        buf = b""
        while b"\r\n\r\n" not in buf and len(buf) < 65536:
            chunk = conn.recv(4096)
            if not chunk:
                break
            buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        line = head.split(b"\r\n", 1)[0].decode("latin-1", "replace")
        parts = line.split()
        verb = parts[0].upper() if parts else ""
        path, _, query = (parts[1] if len(parts) > 1 else "/").partition("?")
        headers: Dict[str, str] = {}
        for hline in head.split(b"\r\n")[1:]:
            name, _, val = hline.partition(b":")
            headers[name.strip().lower().decode("latin-1", "replace")] = (
                val.strip().decode("latin-1", "replace")
            )
        clen = 0
        try:
            clen = int(headers.get("content-length", "0"))
        except ValueError:
            pass
        while len(body) < clen:
            chunk = conn.recv(4096)
            if not chunk:
                break
            body += chunk

        kind = "http"
        ctype = "text/plain; charset=utf-8"
        extra_headers: Dict[str, str] = {}
        norm = path.rstrip("/") or "/"
        allowed = self._ROUTES.get(norm)
        if allowed is None:
            # an unknown path is the CLIENT's mistake: say so crisply
            # instead of falling through to an ambiguous catch-all
            out = (
                b"endpoints: GET /metrics, GET /healthz, GET /readyz, "
                b"GET /statusz, GET /varz, POST /generate, "
                b"GET|POST /admin/tenants, POST /admin/lifecycle\n"
            )
            status = "404 Not Found"
        elif verb not in allowed:
            # right path, wrong verb: 405 with the verbs that would work
            out = f"method {verb or '?'} not allowed on {norm}\n".encode(
                "utf-8"
            )
            status = "405 Method Not Allowed"
            extra_headers["Allow"] = ", ".join(allowed)
        elif norm == "/metrics":
            kind = "metrics"
            out = _render_prometheus().encode("utf-8")
            status = "200 OK"
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif norm == "/healthz":
            kind = "healthz"
            status, out, extra_headers = self._handle_healthz()
            ctype = "application/json; charset=utf-8"
        elif norm == "/readyz":
            kind = "readyz"
            status, out, extra_headers = self._handle_readyz()
            ctype = "application/json; charset=utf-8"
        elif norm == "/statusz":
            kind = "statusz"
            status, out, extra_headers = self._handle_statusz()
            ctype = "application/json; charset=utf-8"
        elif norm == "/varz":
            kind = "varz"
            status, out, extra_headers = self._handle_varz(query)
            ctype = "application/json; charset=utf-8"
        elif norm == "/admin/tenants":
            kind = "admin"
            status, out, extra_headers = self._handle_admin_tenants(
                verb, body
            )
            ctype = "application/json; charset=utf-8"
        elif norm == "/admin/lifecycle":
            kind = "lifecycle"
            status, out, extra_headers = self._handle_lifecycle(body)
            ctype = "application/json; charset=utf-8"
        else:  # /generate, POST
            kind = "generate"
            res = self._handle_generate(body, headers, conn=conn)
            if res is None:
                return kind  # streamed: the response is already on the wire
            status, out, extra_headers = res
            ctype = "application/json; charset=utf-8"
        header_lines = "".join(
            f"{k}: {v}\r\n" for k, v in extra_headers.items()
        )
        conn.sendall(
            (
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(out)}\r\n"
                f"{header_lines}"
                "Connection: close\r\n\r\n"
            ).encode("latin-1")
            + out
        )
        return kind

    def _handle_healthz(self) -> Tuple[str, bytes, Dict[str, str]]:
        """Liveness for load balancers and the chaos soak: the engine's
        :meth:`~tensorframes_tpu.serve.GenerationEngine.health` snapshot
        (last-step watchdog age, queue depth, pages in use, unhealthy
        flags) — for a :class:`~tensorframes_tpu.serve.Fleet`, the
        AGGREGATE with per-replica detail, 200 while any replica serves
        — plus this process's batch-job summary (``engine/jobs.py``:
        active/completed/failed runs, the last job's block counts and
        quarantine tally; for a journaled job, the ``"journal"`` view
        read from the journal directory itself — block progress and the
        distributed worker/lease table of ``engine/dist_jobs.py``, so
        ANY process's probe shows the whole fleet draining the
        manifest) so operators see batch health next to serving
        health. A server with no engine is just an Arrow scorer —
        always healthy as long as it accepts connections. A 503 carries
        the adaptive ``Retry-After`` so probes and balancers know when
        to look again."""
        import json

        if self._engine is None:
            report: Dict[str, Any] = {"healthy": True, "engine": None}
        else:
            report = self._engine.health()
        try:
            from ..engine.jobs import jobs_status

            report["jobs"] = jobs_status()
        except Exception:  # health must never 500 over a status probe
            report["jobs"] = None
        try:
            # the flight recorder's recent debug bundles: the probe that
            # notices a failure points straight at its black box
            report["debug_bundles"] = _flight.recent_bundles()
        except Exception:
            report["debug_bundles"] = []
        # SLO state rides the health probe: "degraded" is a state
        # DISTINCT from unhealthy — the engine still serves (stay 200,
        # the balancer must not drain a whole fleet over a latency SLO)
        # but it is violating its declared objectives, and the "status"
        # field says so to anything that looks
        degraded = False
        try:
            from ..obs import slo as _slo

            mon = _slo.monitor()
            report["slo"] = mon.status()
            degraded = mon.degraded()
        except Exception:
            report["slo"] = []
        # fleet telemetry summary: when a telemetry dir is configured,
        # the probe shows every federated process's identity and
        # staleness (a kill -9'd worker shows up HERE as stale, not by
        # silently vanishing from the page)
        try:
            from ..obs import aggregate as _obs_agg
            from ..obs import export as _obs_export

            tdir = _obs_export.telemetry_dir()
            if tdir:
                fs = _obs_agg.fleet_status(tdir)
                report["fleet"] = {
                    "dir": tdir,
                    "procs": fs.get("procs", []),
                    "stale": sum(
                        1 for p in fs.get("procs", []) if p.get("stale")
                    ),
                }
            else:
                report["fleet"] = None
        except Exception:
            report["fleet"] = None
        report["status"] = (
            "unhealthy"
            if not report["healthy"]
            else ("degraded" if degraded else "ok")
        )
        body = json.dumps(report).encode("utf-8")
        if report["healthy"]:
            return "200 OK", body, {}
        return "503 Service Unavailable", body, {
            "Retry-After": _adaptive_retry_after(self._engine)
        }

    def _handle_readyz(self) -> Tuple[str, bytes, Dict[str, str]]:
        """``GET /readyz`` — readiness, as distinct from ``/healthz``'s
        liveness: "should a balancer SEND this member traffic right
        now", not "is the process worth keeping alive". A serving
        member answers 503 while **draining** (rolling restart /
        SIGTERM), **probing** (restarted, not yet re-validated), or
        **mid-weight-swap** — states where the process is perfectly
        healthy (``/healthz`` stays 200/ok, a balancer must NOT recycle
        it) but must not take new streams. Without a readiness hook
        (plain scorer / standalone engine server) readiness mirrors
        liveness, so probing either endpoint is always safe."""
        import json

        state = "ready"
        if self._readiness is not None:
            try:
                ready, state = self._readiness()
            except Exception as e:  # a probe must never 500
                ready, state = False, f"error: {type(e).__name__}"
        elif self._engine is not None:
            ready = bool(self._engine.health().get("healthy"))
            state = "ready" if ready else "unhealthy"
        else:
            ready = True
        body = json.dumps({"ready": bool(ready), "state": state}).encode(
            "utf-8"
        )
        if ready:
            return "200 OK", body, {}
        return "503 Service Unavailable", body, {"Retry-After": "1"}

    def _handle_lifecycle(
        self, body: bytes
    ) -> Tuple[str, bytes, Dict[str, str]]:
        """``POST /admin/lifecycle`` — the member's lifecycle actuator
        (``serve/membership.py`` wires it): ``{"action": "drain" |
        "admit" | "restart" | "swap" | "rollback", ...}``. The rollout
        orchestrator drives members through drain → restart/swap →
        probe → admit over this endpoint; ``/readyz`` reflects each
        transition. 501 when no lifecycle hook is configured, 400 for
        an unknown action or bad spec, 500 when the action itself
        failed (e.g. a checkpoint that does not load)."""
        import json

        if self._lifecycle is None:
            return (
                "501 Not Implemented",
                json.dumps(
                    {"error": "server has no lifecycle hook (not a "
                              "fleet member)"}
                ).encode("utf-8"),
                {},
            )
        try:
            spec = json.loads(body.decode("utf-8") or "{}")
            action = str(spec.get("action", ""))
        except ValueError as e:
            return (
                "400 Bad Request",
                json.dumps({"error": f"bad JSON: {e}"}).encode("utf-8"),
                {},
            )
        try:
            payload = self._lifecycle(action, spec)
        except ValueError as e:
            return (
                "400 Bad Request",
                json.dumps({"error": str(e)}).encode("utf-8"),
                {},
            )
        except Exception as e:
            return (
                "500 Internal Server Error",
                json.dumps(
                    {"error": f"{type(e).__name__}: {e}",
                     "kind": type(e).__name__}
                ).encode("utf-8"),
                {},
            )
        return "200 OK", json.dumps(dict(payload or {})).encode("utf-8"), {}

    def _handle_statusz(self) -> Tuple[str, bytes, Dict[str, str]]:
        """``GET /statusz`` — the operator's at-a-glance page, JSON:

        - ``requests``: the flight recorder's recent generate/score
          records, newest last (kind, HTTP status, wall seconds,
          trace_id — paste the trace_id into a grep over the JSONL sink
          to pull the whole span tree);
        - ``slowest_requests``: the same records, slowest first (top
          10) — where to start when p99 moved;
        - ``debug_bundles``: recent flight-recorder bundles (path,
          reason, timestamp), newest first;
        - ``flight``: events currently held per ring;
        - ``programs``: the per-program cost registry
          (``obs/programs.py``) — every compiled program with compile
          wall-time, FLOP/byte estimates, invocations, cumulative
          dispatch time, and roofline utilization, heaviest first;
        - ``slo``: every declared objective with its burn rates and
          breach state (``obs/slo.py``);
        - ``timeseries``: sampler state (running, interval, series
          tracked — the full points are on ``GET /varz``);
        - ``chaos``: the active chaos spec ("" when clean — anything
          else taints every number on the page);
        - ``tune``: the self-tuning layer's view
          (``tensorframes_tpu.tune``: active mode, store path, and
          every installed/stored tuned winner with its source);
        - ``identity``: this process's fleet identity (proc id, pid,
          role, package version, device kind — ``obs/export.py``);
        - ``request_costs``: the top requests by estimated FLOPs from
          the per-request cost ledger (``obs/requests.py``), tenant
          label included;
        - ``fleet``: when a telemetry dir is configured, the federated
          process table (``obs/aggregate.py`` — merged numbers are on
          ``GET /varz?scope=fleet``);
        - ``serving``: the engine/fleet health snapshot — per replica:
          ``tp_degree`` and (under tensor parallelism) the ``tp`` block
          with sharded-pool capacity, per-shard pages in use, and
          per-shard KV bytes, so operators see capacity scaling with
          the mesh at a glance (ISSUE 14);
        - ``router``: router-HA election + WAL state when a
          ``RouterHA`` is attached (``serve/router_ha.py``);
        - ``tiers``: replica tier roles and live KV-migration totals
          when the fleet is disaggregated (``serve/tiers.py``; None
          for an untiered topology);
        - ``trace_sink``: whether a JSONL span sink is attached.

        Always 200; rendering reads only lock-light engine counters
        (the same ``health()`` snapshot ``/healthz`` serves — safe even
        against a wedged stepping thread, which holds the step lock,
        not the bookkeeping locks) and never dispatches device work."""
        import json

        from ..obs import programs as _programs
        from ..obs import slo as _slo
        from ..obs import timeseries as _ts
        from ..obs import trace_sink as _trace_sink
        from ..utils.config import get_config
        from ..utils import chaos as _chaos_mod

        from .. import tune as _tune

        rings = _flight.rings()
        requests = rings.get("serving", [])
        slowest = sorted(
            requests, key=lambda e: e.get("dur_s") or 0.0, reverse=True
        )[:10]
        try:
            tune_view = {
                "mode": _tune.mode(),
                "store": _tune.store_path(),
                "winners": _tune.snapshot(),
            }
        except Exception:
            tune_view = None
        try:
            from ..obs import export as _obs_export
            from ..obs import requests as _obs_requests

            identity_view = _obs_export.identity()
            costs_view = _obs_requests.top_by_cost(10)
        except Exception:
            identity_view = None
            costs_view = []
        fleet_view = None
        try:
            from ..obs import aggregate as _obs_agg
            from ..obs import export as _obs_export

            tdir = _obs_export.telemetry_dir()
            if tdir:
                fs = _obs_agg.fleet_status(tdir)
                fleet_view = {"dir": tdir, "procs": fs.get("procs", [])}
        except Exception:
            fleet_view = None
        payload = {
            "requests": requests[-50:],
            "slowest_requests": slowest,
            "debug_bundles": _flight.recent_bundles(),
            "flight": {name: len(evts) for name, evts in rings.items()},
            "programs": _programs.table(),
            "slo": _slo.monitor().status(),
            "timeseries": {
                "sampler_running": _ts.sampler_running(),
                "interval_s": get_config().obs_sample_interval_s,
                "series": len(_ts.store().names()),
            },
            "chaos": _chaos_mod.active_spec(),
            "trace_sink": _trace_sink() is not None,
            # the serving topology: engine (or per-replica fleet)
            # health incl. tensor-parallel degree and sharded-pool
            # capacity — never 500s the status page over a sick engine
            "serving": self._serving_view(),
            # the self-tuning layer's installed/stored winners
            # (tensorframes_tpu.tune): which tuned configs this process
            # is actually running with, and where they came from
            "tune": tune_view,
            # fleet telemetry: who this process is, what its requests
            # cost, and (telemetry dir configured) who else is exporting
            "identity": identity_view,
            "request_costs": costs_view,
            "fleet": fleet_view,
            # the QoS plane's per-tenant view (None with no policies
            # configured): policies, live slots/queue share, recent
            # tokens/s + est FLOPs from the cost ledger, throttles —
            # read-side aggregation only (serve/tenancy.py)
            "tenants": self._tenants_view(),
            # router HA (serve/router_ha.py; None without an attached
            # RouterHA): election state (active/fenced, epoch, TTL) and
            # the WAL tracker's depth — the first place to look after a
            # takeover drill
            "router": self._router_view(),
            # disaggregated tiers (serve/tiers.py; None on an untiered
            # engine/fleet): replica roles plus live KV-migration
            # totals by reason — the first place to look when TTFT or
            # inter-token latency moves after a re-tiering
            "tiers": self._tiers_view(),
        }
        return "200 OK", json.dumps(payload, default=str).encode(
            "utf-8"
        ), {}

    def _tenants_view(self):
        """The QoS plane's ``/statusz`` block (None when off);
        exceptions degrade to an ``"error"`` stub — the status page
        always renders."""
        try:
            from ..serve import tenancy as _tenancy

            return _tenancy.statusz_view(self._engine)
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    def _router_view(self):
        """The router-HA ``/statusz`` block (None when this server's
        engine has no :class:`~tensorframes_tpu.serve.router_ha.RouterHA`
        attached); exceptions degrade to an ``"error"`` stub — the
        status page always renders."""
        ha = getattr(self._engine, "router_ha", None)
        if ha is None:
            return None
        try:
            return ha.statusz_view()
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    def _tiers_view(self):
        """The disaggregated-tier ``/statusz`` block (None when the
        engine is not a fleet, or when every replica is ``mixed`` —
        the monolithic topology has nothing tier-shaped to report);
        exceptions degrade to an ``"error"`` stub — the status page
        always renders."""
        reps = getattr(self._engine, "_replicas", None)
        if reps is None:
            return None
        try:
            roles = {
                rep.name: getattr(rep, "tier", "mixed") for rep in reps
            }
            if all(t == "mixed" for t in roles.values()):
                return None
            from ..obs import metrics as _metrics

            snap = _metrics.snapshot().get("serve.kv_migrations_total", {})
            return {
                "replicas": roles,
                "migrations": dict(snap.get("values", {})),
            }
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    def _serving_view(self):
        """The engine's (or fleet's) ``health()`` snapshot for
        ``/statusz``, None when this server is a pure Arrow scorer;
        exceptions degrade to an ``"error"`` stub — the status page
        always renders."""
        if self._engine is None:
            return None
        try:
            return self._engine.health()
        except Exception as e:  # pragma: no cover - defensive
            return {"error": f"{type(e).__name__}: {e}"}

    @staticmethod
    def _handle_admin_tenants(
        verb: str, body: bytes
    ) -> Tuple[str, bytes, Dict[str, str]]:
        """``/admin/tenants`` — the QoS policy registry
        (``serve/tenancy.py``). GET returns the live policies plus the
        plane/shedding state; POST applies one of three shapes (a
        single policy object → upsert, ``{"tenant": x, "delete":
        true}`` → remove, ``{"tenants": [...]}`` → replace all — ``[]``
        turns the plane off) through ``set_config``, so every consumer
        (scheduler order, admission buckets, placement) flips
        atomically. Validation errors are 400s; nothing changes on a
        rejected body."""
        import json

        from ..serve import tenancy as _tenancy

        if verb == "GET":
            payload = {
                "enabled": _tenancy.enabled(),
                "shedding": _tenancy.shedding(),
                "tenants": _tenancy.policies_view(),
            }
            return (
                "200 OK",
                json.dumps(payload).encode("utf-8"),
                {},
            )
        try:
            spec = json.loads(body.decode("utf-8") or "{}")
            tenants = _tenancy.apply_admin(spec)
        except (ValueError, TypeError, KeyError) as e:
            return (
                "400 Bad Request",
                json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}
                ).encode("utf-8"),
                {},
            )
        return (
            "200 OK",
            json.dumps(
                {"enabled": _tenancy.enabled(), "tenants": tenants}
            ).encode("utf-8"),
            {},
        )

    @staticmethod
    def _handle_varz(query: str = "") -> Tuple[str, bytes, Dict[str, str]]:
        """``GET /varz`` — the time-series store as JSON: every sampled
        series (gauges, counter ``.rate``\\ s, histogram ``.p50``/
        ``.p99``/``.rate``) with its raw recent points and per-tier
        depths, plus the sampler state. Query params: ``prefix=`` keeps
        only series whose name starts with it; ``window=SECONDS``
        returns the tier-merged trailing window instead of the raw
        tier; ``scope=fleet`` answers with the FEDERATED view instead —
        every process's exported snapshot under the telemetry dir,
        merged read-side (``obs/aggregate.py``: counters summed,
        gauges per-proc + sum/max, histogram quantiles recomputed from
        merged bucket counts, stale exporters flagged). Always 200 (an
        empty store renders as ``{}``: the sampler simply has not
        run)."""
        import json
        from urllib.parse import parse_qs

        from ..obs import timeseries as _ts
        from ..utils.config import get_config

        prefix: Optional[str] = None
        window_s: Optional[float] = None
        scope: Optional[str] = None
        try:
            q = parse_qs(query or "")
            if q.get("prefix"):
                prefix = q["prefix"][0]
            if q.get("window"):
                window_s = float(q["window"][0])
            if q.get("scope"):
                scope = q["scope"][0]
        except (ValueError, TypeError):
            return (
                "400 Bad Request",
                b'{"error": "bad query: expected prefix=NAME and/or '
                b'window=SECONDS and/or scope=fleet"}',
                {},
            )
        if scope == "fleet":
            from ..obs import aggregate as _obs_agg
            from ..obs import export as _obs_export

            tdir = _obs_export.telemetry_dir()
            if not tdir:
                payload = {
                    "scope": "fleet",
                    "enabled": False,
                    "error": "no telemetry dir configured (set "
                             "Config.telemetry_dir or TFT_TELEMETRY_DIR)",
                }
            else:
                payload = {"scope": "fleet", "enabled": True}
                payload.update(_obs_agg.fleet_status(tdir))
            return (
                "200 OK",
                json.dumps(payload, default=str).encode("utf-8"),
                {},
            )
        last_tick = _ts.last_tick_ts()
        payload = {
            "sampler_running": _ts.sampler_running(),
            "interval_s": get_config().obs_sample_interval_s,
            "last_tick_ts": last_tick,
            "sampler_lag_s": (
                None if last_tick is None
                else max(0.0, time.time() - last_tick)
            ),
            "series": _ts.store().to_dict(
                prefix=prefix, window_s=window_s
            ),
        }
        return "200 OK", json.dumps(payload).encode("utf-8"), {}

    @staticmethod
    def _timing_payload(handle, total_s: float) -> Dict[str, Any]:
        """The per-request timing breakdown echoed in the generate
        response: endpoint wall clock plus whatever stages the engine
        recorded on the handle (queue wait, prefill, chunked-prefill
        dispatches, summed decode gaps, fleet replays)."""
        t = dict(handle.timings) if handle is not None else {}
        out: Dict[str, Any] = {"total_s": round(total_s, 6)}
        # the speculative keys (draft/verify/rollback walls + the
        # proposed/accepted/rolled-back counts) appear only when the
        # engine actually speculated — a plain decode response carries
        # the same payload it always did
        for k in (
            "queue_wait_s", "prefill_s", "decode_s",
            "draft_s", "verify_s", "rollback_s",
        ):
            if k in t:
                out[k] = round(float(t[k]), 6)
        out["prefill_chunks"] = int(t.get("prefill_chunks", 0))
        out["replays"] = int(t.get("replays", 0))
        for k in ("spec_proposed", "spec_accepted", "spec_rolled_back"):
            if k in t:
                out[k] = int(t[k])
        # per-request cost attribution (obs/requests.py): what this
        # request consumed, echoed so the caller can bill without
        # scraping the server-side ledger
        for k in ("tokens", "kv_pages", "prefix_cached_tokens"):
            if k in t:
                out[k] = int(t[k])
        if "est_flops" in t:
            out["est_flops"] = float(t["est_flops"])
        if t.get("tenant"):
            out["tenant"] = str(t["tenant"])
        return out

    def _handle_generate(
        self,
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
        conn: Optional[socket.socket] = None,
    ) -> Optional[Tuple[str, bytes, Dict[str, str]]]:
        """One generate request against the engine; returns (status,
        JSON body, extra headers). Failure modes map to HTTP semantics
        instead of crashing the connection thread: bad JSON / infeasible
        request → 400, no engine → 501, full admission queue or
        unhealthy engine → fast 503 with ``Retry-After`` (shedding, not
        blocking), missed deadline (``"deadline_s"`` in the request, or
        the ``serve_result_timeout_s`` backstop) → 504.

        **Tracing**: a W3C ``traceparent`` request header is adopted
        (same trace_id, this server as a child position) — absent or
        malformed, a fresh trace starts. Every response carries a
        ``traceparent`` header and a ``"trace_id"`` JSON field, and
        completed generations add a ``"timing"`` breakdown (queue wait,
        prefill, chunked-prefill count, decode, replay count), so a
        caller can join its own telemetry to the engine's spans in the
        JSONL sink (docs/observability.md).

        **Streaming**: ``"stream": true`` switches the success path to
        NDJSON over the same connection — one ``{"t": token}`` line per
        emission, then a terminal ``{"done": ...}`` or ``{"error": ...,
        "kind": ExceptionName}`` line (returns ``None``: the response
        is already on the wire). Pre-submit failures still answer their
        plain-JSON status codes, each now carrying a ``"kind"`` field
        so remote callers (the fleet router's
        :class:`~tensorframes_tpu.serve.membership.RemoteEngine`) can
        re-raise the exact exception class.

        **Admission gate**: while the member's lifecycle state is
        ``"draining"`` (rolling restart / SIGTERM) or ``"fenced"``
        (lease lost — a zombie must not take traffic), new requests
        answer 503 immediately — in-flight streams keep decoding;
        probes during ``"probing"``/``"swapping"`` deliberately pass
        (the rollout's validation traffic must reach the engine).

        **Durable requests** (``Config.router_wal`` +
        ``serve/router_ha.py``): a client-supplied ``"request_id"`` is
        echoed on every response and, with the WAL attached, makes the
        request idempotent — a duplicate id serves the journaled entry
        instead of generating again, and a reconnect with
        ``"request_id"`` + ``"from": <tokens already received>``
        replays the missed prefix then follows the live tail. A
        placement whose ``x-router-epoch`` header is below the router
        election lease's epoch answers ``409 Conflict``
        (``StaleRouterEpochError``) — zombie-router fencing; a standby
        router answers 503 (kind ``RouterStandby``)."""
        import json

        t0 = time.perf_counter()
        root = _TraceContext.from_traceparent(
            (headers or {}).get("traceparent")
        )
        ctx = root.child() if root is not None else _new_trace()
        # the client-supplied idempotent request id (filled in during
        # spec parse); when present, EVERY response echoes it verbatim
        # — it names the request across retries/reconnects, so the
        # engine's internal handle id stays internal
        rid_box: Dict[str, Optional[str]] = {"rid": None}

        def reply(
            status: str,
            payload: Dict[str, Any],
            extra: Optional[Dict[str, str]] = None,
            handle=None,
        ) -> Tuple[str, bytes, Dict[str, str]]:
            total = time.perf_counter() - t0
            if rid_box["rid"] is not None:
                payload["request_id"] = rid_box["rid"]
            payload["trace_id"] = ctx.trace_id
            if handle is not None or status.startswith("200"):
                payload["timing"] = self._timing_payload(handle, total)
            _flight.record(
                "serving", "generate",
                status=status.split(" ", 1)[0],
                trace_id=ctx.trace_id,
                dur_s=round(total, 6),
                request_id=payload.get("request_id"),
            )
            hdrs = dict(extra or {})
            hdrs["traceparent"] = ctx.traceparent()
            return status, json.dumps(payload).encode("utf-8"), hdrs

        if self._engine is None:
            return reply(
                "501 Not Implemented",
                {"error": "server has no generation engine"},
            )
        def echo_rid() -> None:
            # refusals answered BEFORE the spec parse still echo a
            # client-supplied request_id (the retry loop keys on it);
            # best-effort only — a malformed body stays a refusal
            if rid_box["rid"] is None:
                try:
                    _rid = json.loads(
                        body.decode("utf-8") or "{}"
                    ).get("request_id")
                except Exception:
                    _rid = None
                if _rid is not None:
                    rid_box["rid"] = str(_rid)

        # zombie-router fencing (member side): a placement stamped with
        # an election epoch BELOW the lease's current one came from a
        # router that already lost the lease — its requests are being
        # re-generated by the new active, so decoding them here would
        # double-spend the chip and race the resumed stream
        stale = self._stale_router_epoch((headers or {}).get(
            "x-router-epoch"
        ))
        if stale is not None:
            placed, cur = stale
            echo_rid()
            return reply(
                "409 Conflict",
                {"error": f"placement carries router epoch {placed} but "
                          f"the election lease is at epoch {cur}: the "
                          "placing router was superseded (fenced "
                          "zombie)",
                 "kind": "StaleRouterEpochError"},
            )
        # router standby gate (router side): only the ACTIVE router may
        # admit — a standby (or a fenced ex-active) answers 503 so
        # clients re-resolve to the current active instead of parking
        # work on a router that cannot place it
        ha = getattr(self._engine, "router_ha", None)
        if ha is not None and not ha.active:
            echo_rid()
            return reply(
                "503 Service Unavailable",
                {"error": "this router is standby/fenced (not the "
                          "active router); retry — takeover completes "
                          "within the election TTL",
                 "kind": "RouterStandby"},
                {"Retry-After": "1"},
            )
        if self._readiness is not None:
            try:
                _, _member_state = self._readiness()
            except Exception:
                _member_state = ""
            if _member_state in ("draining", "fenced"):
                return reply(
                    "503 Service Unavailable",
                    {"error": "member is draining (admission stopped; "
                              "in-flight streams are finishing)"
                     if _member_state == "draining"
                     else "member was fenced (lease lost; re-register "
                          "before admitting traffic)",
                     "kind": "Draining"},
                    {"Retry-After": "2"},
                )
        from ..serve.engine import EngineUnhealthyError
        from ..serve.scheduler import QueueFullError
        from ..utils.config import get_config
        from ..utils.failures import TenantThrottledError

        try:
            spec = json.loads(body.decode("utf-8") or "{}")
            prompt = spec["prompt"]
            max_new = int(spec["max_new_tokens"])
            deadline = spec.get("deadline_s")
            stream = bool(spec.get("stream", False)) and conn is not None
            kwargs: Dict[str, Any] = dict(
                temperature=float(spec.get("temperature", 0.0)),
                top_p=float(spec.get("top_p", 1.0)),
                seed=int(spec.get("seed", 0)),
                deadline=None if deadline is None else float(deadline),
                block=False,
            )
            if spec.get("eos_id") is not None:
                kwargs["eos_id"] = int(spec["eos_id"])
            if spec.get("session") is not None:
                # replica affinity — only the fleet router understands it
                # (duck-typed on its replica surface; catching TypeError
                # from submit instead would blame the client for any
                # internal TypeError bug)
                if not hasattr(self._engine, "replica_names"):
                    return reply(
                        "400 Bad Request",
                        {"error": "session affinity requires a fleet "
                                  "engine (serve.Fleet)"},
                    )
                kwargs["session"] = str(spec["session"])
            tenant = spec.get("tenant")
            if tenant is None:
                tenant = spec.get("session")
            if tenant is not None:
                # cost-attribution label; only passed when the client
                # supplied one so duck-typed engines without the kwarg
                # keep working
                kwargs["tenant"] = str(tenant)
            if spec.get("request_id") is not None:
                rid_box["rid"] = str(spec["request_id"])
            # stream-resume cursor: how many tokens the client already
            # has (only meaningful on a reconnect with a request_id the
            # WAL tracker knows)
            resume_from = int(spec.get("from", 0) or 0)
            if resume_from < 0:
                raise ValueError(f"negative resume offset {resume_from}")
        except (ValueError, KeyError, TypeError) as e:
            return reply(
                "400 Bad Request",
                {"error": f"bad request: {type(e).__name__}: {e}"},
            )
        # durable-request plane (serve/router_ha.py, Config.router_wal):
        # with a client request_id and an attached WAL, a duplicate id
        # serves the EXISTING entry (dedupe / reconnect-resume) and a
        # fresh one is journaled before placement. Gated zero-cost-off:
        # no request_id, no WAL, or router_wal=False → this whole block
        # is a couple of attribute reads and the path below is
        # byte-identical to the pre-HA stack.
        wal = None
        wal_entry = None
        if rid_box["rid"] is not None:
            wal = getattr(self._engine, "wal", None)
            if wal is not None:
                from ..serve.router_ha import enabled as _wal_enabled

                if not _wal_enabled():
                    wal = None
        if wal is not None:
            rid = rid_box["rid"]
            record = {
                "prompt": [int(t) for t in prompt],
                "max_new": max_new,
                "temperature": kwargs["temperature"],
                "top_p": kwargs["top_p"],
                "seed": kwargs["seed"],
                "eos_id": kwargs.get("eos_id"),
                "session": kwargs.get("session"),
                "tenant": kwargs.get("tenant"),
                "deadline_s": deadline,
                "trace": ctx.traceparent(),
            }
            wal_entry, created = wal.admit(rid, record)
            if not created:
                # duplicate submit or reconnect: serve what the tracker
                # already holds — never generate the same id twice
                _m_stream_resumes.inc()
                if stream:
                    self._stream_entry(
                        conn, ctx, wal_entry, t0, resume_from
                    )
                    return None
                return self._reply_entry(reply, wal_entry)
        try:
            # the ambient trace around submit is how the trace_id
            # reaches the engine/fleet: the request record and every
            # engine-side span (prefill, chunks, failover replays) join
            # this request's trace
            with _use_trace(ctx), _span(
                "serving.generate", prompt_len=len(prompt),
                max_new=max_new,
            ):
                handle = self._engine.submit(prompt, max_new, **kwargs)
        except TimeoutError as e:
            # the fleet router can notice a deadline expiring DURING
            # placement (DeadlineExceededError) — same 504 as a stream
            # that expired mid-generation
            if wal_entry is not None:
                wal.forget(rid_box["rid"], e)
            return reply(
                "504 Gateway Timeout",
                {"error": str(e), "kind": type(e).__name__},
            )
        except TenantThrottledError as e:
            # per-TENANT refusal (quota / rate bucket / SLO shed,
            # serve/tenancy.py) — the server has capacity, this tenant
            # may not use it: 429, not the all-full 503. Retry-After is
            # the refusing token bucket's refill time, clamped to the
            # same [1, 30] window the adaptive 503 hint uses — UNLESS
            # the refusal was relayed from a member, in which case the
            # member's own Retry-After header rides the exception
            # (retry_after_hint) and is echoed verbatim: the member
            # knows its bucket, the router's would be a guess.
            import math

            if wal_entry is not None:
                wal.forget(rid_box["rid"], e)
            hint = getattr(e, "retry_after_hint", None)
            retry = str(hint) if hint else str(
                int(min(30, max(1, math.ceil(e.retry_after))))
            )
            return reply(
                "429 Too Many Requests",
                {"error": str(e), "tenant": e.tenant, "reason": e.reason,
                 "retry_after": e.retry_after,
                 "kind": "TenantThrottledError"},
                {"Retry-After": retry},
            )
        except (QueueFullError, EngineUnhealthyError) as e:
            # overload shedding: the caller can retry, THIS server can't
            # help right now — answer fast instead of parking the
            # connection against a full queue or a dead engine. The
            # Retry-After adapts to the backlog (depth x p50 ITL), or is
            # the member's verbatim hint when the refusal was relayed.
            if wal_entry is not None:
                wal.forget(rid_box["rid"], e)
            hint = getattr(e, "retry_after_hint", None)
            return reply(
                "503 Service Unavailable",
                {"error": str(e), "kind": type(e).__name__},
                {"Retry-After": str(hint) if hint
                 else _adaptive_retry_after(self._engine)},
            )
        except ValueError as e:
            if wal_entry is not None:
                wal.forget(rid_box["rid"], e)
            return reply(
                "400 Bad Request",
                {"error": str(e), "kind": "ValueError"},
            )
        if wal_entry is not None:
            # from here the tracker entry is the request's source of
            # truth: the pump (owning the handle's queue) feeds it and
            # the journal; this and any future connection stream FROM it
            wal.bind(wal_entry, handle)
            if stream:
                self._stream_entry(conn, ctx, wal_entry, t0, resume_from)
                return None
            return self._reply_entry(reply, wal_entry)
        if stream:
            self._stream_generate(conn, ctx, handle, t0, rid=rid_box["rid"])
            return None
        try:
            toks = handle.result(
                timeout=get_config().serve_result_timeout_s
            )
        except TimeoutError as e:
            # DeadlineExceededError (the scheduler evicted it) and the
            # result-timeout backstop both mean the same thing upstream
            return reply(
                "504 Gateway Timeout",
                {"request_id": handle.request_id, "error": str(e),
                 "kind": type(e).__name__},
                handle=handle,
            )
        except Exception as e:  # engine-side failure closed the handle
            return reply(
                "500 Internal Server Error",
                {
                    "request_id": handle.request_id,
                    "error": f"{type(e).__name__}: {e}",
                    "kind": type(e).__name__,
                },
                handle=handle,
            )
        return reply(
            "200 OK",
            {
                "request_id": handle.request_id,
                "tokens": [int(t) for t in toks],
            },
            handle=handle,
        )

    def _stale_router_epoch(self, hdr) -> Optional[Tuple[int, int]]:
        """``(placed, current)`` when a placement's ``x-router-epoch``
        header is BELOW the election lease's current epoch — the
        zombie-router case — else ``None`` (no fencing configured, no
        header, or the lease is unreadable: a broken shared filesystem
        must not reject live traffic)."""
        if self._router_epoch_fn is None or hdr is None:
            return None
        try:
            placed = int(hdr)
        except (TypeError, ValueError):
            return None
        try:
            cur = self._router_epoch_fn()
        except Exception:
            return None
        if cur is None or placed >= int(cur):
            return None
        return placed, int(cur)

    def _reply_entry(self, reply, entry):
        """Answer a NON-streaming generate from a WAL tracker entry
        (fresh admissions and duplicate-id dedupes both land here when
        the durable plane is on): wait for the entry to settle — the
        pump thread feeds it from the engine handle — then map its
        outcome through the same status ladder the handle path uses."""
        from ..utils.config import get_config

        timeout_s = get_config().serve_result_timeout_s
        deadline = time.monotonic() + timeout_s
        with entry.cond:
            while not entry.done:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return reply(
                        "504 Gateway Timeout",
                        {"error": f"no result within {timeout_s}s",
                         "kind": "TimeoutError"},
                        handle=entry.handle,
                    )
                entry.cond.wait(rem)
            err = entry.error
            toks = list(entry.tokens)
        if err is not None:
            kind, msg = err
            status = (
                "504 Gateway Timeout"
                if kind in ("TimeoutError", "DeadlineExceededError")
                else "500 Internal Server Error"
            )
            return reply(
                status, {"error": msg, "kind": kind}, handle=entry.handle
            )
        return reply(
            "200 OK",
            {"tokens": [int(t) for t in toks]},
            handle=entry.handle,
        )

    def _stream_entry(
        self, conn, ctx, entry, t0: float, from_off: int = 0
    ) -> None:
        """NDJSON streaming from a WAL tracker entry — the durable
        twin of :meth:`_stream_generate`. The already-delivered prefix
        past ``from_off`` replays immediately (a reconnecting client
        sends ``from=<count of tokens it already has>``), then the live
        tail follows as the pump lands tokens, then exactly one
        terminal line. Byte-identity of the replayed prefix with what
        the torn connection delivered is inherited from the fleet's
        deterministic replay — the tracker holds THE token sequence,
        every connection is a view of it."""
        import json

        from ..utils.config import get_config

        conn.sendall(
            (
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: application/x-ndjson; charset=utf-8\r\n"
                f"traceparent: {ctx.traceparent()}\r\n"
                "Connection: close\r\n\r\n"
            ).encode("latin-1")
        )
        cursor = max(0, int(from_off))
        timeout_s = get_config().serve_result_timeout_s
        sent = 0
        terminal: Dict[str, Any]
        try:
            while True:
                got = entry.wait(cursor, timeout_s)
                if got is None:  # the no-emission backstop fired
                    terminal = {
                        "error": f"no emission within {timeout_s}s",
                        "kind": "TimeoutError",
                        "request_id": entry.rid,
                    }
                    break
                new, done, err = got
                for t in new:
                    conn.sendall(
                        (json.dumps({"t": int(t)}) + "\n").encode("utf-8")
                    )
                cursor += len(new)
                sent += len(new)
                if done:
                    if err is None:
                        total = time.perf_counter() - t0
                        terminal = {
                            "done": True,
                            "request_id": entry.rid,
                            "tokens_total": cursor,
                            "trace_id": ctx.trace_id,
                            "timing": self._timing_payload(
                                entry.handle, total
                            ),
                        }
                    else:
                        terminal = {
                            "error": err[1],
                            "kind": err[0],
                            "request_id": entry.rid,
                        }
                    break
            conn.sendall((json.dumps(terminal) + "\n").encode("utf-8"))
            status = "200" if terminal.get("done") else "error"
        except OSError:
            # the client went away (again): the pump keeps feeding the
            # tracker and the journal, so the NEXT reconnect resumes
            # from wherever the stream is by then
            status = "client-gone"
        _flight.record(
            "serving", "generate_stream",
            status=status,
            trace_id=ctx.trace_id,
            tokens=sent,
            request_id=entry.rid,
            resumed_from=int(from_off),
            dur_s=round(time.perf_counter() - t0, 6),
        )

    def _stream_generate(self, conn, ctx, handle, t0: float,
                         rid: Optional[str] = None) -> None:
        """The NDJSON success path of ``POST /generate`` with
        ``"stream": true``: headers first (no Content-Length — the
        stream's end is the connection's), then one ``{"t": token}``
        line per emission as the engine emits it, then exactly one
        terminal line — ``{"done": true, request_id, tokens_total,
        trace_id, timing}`` or ``{"error", "kind", request_id}``. The
        per-line flush is the point: a remote router relays each token
        to its caller the moment it lands, and a member killed
        mid-stream tears the connection, which the router treats as a
        replayable replica fault (the emitted prefix folds into the
        replay prompt — byte-identity preserved)."""
        import json

        from ..utils.config import get_config

        conn.sendall(
            (
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: application/x-ndjson; charset=utf-8\r\n"
                f"traceparent: {ctx.traceparent()}\r\n"
                "Connection: close\r\n\r\n"
            ).encode("latin-1")
        )
        # a client-supplied request_id is the stream's identity even
        # without the durable plane: echo it, not the engine handle's
        rid = rid if rid is not None else handle.request_id
        sent = 0
        timeout_s = get_config().serve_result_timeout_s
        terminal: Dict[str, Any]
        try:
            while True:
                try:
                    item = handle._q.get(timeout=timeout_s)
                except Exception:  # queue.Empty: the backstop fired
                    terminal = {
                        "error": f"no emission within {timeout_s}s",
                        "kind": "TimeoutError",
                        "request_id": rid,
                    }
                    break
                if item is handle._DONE:
                    err = handle.error
                    if err is None:
                        total = time.perf_counter() - t0
                        terminal = {
                            "done": True,
                            "request_id": rid,
                            "tokens_total": sent,
                            "trace_id": ctx.trace_id,
                            "timing": self._timing_payload(handle, total),
                        }
                    else:
                        terminal = {
                            "error": str(err),
                            "kind": type(err).__name__,
                            "request_id": rid,
                        }
                    break
                conn.sendall(
                    (json.dumps({"t": int(item)}) + "\n").encode("utf-8")
                )
                sent += 1
            conn.sendall((json.dumps(terminal) + "\n").encode("utf-8"))
            status = "200" if terminal.get("done") else "error"
        except OSError:
            # the client went away mid-stream (a fenced router, a killed
            # process): nothing to answer — the engine-side stream keeps
            # its own lifecycle and the relay identity gate upstream
            # drops whatever else this request emits
            status = "client-gone"
        _flight.record(
            "serving", "generate_stream",
            status=status,
            trace_id=ctx.trace_id,
            tokens=sent,
            request_id=rid,
            dur_s=round(time.perf_counter() - t0, 6),
        )

    def _serve_one(self, conn: socket.socket) -> None:
        import pyarrow as pa

        from ..utils import get_logger

        t0 = time.perf_counter()
        kind, status = "score", "ok"
        # one gate snapshot for the inc/dec PAIR: a kill-switch flip while
        # this request is in flight must not strand the gauge
        tracked = _obs_enabled()
        if tracked:
            _m_active.adjust(1.0)
        try:
            with conn:
                # chaos: a dropped/slow connection at the door — the
                # teardown path below must absorb it like a real one
                _chaos.site("serving.conn")
                first = self._peek(conn)
                if not first:
                    # client connected and went away without a request
                    status = "empty"
                    return
                if first in self._HTTP_PREFIXES:
                    kind = "http"
                    try:
                        kind = self._serve_http(conn)
                    except OSError:
                        status = "error"
                    return
                wf = None
                try:
                    if self._mapper is None:
                        raise RuntimeError(
                            "server has no scoring program (generate-only "
                            "server; use POST /generate)"
                        )
                    rf = _CountingFile(conn.makefile("rb"), _m_bytes_in)
                    reader = pa.ipc.open_stream(rf)
                    # results buffer until the request stream ends: a
                    # client that writes its whole partition before
                    # reading (Spark's mapInArrow generator does) must
                    # never deadlock against our send buffer
                    with _span("serving.request", peer=conn.getpeername()[0]):
                        out_batches = list(self._mapper(reader))
                    conn.shutdown(socket.SHUT_RD)
                    wf = _CountingFile(conn.makefile("wb"), _m_bytes_out)
                    # response = 1 status byte, then the payload: \x00 +
                    # Arrow stream, or \x01 + utf-8 error text (the
                    # executor re-raises it as its task failure — engine
                    # errors must not look like wire corruption)
                    wf.write(b"\x00")
                    if out_batches:
                        with pa.ipc.new_stream(
                            wf, out_batches[0].schema
                        ) as w:
                            for b in out_batches:
                                w.write_batch(b)
                    else:
                        with pa.ipc.new_stream(wf, pa.schema([])):
                            pass
                    wf.flush()
                except Exception as e:
                    status = "error"
                    get_logger("interop.serving").warning(
                        "scoring connection failed", exc_info=True
                    )
                    try:
                        if wf is None:
                            wf = conn.makefile("wb")
                        wf.write(
                            b"\x01"
                            + f"{type(e).__name__}: {e}".encode(
                                "utf-8", "replace"
                            )
                        )
                        wf.flush()
                    except OSError:
                        pass  # client already gone
                finally:
                    # drain any unread request bytes BEFORE closing: a
                    # failure mid-stream leaves data in the receive
                    # buffer, and closing over it makes the kernel send
                    # RST — destroying the in-flight \x01 error reply
                    # (the client would see ConnectionReset instead of
                    # the engine error). Bounded by a timeout so a
                    # wedged client cannot pin the worker.
                    try:
                        conn.settimeout(10)
                        while conn.recv(1 << 16):
                            pass
                    except OSError:
                        pass
                    # then force the FIN at the TCP level: socket.close()
                    # defers while makefile handles are alive, and a
                    # captured log record (exc_info traceback frames —
                    # e.g. pytest's logging plugin) can pin them long
                    # after this thread exits, leaving the client
                    # blocked on read
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
        except Exception:
            status = "error"
            get_logger("interop.serving").warning(
                "scoring connection teardown failed", exc_info=True
            )
        finally:
            if tracked:
                _m_active.adjust(-1.0)
            _m_requests.inc(kind=kind, status=status)
            if kind == "score" and status != "empty":
                _m_latency.observe(time.perf_counter() - t0)
            if kind not in ("generate", "empty") and status != "empty":
                # generate requests record themselves (with trace ids)
                # inside the handler; real work (score/http) lands in
                # the same `serving` ring, while metrics/health/statusz
                # PROBES get their own — a 15s scrape + health check
                # would otherwise evict the entire trace-id request
                # history from the 512-slot ring within the hour
                ring = (
                    "probes"
                    if kind in ("metrics", "healthz", "statusz", "varz")
                    else "serving"
                )
                _flight.record(
                    ring, kind, status=status,
                    dur_s=round(time.perf_counter() - t0, 6),
                )
            self._limit.release()


def remote_arrow_mapper(address: str):
    """The executor-side function for ``DataFrame.mapInArrow`` against a
    :class:`ScoringServer` at ``"host:port"``.

    The returned closure captures only the address string and imports
    only ``socket``/``pyarrow`` inside — it pickles to Spark workers
    that have NO jax and NO tensorframes_tpu installed (the whole point:
    the engine lives on the TPU host, executors just move Arrow)."""
    host, port_s = address.rsplit(":", 1)
    port = int(port_s)

    def fn(batches):
        import socket as _socket

        import pyarrow as _pa

        it = iter(batches)
        first = next(it, None)
        if first is None:
            return
        conn = _socket.create_connection((host, port))
        try:
            wf = conn.makefile("wb")
            with _pa.ipc.new_stream(wf, first.schema) as w:
                w.write_batch(first)
                for b in it:
                    w.write_batch(b)
            wf.flush()
            conn.shutdown(_socket.SHUT_WR)  # end of request stream
            rf = conn.makefile("rb")
            status = rf.read(1)
            if status == b"\x01":  # server-side failure, text follows
                raise RuntimeError(
                    "remote scoring failed: "
                    + rf.read().decode("utf-8", "replace")
                )
            if status != b"\x00":
                raise RuntimeError(
                    "remote scoring connection closed without a response"
                )
            reader = _pa.ipc.open_stream(rf)
            for b in reader:
                yield b
        finally:
            conn.close()

    return fn


def remote_map_in_arrow(spark_df, address: str, output_schema):
    """``mapInArrow`` against a remote :class:`ScoringServer`: each Spark
    partition streams to the TPU host and back, no driver collect. Pair
    with repartitioning so partitions match the block sizes the scoring
    program wants (one connection = one partition = one logical block
    span)."""
    return spark_df.mapInArrow(remote_arrow_mapper(address), output_schema)
