"""Host-side binary codecs for ``map_blocks(decoders=)`` / ``decode_column``.

The reference's image workload reads files with ``sc.binaryFiles`` and
decodes inside the TF graph with ``tf.image.decode_jpeg`` + resize
(``read_image.py:80-87``). On TPU, image decode is host work — XLA has no
byte-stream ops, and shipping raw encoded bytes to the chip would waste
link bandwidth — so codecs run on the engine's decode thread pool, several
partitions ahead of the device (``engine/ops.py`` decoder prefetch), which
is the same decode-overlaps-compute schedule the reference got from
Spark's partition iterator feeding the session.
"""

from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np

__all__ = ["decode_image", "encode_image", "image_decoder"]


def decode_image(
    raw: bytes,
    resize_hw: Optional[Tuple[int, int]] = None,
    channels: int = 3,
) -> np.ndarray:
    """Decode PNG/JPEG/... bytes to a uint8 HWC array (the parity op for
    the reference's ``decode_jpeg`` + ``resize_images`` stage). Grayscale
    and RGBA inputs are converted to ``channels``; ``resize_hw`` uses
    bilinear, like the reference's default ``resize_images``."""
    from PIL import Image

    img = Image.open(io.BytesIO(raw))
    img = img.convert({1: "L", 3: "RGB", 4: "RGBA"}[channels])
    if resize_hw is not None:
        h, w = resize_hw
        img = img.resize((w, h), Image.BILINEAR)  # PIL takes (W, H)
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def image_decoder(
    resize_hw: Optional[Tuple[int, int]] = None, channels: int = 3
):
    """A ``bytes -> array`` codec closure for ``decoders=`` with the
    resize/channel policy bound in (decoders are probed on row 0 and must
    produce one uniform shape — fix it here, not per image)."""

    def decode(raw: bytes) -> np.ndarray:
        return decode_image(raw, resize_hw=resize_hw, channels=channels)

    return decode


def encode_image(arr: np.ndarray, format: str = "PNG") -> bytes:
    """uint8 HWC array -> encoded bytes (test/e2e helper; PNG round-trips
    losslessly, so decode(encode(x)) == x exactly)."""
    from PIL import Image

    arr = np.asarray(arr, dtype=np.uint8)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format=format)
    return buf.getvalue()
