"""Host data plane: native packing kernels + ragged buffers."""

from .packer import (
    native_available,
    pad_ragged,
    unpad_ragged,
    gather_rows,
    scatter_rows,
    gather_ragged_pad,
)
from .ragged import RaggedBuffer

__all__ = [
    "native_available",
    "pad_ragged",
    "unpad_ragged",
    "gather_rows",
    "scatter_rows",
    "gather_ragged_pad",
    "RaggedBuffer",
]
