"""Host data plane: native packing kernels, ragged buffers, binary codecs."""

from .codecs import decode_image, encode_image, image_decoder
from .packer import (
    native_available,
    pad_ragged,
    unpad_ragged,
    gather_rows,
    scatter_rows,
    gather_ragged_pad,
)
from .ragged import RaggedBuffer

__all__ = [
    "decode_image",
    "encode_image",
    "image_decoder",
    "native_available",
    "pad_ragged",
    "unpad_ragged",
    "gather_rows",
    "scatter_rows",
    "gather_ragged_pad",
    "RaggedBuffer",
]
