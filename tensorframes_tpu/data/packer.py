"""ctypes bindings for the native packer, with numpy fallbacks.

The native library is built on demand with the system toolchain (g++) and
cached next to the source; environments without a compiler silently use the
numpy implementations (same results, slower on wide ragged data). This
mirrors how the reference leans on a prebuilt native artifact for its
buffer hot loops (the TF JNI `Tensor.create`/`writeTo` paths,
``datatypes.scala:344-370``) while keeping the JVM-only path functional.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..obs.metrics import counter as _counter
from ..utils import get_logger

#: which implementation served each packer kernel call — the fleet-level
#: answer to "is this host actually running the native hot loops, or did
#: the toolchain silently fall back to numpy?" (``path``: native |
#: native_list | native_buffer | fallback)
_m_kernel_calls = _counter(
    "packer.kernel_calls_total",
    "Packer kernel invocations, by kernel and implementation path",
    labels=("kernel", "path"),
)

__all__ = [
    "native_available",
    "pad_ragged",
    "unpad_ragged",
    "gather_rows",
    "scatter_rows",
    "gather_ragged_pad",
    "code_keys",
    "set_native_threads",
    "native_threads",
]

#: outputs larger than this route to the native thread-pool executor
#: (native/executor.cpp); smaller ones stay single-threaded — splitting
#: costs more than it saves under ~a few MB
_PAR_THRESHOLD_BYTES = 8 << 20

logger = get_logger("data.packer")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "packer.cpp")
_SRC_EXEC = os.path.join(_NATIVE_DIR, "executor.cpp")
_SRC_HDR = os.path.join(_NATIVE_DIR, "kernels.h")
_LIB = os.path.join(_NATIVE_DIR, "libtfspacker.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC_CODER = os.path.join(_NATIVE_DIR, "coder.cpp")
_LIB_CODER = os.path.join(_NATIVE_DIR, "libtfscoder.so")
_coder_lib = None
_coder_tried = False


def _build() -> bool:
    # -std=c++17 explicitly: the sources use std::string_view, and
    # toolchains older than gcc 11 still default to gnu++14
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, _SRC_EXEC, "-o", _LIB,
    ]
    try:
        res = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native packer build unavailable: %s", e)
        return False
    if res.returncode != 0:
        logger.warning("native packer build failed:\n%s", res.stderr)
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < max(
            os.path.getmtime(_SRC),
            os.path.getmtime(_SRC_EXEC),
            os.path.getmtime(_SRC_HDR),  # kernel bodies live here
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.warning("native packer load failed: %s", e)
            return None
        c_char_p = ctypes.c_char_p
        c_i64 = ctypes.c_int64
        p_i64 = ctypes.POINTER(ctypes.c_int64)
        # ABI gate FIRST: a stale library must fall back to numpy with a
        # warning, not crash on a missing tfs_par_* symbol below
        try:
            lib.tfs_packer_abi_version.restype = c_i64
            abi = lib.tfs_packer_abi_version()
        except AttributeError:
            abi = -1
        if abi != 3:
            logger.warning(
                "native packer ABI %s != 3; using numpy fallback", abi
            )
            return None
        lib.tfs_pad_ragged.argtypes = [
            c_char_p, p_i64, c_i64, c_i64, c_i64, c_char_p, c_char_p,
        ]
        lib.tfs_unpad_ragged.argtypes = [
            c_char_p, p_i64, c_i64, c_i64, c_i64, c_char_p,
        ]
        lib.tfs_gather_rows.argtypes = [c_char_p, c_i64, p_i64, c_i64, c_char_p]
        lib.tfs_scatter_rows.argtypes = [c_char_p, c_i64, p_i64, c_i64, c_char_p]
        lib.tfs_gather_ragged_pad.argtypes = [
            c_char_p, p_i64, p_i64, c_i64, c_i64, c_i64, c_char_p, c_char_p,
        ]
        lib.tfs_par_gather_rows.argtypes = lib.tfs_gather_rows.argtypes
        lib.tfs_par_scatter_rows.argtypes = lib.tfs_scatter_rows.argtypes
        lib.tfs_par_pad_ragged.argtypes = lib.tfs_pad_ragged.argtypes
        lib.tfs_par_gather_ragged_pad.argtypes = (
            lib.tfs_gather_ragged_pad.argtypes
        )
        lib.tfs_executor_set_threads.argtypes = [c_i64]
        lib.tfs_executor_set_threads.restype = c_i64
        lib.tfs_executor_threads.restype = c_i64
        lib.tfs_code_keys.argtypes = [
            c_char_p, p_i64, c_i64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.tfs_code_keys.restype = c_i64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _load_coder():
    """The list-direct key coder (libtfscoder.so) is built and loaded
    separately from the packer kernels: it links against the CPython API
    (sysconfig include/lib paths), and a host where that fails must not
    take down the plain packer .so. Loaded with ``PyDLL`` — the
    extraction phase reads PyBytes internals and must hold the GIL (the
    library releases it itself around the hash pass)."""
    global _coder_lib, _coder_tried
    if _coder_lib is not None or _coder_tried:
        return _coder_lib
    with _lock:
        if _coder_lib is not None or _coder_tried:
            return _coder_lib
        _coder_tried = True
        import sysconfig

        try:
            need_build = not os.path.exists(_LIB_CODER) or (
                os.path.getmtime(_LIB_CODER) < os.path.getmtime(_SRC_CODER)
            )
        except OSError:
            # source pruned from the install: a prebuilt library is
            # usable as-is (the ABI gate below rejects stale ones)
            need_build = not os.path.exists(_LIB_CODER)
        if need_build:
            cmd = [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                f"-I{sysconfig.get_paths()['include']}",
                _SRC_CODER, "-o", _LIB_CODER,
            ]
            libdir = sysconfig.get_config_var("LIBDIR")
            if libdir:
                cmd.insert(-2, f"-L{libdir}")
            try:
                res = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=120
                )
            except (OSError, subprocess.TimeoutExpired) as e:
                logger.info("native coder build unavailable: %s", e)
                return None
            if res.returncode != 0:
                logger.warning(
                    "native coder build failed:\n%s", res.stderr
                )
                return None
        try:
            lib = ctypes.PyDLL(_LIB_CODER)
        except OSError as e:
            logger.warning("native coder load failed: %s", e)
            return None
        try:
            lib.tfs_coder_abi_version.restype = ctypes.c_int64
            abi = lib.tfs_coder_abi_version()
        except AttributeError:
            abi = -1
        if abi != 1:
            logger.warning(
                "native coder ABI %s != 1; using fallback", abi
            )
            return None
        lib.tfs_code_keys_list.argtypes = [
            ctypes.py_object, ctypes.POINTER(ctypes.c_int32)
        ]
        lib.tfs_code_keys_list.restype = ctypes.c_int64
        _coder_lib = lib
        return _coder_lib


def set_native_threads(n: int) -> int:
    """Size the native executor pool (0 = auto: hardware up to 16).
    Takes effect on the pool's next (re)creation; returns the previous
    setting. No-op (returns 0) without the native library."""
    lib = _load()
    if lib is None:
        return 0
    return int(lib.tfs_executor_set_threads(int(n)))


def native_threads() -> int:
    """The executor pool's active size (incl. the calling thread)."""
    lib = _load()
    if lib is None:
        return 1
    return int(lib.tfs_executor_threads())


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_char_p)


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _check_flat(flat: np.ndarray, offsets: np.ndarray):
    if flat.ndim != 1 or not flat.flags.c_contiguous:
        raise ValueError("flat must be a contiguous 1-D array")
    if offsets.dtype != np.int64 or offsets.ndim != 1:
        raise ValueError("offsets must be a 1-D int64 array")
    # the native path drives memcpy straight off this pointer: a
    # non-contiguous, decreasing, or out-of-range offsets array would turn
    # into negative lengths / out-of-bounds reads, so validate up front
    if not offsets.flags.c_contiguous:
        raise ValueError("offsets must be contiguous")
    if len(offsets) == 0 or offsets[0] != 0:
        raise ValueError("offsets must start at 0")
    if len(offsets) > 1 and bool(np.any(np.diff(offsets) < 0)):
        raise ValueError("offsets must be non-decreasing")
    if int(offsets[-1]) > len(flat):
        raise ValueError(
            f"offsets end at {int(offsets[-1])} beyond flat length {len(flat)}"
        )


def pad_ragged(
    flat: np.ndarray,
    offsets: np.ndarray,
    max_len: Optional[int] = None,
    pad_value=0,
) -> np.ndarray:
    """Arrow-style (flat, offsets) ragged rows -> dense [n, max_len] matrix."""
    _check_flat(flat, offsets)
    n = len(offsets) - 1
    lens = np.diff(offsets)
    ml = int(max_len) if max_len is not None else (int(lens.max()) if n else 0)
    if n and int(lens.max()) > ml:
        raise ValueError(f"max_len {ml} smaller than longest row {int(lens.max())}")
    out = np.empty((n, ml), dtype=flat.dtype)
    lib = _load()
    pad = np.asarray(pad_value, dtype=flat.dtype)
    if lib is not None:
        _m_kernel_calls.inc(kernel="pad_ragged", path="native")
        fn = (
            lib.tfs_par_pad_ragged
            if out.nbytes >= _PAR_THRESHOLD_BYTES
            else lib.tfs_pad_ragged
        )
        fn(
            _ptr(flat), _i64ptr(offsets), n, ml, flat.dtype.itemsize,
            _ptr(pad.reshape(1)), _ptr(out),
        )
        return out
    _m_kernel_calls.inc(kernel="pad_ragged", path="fallback")
    out[:] = pad
    for i in range(n):
        row = flat[offsets[i] : offsets[i + 1]]
        out[i, : len(row)] = row
    return out


def unpad_ragged(padded: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Dense [n, max_len] + per-row lengths -> flat concatenated values."""
    if padded.ndim != 2 or not padded.flags.c_contiguous:
        raise ValueError("padded must be a contiguous 2-D array")
    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    if len(lengths) != padded.shape[0]:
        raise ValueError(
            f"lengths has {len(lengths)} entries for {padded.shape[0]} rows"
        )
    if len(lengths) and (
        int(lengths.max()) > padded.shape[1] or int(lengths.min()) < 0
    ):
        raise ValueError(
            f"lengths must be within [0, {padded.shape[1]}]; got "
            f"[{int(lengths.min())}, {int(lengths.max())}]"
        )
    total = int(lengths.sum())
    out = np.empty(total, dtype=padded.dtype)
    lib = _load()
    if lib is not None:
        _m_kernel_calls.inc(kernel="unpad_ragged", path="native")
        lib.tfs_unpad_ragged(
            _ptr(padded), _i64ptr(lengths), padded.shape[0],
            padded.shape[1], padded.dtype.itemsize, _ptr(out),
        )
        return out
    _m_kernel_calls.inc(kernel="unpad_ragged", path="fallback")
    off = 0
    for i, ln in enumerate(lengths):
        out[off : off + ln] = padded[i, :ln]
        off += int(ln)
    return out


def _check_idx(idx: np.ndarray, n_rows: int) -> None:
    if len(idx) and (int(idx.min()) < 0 or int(idx.max()) >= n_rows):
        raise IndexError(
            f"row index out of range [0, {n_rows}): "
            f"[{int(idx.min())}, {int(idx.max())}]"
        )


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[k] = src[idx[k]] for fixed-width rows (any trailing dims)."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    _check_idx(idx, src.shape[0])
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    lib = _load()
    if lib is not None and src.ndim >= 1:
        _m_kernel_calls.inc(kernel="gather_rows", path="native")
        row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.dtype.itemsize
        fn = (
            lib.tfs_par_gather_rows
            if out.nbytes >= _PAR_THRESHOLD_BYTES
            else lib.tfs_gather_rows
        )
        fn(_ptr(src), row_bytes, _i64ptr(idx), len(idx), _ptr(out))
        return out
    _m_kernel_calls.inc(kernel="gather_rows", path="fallback")
    return src[idx]


def scatter_rows(src: np.ndarray, idx: np.ndarray, n_rows: int) -> np.ndarray:
    """out[idx[k]] = src[k]; inverse of :func:`gather_rows` for a
    permutation index."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if len(idx) != src.shape[0]:
        raise ValueError(f"idx has {len(idx)} entries for {src.shape[0]} rows")
    _check_idx(idx, n_rows)
    out = np.empty((n_rows,) + src.shape[1:], dtype=src.dtype)
    lib = _load()
    if lib is not None:
        _m_kernel_calls.inc(kernel="scatter_rows", path="native")
        row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.dtype.itemsize
        # the pooled scatter would race on duplicate targets (the serial
        # kernel is deterministic last-wins), so it is reserved for
        # permutation-like unique indices — checked in O(n) via bincount
        # (a sort-based uniqueness test would cost more than the copy)
        fn = lib.tfs_scatter_rows
        if out.nbytes >= _PAR_THRESHOLD_BYTES and (
            len(idx) == 0
            # no minlength: padding zeros cannot change the max, and
            # the temp stays bounded by max(idx)+1, not table size
            or int(np.bincount(idx).max()) <= 1
        ):
            fn = lib.tfs_par_scatter_rows
        fn(_ptr(src), row_bytes, _i64ptr(idx), len(idx), _ptr(out))
        return out
    _m_kernel_calls.inc(kernel="scatter_rows", path="fallback")
    out[idx] = src
    return out


def gather_ragged_pad(
    flat: np.ndarray,
    offsets: np.ndarray,
    idx: np.ndarray,
    max_len: int,
    pad_value=0,
) -> np.ndarray:
    """Gather ragged rows by index into a dense padded matrix (the map_rows
    shape-bucket stacking step)."""
    _check_flat(flat, offsets)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    n_rows = len(offsets) - 1
    if len(idx) and (int(idx.min()) < 0 or int(idx.max()) >= n_rows):
        raise IndexError(
            f"gather index out of range [0, {n_rows}): "
            f"[{int(idx.min())}, {int(idx.max())}]"
        )
    lens = np.diff(offsets)
    if len(idx) and int(lens[idx].max()) > int(max_len):
        raise ValueError(
            f"max_len {int(max_len)} smaller than longest selected row "
            f"{int(lens[idx].max())}"
        )
    out = np.empty((len(idx), int(max_len)), dtype=flat.dtype)
    lib = _load()
    pad = np.asarray(pad_value, dtype=flat.dtype)
    if lib is not None:
        _m_kernel_calls.inc(kernel="gather_ragged_pad", path="native")
        fn = (
            lib.tfs_par_gather_ragged_pad
            if out.nbytes >= _PAR_THRESHOLD_BYTES
            else lib.tfs_gather_ragged_pad
        )
        fn(
            _ptr(flat), _i64ptr(offsets), _i64ptr(idx), len(idx),
            int(max_len), flat.dtype.itemsize, _ptr(pad.reshape(1)), _ptr(out),
        )
        return out
    _m_kernel_calls.inc(kernel="gather_ragged_pad", path="fallback")
    out[:] = pad
    for k, i in enumerate(idx):
        row = flat[offsets[i] : offsets[i + 1]]
        out[k, : len(row)] = row
    return out


def code_keys(cells) -> Optional[np.ndarray]:
    """First-appearance integer codes for a list of byte strings — the
    group-by key coding pass (the role ``pandas.factorize`` plays on the
    fallback path). Two native paths, fastest first:

    1. list-direct (libtfscoder.so): pointers read straight out of the
       PyBytes objects under the GIL, hash pass with the GIL released —
       no marshalling at all (building a contiguous buffer from Python
       measured 4.5 s against 0.5 s of hashing at 10M rows);
    2. buffer path (libtfspacker.so): join + offsets, for cell lists
       holding non-``bytes`` byte-likes.

    Both are chunk-parallel with a first-appearance merge (serial on
    one-CPU hosts). Returns int32 codes (a group id is bounded by the
    row count), or ``None`` when no native library is available or a
    cell is not bytes-like (callers fall back to pandas/numpy)."""
    n = len(cells)
    if n == 0:
        return np.empty(0, dtype=np.int32)
    codes = np.empty(n, dtype=np.int32)
    coder = _load_coder()
    if coder is not None and isinstance(cells, list):
        got = coder.tfs_code_keys_list(
            cells, codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if got >= 0:
            _m_kernel_calls.inc(kernel="code_keys", path="native_list")
            return codes
        if got != -2:  # -2 = non-bytes cell; try the buffer path
            _m_kernel_calls.inc(kernel="code_keys", path="fallback")
            return None
    lib = _load()
    if lib is None:
        _m_kernel_calls.inc(kernel="code_keys", path="fallback")
        return None
    try:
        buf = b"".join(cells)
    except TypeError:
        _m_kernel_calls.inc(kernel="code_keys", path="fallback")
        return None
    lengths = np.fromiter(
        (len(c) for c in cells), dtype=np.int64, count=n
    )
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    got = lib.tfs_code_keys(
        buf, _i64ptr(offsets), n,
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if got < 0:
        _m_kernel_calls.inc(kernel="code_keys", path="fallback")
        return None
    _m_kernel_calls.inc(kernel="code_keys", path="native_buffer")
    return codes
