"""Arrow-style ragged buffers: flat values + offsets.

The TPU-friendly columnar form for variable-length rows (cf. Arrow
ListArray): one contiguous value buffer + an int64 offsets array. All
pad/bucket/slice operations become byte moves handled by the native packer.
This replaces the reference's per-row boxed handling of ragged vectors
(``TFDataOps.scala:90-113``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from . import packer

__all__ = ["RaggedBuffer"]


class RaggedBuffer:
    """Immutable (flat, offsets) ragged rows of 1-D cells."""

    __slots__ = ("flat", "offsets")

    def __init__(self, flat: np.ndarray, offsets: np.ndarray):
        flat = np.ascontiguousarray(flat)
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        if flat.ndim != 1:
            raise ValueError("flat must be 1-D")
        if offsets.ndim != 1 or len(offsets) == 0 or offsets[0] != 0:
            raise ValueError("offsets must be 1-D starting at 0")
        if offsets[-1] != len(flat):
            raise ValueError("offsets must end at len(flat)")
        self.flat = flat
        self.offsets = offsets

    @staticmethod
    def from_cells(cells: Sequence[np.ndarray]) -> "RaggedBuffer":
        lens = np.fromiter(
            (len(c) for c in cells), count=len(cells), dtype=np.int64
        )
        offsets = np.zeros(len(cells) + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        flat = (
            np.concatenate([np.ravel(c) for c in cells])
            if cells
            else np.empty(0)
        )
        return RaggedBuffer(flat, offsets)

    @property
    def num_rows(self) -> int:
        return len(self.offsets) - 1

    @property
    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets)

    @property
    def max_len(self) -> int:
        return int(self.lengths.max()) if self.num_rows else 0

    def cell(self, i: int) -> np.ndarray:
        return self.flat[self.offsets[i] : self.offsets[i + 1]]

    def cells(self) -> List[np.ndarray]:
        return [self.cell(i) for i in range(self.num_rows)]

    def pad(self, max_len: Optional[int] = None, pad_value=0) -> np.ndarray:
        """Dense [n, max_len] matrix with padding."""
        return packer.pad_ragged(self.flat, self.offsets, max_len, pad_value)

    def gather_pad(
        self, idx: np.ndarray, max_len: Optional[int] = None, pad_value=0
    ) -> np.ndarray:
        """Selected rows stacked into a dense padded matrix."""
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        ml = (
            int(max_len)
            if max_len is not None
            else (int(self.lengths[idx].max()) if len(idx) else 0)
        )
        return packer.gather_ragged_pad(
            self.flat, self.offsets, idx, ml, pad_value
        )

    @staticmethod
    def from_padded(padded: np.ndarray, lengths: np.ndarray) -> "RaggedBuffer":
        lengths = np.ascontiguousarray(lengths, dtype=np.int64)
        offsets = np.zeros(len(lengths) + 1, dtype=np.int64)
        np.cumsum(lengths, out=offsets[1:])
        return RaggedBuffer(packer.unpad_ragged(padded, lengths), offsets)

    def __repr__(self):
        return f"RaggedBuffer(rows={self.num_rows}, values={len(self.flat)})"
