"""tensorframes_tpu: manipulate columnar dataframes with JAX/XLA programs on TPU.

A TPU-native framework with the capabilities of TensorFrames (the reference
at ``/root/reference``: TensorFlow-on-Spark-DataFrames). Where the reference
pairs a Spark cluster with per-partition libtensorflow sessions, this
framework pairs a columnar table with XLA-compiled programs over a TPU
device mesh:

- frames are columnar host tables partitioned on the row axis
  (:mod:`tensorframes_tpu.frame`);
- user programs are captured JAX functions or a lazy op-builder DSL
  (:mod:`tensorframes_tpu.capture`), analyzed with ``jax.eval_shape``
  instead of the reference's driver-side TF shape inference
  (``TensorFlowOps.scala:101-141``);
- the engine compiles one XLA program per shape bucket and executes blocks
  on device (:mod:`tensorframes_tpu.engine`);
- distribution is a ``jax.sharding.Mesh``: one table shard per chip,
  reductions ride ICI collectives instead of a driver funnel
  (:mod:`tensorframes_tpu.parallel`).

Public API parity with the reference's nine functions (``core.py:11-12``):
``map_blocks, map_rows, reduce_blocks, reduce_rows, aggregate, analyze,
print_schema, block, row``.
"""

__version__ = "0.1.0"

from .utils.config import enable_compilation_cache

# the reference pays zero compile cost (TF 1.x sessions run GraphDefs
# directly); the persistent XLA cache is this framework's equivalent —
# fresh processes reload compiled executables instead of recompiling.
# Opt out with TFT_NO_COMPILE_CACHE=1.
enable_compilation_cache()

from .schema import Shape, Unknown
from .frame import TensorFrame, GroupedFrame, Row
from .engine import (
    map_blocks,
    precompile,
    map_rows,
    reduce_blocks,
    reduce_rows,
    aggregate,
    analyze,
    print_schema,
    explain,
    block,
    row,
    run_job,
    resume_job,
    run_worker,
    wait_job,
    journal_status,
    JobResult,
    QuarantinedBlock,
    WorkerReport,
    load_quarantine,
    InputNotFoundError,
    InvalidTypeError,
    InvalidDimensionError,
    OutputCollisionError,
)
from .capture import (
    CapturedGraph,
    Node,
    graph,
    scope,
    placeholder,
    constant,
    build_graph,
    apply_op,
    serialize_graph,
    deserialize_graph,
    save_graph,
    load_graph,
    functions,
)
from .builder import OpBuilder
from . import obs, schema, tune, utils

__all__ = [
    # the reference's nine public functions (core.py:11-12)
    "map_blocks",
    "precompile",
    "enable_compilation_cache",
    "map_rows",
    "reduce_blocks",
    "reduce_rows",
    "aggregate",
    "analyze",
    "print_schema",
    "block",
    "row",
    # durable batch jobs (engine/jobs.py) + distributed drain
    # (engine/dist_jobs.py)
    "run_job",
    "resume_job",
    "run_worker",
    "wait_job",
    "journal_status",
    "JobResult",
    "QuarantinedBlock",
    "WorkerReport",
    "load_quarantine",
    # frames & schema
    "Shape",
    "Unknown",
    "TensorFrame",
    "GroupedFrame",
    "Row",
    "explain",
    # capture layer
    "CapturedGraph",
    "Node",
    "graph",
    "scope",
    "placeholder",
    "constant",
    "build_graph",
    "apply_op",
    "serialize_graph",
    "deserialize_graph",
    "save_graph",
    "load_graph",
    "functions",
    "OpBuilder",
    "obs",
    "schema",
    "tune",
    "utils",
    # errors
    "InputNotFoundError",
    "InvalidTypeError",
    "InvalidDimensionError",
    "OutputCollisionError",
    "__version__",
]
