"""Elastic multi-host serving fleet: lease-based membership, host-death
failover, and zero-downtime rolling weight swaps.

PR 17's fleet (``serve/fleet.py``) replicates engines INSIDE one
process: the router holds every
:class:`~tensorframes_tpu.serve.GenerationEngine` object, so a "replica
death" is an exception, never a dead host. This module is the
multi-host tier — the deployment shape where each replica is its own
OS process (its own chip, its own ``ScoringServer`` ingress) and the
router reaches it over HTTP:

- :class:`MemberRegistry` — membership as **epoch-stamped lease files**
  (:class:`~tensorframes_tpu.utils.leases.LeaseStore`, the primitive
  generalized out of ``engine/dist_jobs.py``) in a shared filesystem
  directory. A serving process registers itself with its URL and model
  shape, a background heartbeat keeps the lease fresh, and the epoch in
  the filename is the **fencing token**: a member whose heartbeat
  lapses past the TTL is presumed dead and fenced by a tombstone at
  ``epoch + 1``; if the "dead" process was merely wedged and wakes up,
  its next registry write raises
  :class:`~tensorframes_tpu.utils.failures.StaleLeaseError` — the
  zombie cannot re-assert itself (exactly the dist-jobs write fence).
- :class:`RemoteEngine` — the router-side adapter that makes a remote
  member look like a local engine to the PR-17 router: ``submit()``
  opens a streaming ``POST /generate`` (NDJSON) against the member's
  ingress and relays each token as it lands; ``health()`` forwards
  ``GET /healthz``. A connection torn mid-stream (kill -9, host gone)
  closes the relay with a REPLAYABLE error, so the router resubmits the
  stream's remainder to a survivor recompute-style — byte-identical for
  greedy and seeded sampling, exactly like in-process failover.
- :class:`MemberAgent` — the member-side state machine
  (``ready | draining | probing | swapping | fenced``) wired into the
  server's ``/readyz`` and ``POST /admin/lifecycle``: drains stop
  admission at the ingress while in-flight streams finish, SIGTERM
  triggers drain → final telemetry export → lease release, and a
  lease lost underneath us (we were presumed dead) stops admission
  immediately.
- :func:`connect_fleet` — builds a :class:`~.fleet.Fleet` in
  remote-replica mode (pre-built ``engines=``) plus a registry-sync
  hook on the router tick: new registrations join the roster, expired
  heartbeats fence the member like in-process fencing (streams replay
  to survivors), tombstones and resignations leave.
- :func:`rolling_restart` / :func:`rolling_weight_swap` — one member
  at a time: drain (admission stops, in-flight finishes or migrates),
  restart or hot-swap weights (``engine.swap_weights`` — a device_put
  + pointer flip, zero recompiles), then a **probe generation must
  pass before re-admission**; a failed probe rolls the weights back
  (fleet-wide, so replicas never serve mixed weights) and halts the
  rollout.
- :class:`Autoscaler` — watches the PR-12 time-series (queue depth,
  pages in use, inter-token p99) and calls injectable spawn/drain
  callbacks with cooldown and min/max bounds.

Liveness vs safety, stated once: the lease TTL
(``member_lease_ttl_s``) only affects how FAST a dead member is
noticed; correctness never depends on it. A premature fence of a live
member costs a replay (byte-identical) and the fenced member learns
via ``on_lost``/``StaleLeaseError`` — it can re-register under a new
epoch whenever it is actually healthy.

Chaos sites: ``fleet.member_heartbeat`` fires in the member's
heartbeat sweep (``latency`` past the TTL is the presumed-dead drill);
``fleet.registry`` fires in registry reads/writes (``transient`` there
retries invisibly). Metrics: ``fleet.members``,
``fleet.member_fences_total``, ``fleet.rollouts_total{outcome}``,
``fleet.scale_decisions_total{direction}`` (docs/observability.md).
Cookbook: docs/fault_tolerance.md "Elastic fleet";
deployment shapes: docs/serving_llm.md.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import flight as _flight
from ..obs.metrics import counter as _counter, gauge as _gauge
from ..utils import chaos as _chaos
from ..utils.config import get_config
from ..utils.failures import (
    DeadlineExceededError,
    StaleLeaseError,
    StaleRouterEpochError,
    TenantThrottledError,
    run_with_retries,
)
from ..utils.leases import LeaseStore, LeaseView
from ..utils.logging import get_logger
from .engine import EngineUnhealthyError
from .fleet import Fleet
from .router_ha import ROUTER_LEASE_KEY, router_epoch_from
from .scheduler import GenerationHandle, QueueFullError

__all__ = [
    "Autoscaler",
    "LocalProcessProvisioner",
    "MemberAgent",
    "MemberRegistry",
    "RemoteEngine",
    "connect_fleet",
    "load_params",
    "rolling_restart",
    "rolling_weight_swap",
    "save_params",
]

logger = get_logger("serve.membership")

_m_members = _gauge(
    "fleet.members",
    "Live members in the shared registry (fresh heartbeat, not "
    "tombstoned)",
)
_m_member_fences = _counter(
    "fleet.member_fences_total",
    "Members fenced via lease tombstone after an expired heartbeat "
    "(presumed dead; their streams replayed to survivors)",
)
_m_rollouts = _counter(
    "fleet.rollouts_total",
    "Rolling restarts / weight swaps, by terminal outcome "
    "(ok | rolled_back | halted)",
    labels=("outcome",),
)
_m_scale_decisions = _counter(
    "fleet.scale_decisions_total",
    "Autoscaler actions taken, by direction (up | down)",
    labels=("direction",),
)


# -- checkpoint helpers ----------------------------------------------------
#
# A deliberately tiny format for the SERVING plane's hot swaps: flatten
# the params pytree (nested dicts + per-block lists) to dotted keys in
# one ``np.savez``. Training-state checkpointing keeps its Orbax path
# (utils/checkpoint.py); serving processes swapping weights need no
# checkpointing dependency at all, just numpy.

def _flatten_params(tree: Any, prefix: str, out: Dict[str, np.ndarray]):
    if isinstance(tree, dict):
        for k in tree:
            if "." in str(k):
                raise ValueError(f"param key {k!r} contains '.'")
            _flatten_params(tree[k], f"{prefix}{k}.", out)
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            _flatten_params(v, f"{prefix}[{i}].", out)
    else:
        out[prefix[:-1]] = np.asarray(tree)


def save_params(path: str, model_or_params: Any) -> str:
    """Save a model's params (or a bare params dict) as one ``.npz``
    the rolling weight swap can ship to members. Returns ``path``."""
    params = getattr(model_or_params, "params", model_or_params)
    flat: Dict[str, np.ndarray] = {}
    _flatten_params(params, "", flat)
    with open(path, "wb") as f:
        np.savez(f, **flat)
    return path


def load_params(path: str) -> Dict[str, Any]:
    """Load a :func:`save_params` checkpoint back into the nested
    params structure (dicts, per-block lists, static ints restored as
    Python scalars) that :meth:`GenerationEngine.swap_weights`
    validates against the live model."""
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    root: Dict[str, Any] = {}
    for key in sorted(flat):
        parts = key.split(".")
        node: Any = root
        for i, part in enumerate(parts):
            last = i == len(parts) - 1
            if part.startswith("[") and part.endswith("]"):
                idx = int(part[1:-1])
                while len(node) <= idx:
                    node.append(None)
                if last:
                    node[idx] = _unflatten_leaf(flat[key])
                else:
                    if node[idx] is None:
                        node[idx] = (
                            [] if parts[i + 1].startswith("[") else {}
                        )
                    node = node[idx]
            else:
                if last:
                    node[part] = _unflatten_leaf(flat[key])
                else:
                    if part not in node:
                        node[part] = (
                            [] if parts[i + 1].startswith("[") else {}
                        )
                    node = node[part]
    return root


def _unflatten_leaf(arr: np.ndarray) -> Any:
    # static scalars (``n_heads``) round-trip as 0-d arrays; the model
    # treats them as Python ints, so restore them that way
    return arr.item() if arr.ndim == 0 else arr


# -- the shared registry ---------------------------------------------------


class MemberRegistry(LeaseStore):
    """The fleet's membership table: one lease per member under
    ``<path>/leases/``, metadata (URL, pid, model shape, lifecycle
    state) in the lease payload.

    Members call :meth:`register` once and :meth:`publish_state` on
    lifecycle transitions; the inherited heartbeat thread renews the
    lease every ``heartbeat_s``. Routers call :meth:`members` to scan
    and :meth:`fence` to tombstone a member whose heartbeat lapsed —
    the steal races at ``epoch + 1``, so concurrent routers fence a
    victim exactly once, and the victim's own next write raises
    :class:`StaleLeaseError` (the zombie rejection)."""

    def __init__(
        self,
        path: str,
        worker_id: Optional[str] = None,
        ttl_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
    ):
        cfg = get_config()
        if worker_id is None:
            worker_id = (
                f"{socket.gethostname()}-{os.getpid()}-"
                f"{uuid.uuid4().hex[:6]}"
            )
        super().__init__(
            path,
            worker_id,
            ttl_s=float(
                cfg.member_lease_ttl_s if ttl_s is None else ttl_s
            ),
            heartbeat_s=float(
                cfg.member_heartbeat_s
                if heartbeat_s is None
                else heartbeat_s
            ),
        )

    # every registry mutation/scan passes the chaos site inside a retry
    # loop: a ``transient`` there (flaky shared filesystem) is invisible

    def register(self, name: str, meta: Dict[str, Any]) -> int:
        """Claim the member's lease and publish its registration
        metadata (``url``, ``pid``, ``state``, model shape). Raises
        ``RuntimeError`` when the name is live-leased by another
        process (two members may not share a name)."""

        def attempt() -> int:
            _chaos.site("fleet.registry")
            epoch = self.acquire(name, meta=meta)
            if epoch is None:
                cur = self._scan(name)
                if cur is not None and cur.terminal:
                    epoch = self._reincarnate(name, cur, meta)
            if epoch is None:
                raise RuntimeError(
                    f"member name {name!r} is live-leased by another "
                    f"process"
                )
            return epoch

        epoch = run_with_retries(attempt, what="fleet.registry")
        _flight.record(
            "membership", "register",
            member=name, epoch=epoch, url=meta.get("url"),
        )
        logger.warning(
            "membership: %s registered as %r epoch %d (%s)",
            self.worker_id, name, epoch, meta.get("url"),
        )
        return epoch

    def _reincarnate(self, name, cur: LeaseView, meta) -> Optional[int]:
        """Claim a live lease PAST a tombstone: a fresh process reusing
        a fenced/resigned member's name is a new incarnation and races
        for ``tombstone_epoch + 1`` — epochs stay monotonic, so the old
        incarnation's zombie writes stay epoch-rejected forever. (Job
        leases deliberately lack this: a terminal block must never
        re-run; a terminal MEMBER NAME may serve again.)"""
        epoch = cur.epoch + 1
        fname = f"{name}.e{epoch:06d}.lease"
        if not self._create_excl(fname, self._payload(epoch, meta=meta)):
            return None  # lost the race to another new incarnation
        with self._lock:
            self._held[name] = (epoch, fname)
        self._ensure_heartbeat()
        self._unlink_superseded(name, epoch)
        return epoch

    def publish_state(self, name: str, **meta_updates: Any) -> int:
        """Fenced metadata write: merge ``meta_updates`` over the
        member's current metadata. Raises :class:`StaleLeaseError`
        when this process no longer owns the lease — a fenced zombie's
        late write lands HERE and is rejected."""

        def attempt() -> int:
            _chaos.site("fleet.registry")
            cur = self._scan(name)
            meta = dict(cur.meta) if cur is not None else {}
            meta.update(meta_updates)
            return self.publish(name, meta)

        return run_with_retries(attempt, what="fleet.registry")

    def members(self) -> List[LeaseView]:
        """Every member's current lease view (live, expired, and
        tombstoned alike — the router-side sync decides what each
        means)."""

        def attempt() -> List[LeaseView]:
            _chaos.site("fleet.registry")
            return self.scan_all()

        return run_with_retries(attempt, what="fleet.registry")

    def fence(self, name: str) -> Optional[int]:
        """Tombstone a presumed-dead member at ``epoch + 1``. Returns
        the tombstone epoch, or ``None`` when another router already
        fenced it (or it resigned) — the exactly-once guarantee rides
        the exclusive epoch-file create."""

        def attempt() -> Optional[int]:
            _chaos.site("fleet.registry")
            return self.steal(name, state="fenced")

        epoch = run_with_retries(attempt, what="fleet.registry")
        if epoch is not None:
            _m_member_fences.inc()
            _flight.record(
                "membership", "fence", member=name, epoch=epoch,
            )
            logger.warning(
                "membership: member %r fenced at epoch %d (heartbeat "
                "expired — presumed dead)", name, epoch,
            )
        return epoch

    def resign(self, name: str) -> None:
        """Clean departure: tombstone our own lease as ``resigned`` so
        routers drop the member without fencing theatrics."""
        self.mark_state(name, "resigned")
        _flight.record("membership", "resign", member=name)

    def _heartbeat_sweep(self) -> None:
        # the presumed-death drill: ``latency`` injected here past the
        # TTL delays renewal until the lease has expired and a router
        # fences us; ``transient`` skips one sweep (survivable)
        _chaos.site("fleet.member_heartbeat")
        super()._heartbeat_sweep()


# -- the router-side remote engine adapter ---------------------------------


class _RemotePool:
    """Placement-key shim: the router sorts candidates by
    ``pool.pages_free``; for a remote member that is the last health
    poll's view (the watchdog refreshes it every tick)."""

    def __init__(self, engine: "RemoteEngine"):
        self._engine = engine

    @property
    def pages_free(self) -> int:
        h = self._engine._last_health
        return max(
            0,
            int(h.get("pages_capacity", 0)) - int(h.get("pages_in_use", 0)),
        )


class _RemoteSlot:
    __slots__ = ("req",)

    def __init__(self, tenant: str):
        self.req = _RemoteSlotReq(tenant)


class _RemoteSlotReq:
    __slots__ = ("tenant",)

    def __init__(self, tenant: str):
        self.tenant = tenant


class _RemoteScheduler:
    """Scheduler-shaped view of a remote member, backed by the relays
    this ROUTER has open against it (per-tenant accounting must count
    this router's own in-flight placements synchronously — the remote
    health poll lags a tick) plus the health poll's queue depth."""

    def __init__(self, engine: "RemoteEngine"):
        self._engine = engine

    @property
    def queue_depth(self) -> int:
        return int(self._engine._last_health.get("queue_depth", 0))

    @property
    def slots(self) -> List[Optional[_RemoteSlot]]:
        with self._engine._lock:
            tenants = [
                t for _, t in self._engine._inflight.values()
            ]
        return [_RemoteSlot(t) for t in tenants]

    def tenant_counts(self) -> Tuple[dict, dict]:
        active: Dict[str, int] = {}
        with self._engine._lock:
            for _, tenant in self._engine._inflight.values():
                active[tenant] = active.get(tenant, 0) + 1
        return active, {}

    def has_work(self) -> bool:
        with self._engine._lock:
            return bool(self._engine._inflight)

    def fail_all(self, error: BaseException) -> int:
        return self._engine._fail_inflight(error)


class RemoteEngine:
    """A remote serving member, duck-typed as a local engine for the
    PR-17 router: ``submit()`` opens a streaming ``POST /generate``
    against the member's ingress and relays NDJSON tokens into the
    router's handle the moment they land; ``health()`` forwards ``GET
    /healthz``. A torn connection mid-stream (the member was killed, or
    the host vanished) finishes the relay with a replayable
    ``RuntimeError`` — the router folds the emitted prefix into the
    prompt and resubmits to a survivor, byte-identical.

    The ``_thread is None`` shape is deliberate: the router's fence
    path then drains via :meth:`_fail_inflight` (this router's relays)
    instead of trying to reach into a remote process, and the probe
    path's ``run_until_idle()`` is a no-op (the member steps itself).
    """

    #: pre-submit error kinds from the member's JSON replies, re-raised
    #: as the exception class the router's placement loop expects; a
    #: member answering "Draining" raced an administrative drain — the
    #: router treats it like unhealthy and tries the next candidate
    _KIND_MAP: Dict[str, Callable[[str], BaseException]] = {
        "QueueFullError": QueueFullError,
        "EngineUnhealthyError": EngineUnhealthyError,
        "Draining": EngineUnhealthyError,
        "ValueError": ValueError,
        "DeadlineExceededError": DeadlineExceededError,
        "TimeoutError": TimeoutError,
        # the member refused a ZOMBIE router's placement (its
        # x-router-epoch is below the election lease's current epoch,
        # serve/router_ha.py) — non-replayable: the new active router
        # already owns this request
        "StaleRouterEpochError": StaleRouterEpochError,
    }

    def __init__(
        self,
        name: str,
        url: str,
        *,
        eos_id: Optional[int] = None,
        max_seq_len: int = 2048,
        connect_timeout_s: float = 5.0,
    ):
        self.name = name
        self.url = url  # "host:port"
        self.eos_id = eos_id
        self.max_seq_len = int(max_seq_len)
        self.connect_timeout_s = float(connect_timeout_s)
        self.healthy = True
        #: ``() -> Optional[int]``: the placing fleet's router-election
        #: epoch (set by the membership sync when router HA is attached;
        #: ``serve/router_ha.py``). None / returning None → no fencing
        #: header on the wire, the pre-HA format.
        self.router_epoch_fn: Optional[Callable[[], Optional[int]]] = None
        self._stop_wedged = False
        self._thread = None
        self._poison = None
        self._lock = threading.Lock()
        self._id_lock = threading.Lock()
        self._req_counter = 0
        #: rid -> (handle, tenant) for relays this router holds open
        self._inflight: Dict[int, Tuple[GenerationHandle, str]] = {}
        self._last_health: Dict[str, Any] = {}
        self.scheduler = _RemoteScheduler(self)
        self.pool = _RemotePool(self)

    # -- HTTP plumbing -----------------------------------------------------

    def _connect(self) -> socket.socket:
        host, _, port = self.url.rpartition(":")
        return socket.create_connection(
            (host, int(port)), timeout=self.connect_timeout_s
        )

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Tuple[int, dict]:
        """One plain (non-streaming) HTTP exchange with the member;
        returns ``(status_code, parsed_json_body)``."""
        payload = b"" if body is None else json.dumps(body).encode("utf-8")
        conn = self._connect()
        try:
            if timeout_s is not None:
                conn.settimeout(timeout_s)
            conn.sendall(
                (
                    f"{method} {path} HTTP/1.1\r\n"
                    f"Host: {self.url}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"Connection: close\r\n\r\n"
                ).encode("latin-1")
                + payload
            )
            f = conn.makefile("rb")
            status_line = f.readline().decode("latin-1", "replace")
            status = int(status_line.split(" ", 2)[1])
            while f.readline() not in (b"\r\n", b"\n", b""):
                pass  # headers; Connection: close → body runs to EOF
            raw = f.read()
        finally:
            conn.close()
        try:
            parsed = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError:
            parsed = {}
        return status, parsed if isinstance(parsed, dict) else {}

    # -- the engine surface the router drives ------------------------------

    def health(self) -> Dict[str, Any]:
        """The member's ``GET /healthz`` snapshot, shaped for the
        router's watchdog. A connection failure reads as unhealthy —
        the watchdog fences on it, and the registry sweep (lease
        expiry) independently confirms an actual death."""
        try:
            status, body = self._request(
                "GET", "/healthz", timeout_s=self.connect_timeout_s
            )
        except OSError as e:
            self.healthy = False
            return {
                "healthy": False,
                "reachable": False,
                "error": f"{type(e).__name__}: {e}",
                "last_step_age_s": 0.0,
                "queue_depth": 0,
                "active_slots": 0,
                "pages_in_use": 0,
                "pages_capacity": 0,
                "stepping_thread_alive": False,
            }
        body.setdefault("last_step_age_s", 0.0)
        body.setdefault("queue_depth", 0)
        body.setdefault("active_slots", 0)
        body.setdefault("pages_in_use", 0)
        body.setdefault("pages_capacity", 0)
        body.setdefault("stepping_thread_alive", True)
        body["healthy"] = bool(body.get("healthy")) and status == 200
        body["reachable"] = True
        self._last_health = body
        self.healthy = body["healthy"]
        return body

    @property
    def num_step_programs(self) -> int:
        return int(self._last_health.get("num_step_programs", 0))

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        block: bool = True,
        timeout: Optional[float] = None,
        deadline: Optional[float] = None,
        trace=None,
        tenant: Optional[str] = None,
        _handle_factory=None,
    ) -> GenerationHandle:
        """Open a streaming generation against the member. Pre-submit
        refusals re-raise as the exception class the member named in
        its JSON ``kind`` (queue full, unhealthy, throttled, 400);
        after the 200 status line a daemon reader relays each NDJSON
        token into the handle, and a torn connection finishes the
        handle with a replayable error."""
        if not self.healthy:
            raise EngineUnhealthyError(
                f"remote member {self.name} is unhealthy"
            )
        spec: Dict[str, Any] = {
            "prompt": [int(t) for t in np.asarray(prompt).ravel()],
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature),
            "top_p": float(top_p),
            "seed": int(seed),
            "stream": True,
        }
        if eos_id is not None:
            spec["eos_id"] = int(eos_id)
        if deadline is not None:
            spec["deadline_s"] = float(deadline)
        if tenant:
            spec["tenant"] = str(tenant)
        payload = json.dumps(spec).encode("utf-8")
        traceparent = None
        if trace is not None:
            try:
                traceparent = trace.traceparent()
            except Exception:
                traceparent = None
        with self._id_lock:
            self._req_counter += 1
            rid = self._req_counter
        router_epoch = None
        if self.router_epoch_fn is not None:
            try:
                router_epoch = self.router_epoch_fn()
            except Exception:
                router_epoch = None
        conn = None
        try:
            conn = self._connect()
            extra = (
                f"traceparent: {traceparent}\r\n" if traceparent else ""
            )
            if router_epoch is not None:
                # the fencing token: a member whose election-lease view
                # is AHEAD of this epoch rejects the placement (zombie
                # router; serve/router_ha.py)
                extra += f"x-router-epoch: {int(router_epoch)}\r\n"
            conn.sendall(
                (
                    f"POST /generate HTTP/1.1\r\n"
                    f"Host: {self.url}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"{extra}"
                    f"Connection: close\r\n\r\n"
                ).encode("latin-1")
                + payload
            )
            f = conn.makefile("rb")
            status_line = f.readline().decode("latin-1", "replace")
            status = int(status_line.split(" ", 2)[1])
            # keep the refusal headers: the member's own Retry-After
            # must reach the ultimate client verbatim, not be
            # recomputed from this router's (different) backlog
            resp_headers: Dict[str, str] = {}
            while True:
                line = f.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.partition(b":")
                resp_headers[
                    k.strip().lower().decode("latin-1", "replace")
                ] = v.strip().decode("latin-1", "replace")
            if status != 200:
                raw = f.read()
                conn.close()
                self._raise_refusal(
                    status, raw,
                    retry_after=resp_headers.get("retry-after"),
                )
        except (OSError, IndexError, ValueError) as e:
            # the member went away between the health poll and this
            # placement (or refused the connection outright): shaped as
            # unhealthy so the router's placement loop moves to the
            # next candidate this tick
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            if isinstance(e, (IndexError, ValueError)):
                raise EngineUnhealthyError(
                    f"remote member {self.name} sent a malformed "
                    f"response: {e}"
                ) from e
            raise EngineUnhealthyError(
                f"remote member {self.name} unreachable: "
                f"{type(e).__name__}: {e}"
            ) from e
        handle = (
            _handle_factory(rid)
            if _handle_factory is not None
            else GenerationHandle(rid)
        )
        with self._lock:
            self._inflight[rid] = (handle, str(tenant or ""))
        reader = threading.Thread(
            target=self._relay,
            args=(conn, f, rid, handle),
            name=f"tft-remote-relay-{self.name}-{rid}",
            daemon=True,
        )
        reader.start()
        return handle

    def _raise_refusal(
        self,
        status: int,
        raw: bytes,
        retry_after: Optional[str] = None,
    ) -> None:
        """Re-raise a member's pre-submit refusal as the exception
        class it named. ``retry_after`` (the member's literal
        ``Retry-After`` header) rides the exception as
        ``retry_after_hint`` so the serving layer fronting this router
        can echo the MEMBER's verbatim hint to the client instead of
        recomputing one from the router's own (empty) backlog."""
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError:
            body = {}
        kind = str(body.get("kind", ""))
        msg = str(
            body.get("error", f"member {self.name} answered {status}")
        )

        def _hinted(exc: BaseException) -> BaseException:
            exc.retry_after_hint = retry_after
            return exc

        if kind == "TenantThrottledError":
            raise _hinted(
                TenantThrottledError(
                    msg,
                    retry_after=float(body.get("retry_after", 1.0)),
                    reason=str(body.get("reason", "quota")),
                    tenant=str(body.get("tenant", "")),
                )
            )
        exc_cls = self._KIND_MAP.get(kind)
        if exc_cls is not None:
            raise _hinted(exc_cls(msg))
        if status in (503, 501):
            raise _hinted(EngineUnhealthyError(msg))
        if status == 400:
            raise ValueError(msg)
        raise RuntimeError(f"member {self.name}: HTTP {status}: {msg}")

    def _relay(self, conn, f, rid: int, handle: GenerationHandle) -> None:
        """Reader thread for one streaming generation: NDJSON lines →
        handle emissions; the terminal line (or a torn connection)
        closes the handle. The handle is a router relay, so its close
        reports to the fleet's failover machinery."""
        err: Optional[BaseException] = None
        terminal = False
        try:
            conn.settimeout(get_config().serve_result_timeout_s)
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line.decode("utf-8"))
                if "t" in d:
                    handle._emit(int(d["t"]))
                    continue
                terminal = True
                if not d.get("done"):
                    kind = str(d.get("kind", "RuntimeError"))
                    exc_cls = self._KIND_MAP.get(kind, RuntimeError)
                    err = exc_cls(str(d.get("error", "remote error")))
                break
            if not terminal:
                # EOF before the terminal line: the member died
                # mid-stream (kill -9, host gone) — a REPLAYABLE fault;
                # the router folds the emitted prefix into the replay
                err = RuntimeError(
                    f"member {self.name} connection lost mid-stream "
                    f"(request {rid})"
                )
        except (OSError, ValueError) as e:
            err = RuntimeError(
                f"member {self.name} stream failed mid-flight: "
                f"{type(e).__name__}: {e}"
            )
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._inflight.pop(rid, None)
            handle._finish(err)

    def _fail_inflight(self, error: BaseException) -> int:
        """Fail every relay this router holds open against the member
        (the router's fence-drain path for a ``_thread is None``
        engine). The remote process — if it still exists — keeps
        decoding into closed sockets; its late bytes go nowhere."""
        with self._lock:
            victims = list(self._inflight.values())
            self._inflight.clear()
        for handle, _ in victims:
            handle._finish(error)
        return len(victims)

    def inject_fault(self, error: BaseException) -> None:
        self._fail_inflight(error)

    def restart(self) -> "RemoteEngine":
        """Ask the member to restart its engine (``POST
        /admin/lifecycle``) — the auto-restart path after a fence.
        Raises when the member is unreachable or refuses (it stays
        fenced for the next attempt)."""
        status, body = self.lifecycle("restart")
        if status != 200:
            raise RuntimeError(
                f"member {self.name} restart failed: HTTP {status}: "
                f"{body.get('error')}"
            )
        self.healthy = True
        return self

    def lifecycle(self, action: str, **spec: Any) -> Tuple[int, dict]:
        """Drive the member's lifecycle actuator. Returns
        ``(status, body)`` — rollout orchestration checks the status
        rather than interpreting exceptions."""
        return self._request(
            "POST",
            "/admin/lifecycle",
            body={"action": action, **spec},
            timeout_s=max(self.connect_timeout_s, 30.0),
        )

    def start(self) -> "RemoteEngine":
        return self  # the member steps itself

    def run_until_idle(self) -> None:
        pass  # probe results arrive over the stream; nothing to drive

    def stop(self) -> None:
        # the router stopping must NOT stop the remote member (other
        # routers may be serving through it); open relays are failed by
        # Fleet.stop()'s sweep
        pass


# -- the member-side agent -------------------------------------------------


class MemberAgent:
    """One serving process's membership state machine, wired into its
    :class:`~tensorframes_tpu.interop.serving.ScoringServer`:

    - ``/readyz`` answers from :meth:`_readiness` — 503 unless the
      state is ``ready`` (draining / probing / swapping / fenced are
      healthy-but-not-admitting states; ``/healthz`` stays 200);
    - ``POST /admin/lifecycle`` drives :meth:`_lifecycle` (drain /
      admit / restart / swap / rollback / status / resign);
    - the registry lease carries ``state`` in its metadata, so routers
      see transitions without polling every member's HTTP endpoint;
    - SIGTERM (:meth:`install_sigterm`) triggers the graceful drain:
      stop admission, wait for in-flight streams to finish, export a
      final telemetry snapshot, resign the lease, stop the server.

    ``swap`` loads a :func:`save_params` checkpoint and hot-swaps it
    into the live engine (``swap_weights`` — a device_put + pointer
    flip under the step lock, zero recompiles), stashing the old params
    so ``rollback`` can restore them when the orchestrator's probe
    fails."""

    def __init__(
        self,
        engine,
        registry: MemberRegistry,
        name: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        drain_timeout_s: float = 30.0,
        tier: str = "mixed",
        server_kwargs: Optional[Dict[str, Any]] = None,
    ):
        from ..interop.serving import ScoringServer
        from .tiers import TIERS

        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; expected one of {TIERS}")
        self.engine = engine
        self.registry = registry
        self.name = name
        #: advertised placement role (serve/tiers.py), carried in the
        #: lease metadata so routers apply it on join without polling
        self.tier = str(tier)
        self.drain_timeout_s = float(drain_timeout_s)
        self._state = "ready"
        self._state_lock = threading.Lock()
        self._old_params: Optional[Dict[str, Any]] = None
        self._shutdown_done = threading.Event()
        kw = dict(server_kwargs or {})
        # the member-side half of zombie-router fencing: /generate
        # compares a placement's x-router-epoch header against the
        # election lease's current epoch in the shared registry dir and
        # answers 409 StaleRouterEpochError when it is superseded
        # (serve/router_ha.py; cached scan, ~one clock read/request)
        kw.setdefault("router_epoch_fn", router_epoch_from(registry))
        self.server = ScoringServer(
            engine=engine,
            host=host,
            port=port,
            readiness=self._readiness,
            lifecycle=self._lifecycle,
            **kw,
        )

    # -- state -------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._state_lock:
            return self._state

    def _set_state(self, state: str, publish: bool = True) -> None:
        with self._state_lock:
            self._state = state
        if publish:
            try:
                self.registry.publish_state(self.name, state=state)
            except StaleLeaseError:
                # fenced underneath us: the registry write is refused
                # (the zombie rejection) — stop admitting; the router
                # already replayed our streams elsewhere
                with self._state_lock:
                    self._state = "fenced"
                logger.warning(
                    "membership: %s state publish fenced (presumed "
                    "dead); admission stopped", self.name,
                )

    def _readiness(self) -> Tuple[bool, str]:
        state = self.state
        if state != "ready":
            return False, state
        try:
            healthy = bool(self.engine.health().get("healthy"))
        except Exception:
            healthy = False
        return healthy, "ready" if healthy else "unhealthy"

    # -- lifecycle actuator ------------------------------------------------

    def _lifecycle(self, action: str, spec: Dict[str, Any]) -> Dict[str, Any]:
        if action == "drain":
            self._set_state("draining")
            return {"state": self.state}
        if action == "admit":
            # NOTE: the rollback stash survives re-admission — during a
            # rolling swap every member is re-admitted as soon as ITS
            # probe passes, and a LATER member's failure must still be
            # able to roll this one back; only an explicit "commit" (the
            # whole rollout succeeded) drops the stash
            self._set_state("ready")
            return {"state": self.state}
        if action == "commit":
            self._old_params = None  # the rollout committed fleet-wide
            return {"state": self.state, "committed": True}
        if action == "restart":
            self._set_state("probing")
            try:
                self.engine.restart()
            except Exception:
                self._set_state("draining")
                raise
            return {"state": self.state, "restarted": True}
        if action == "swap":
            path = spec.get("checkpoint")
            if not path:
                raise ValueError("swap needs a 'checkpoint' path")
            self._set_state("swapping")
            try:
                params = load_params(str(path))
                old = self.engine.swap_weights(params)
            except Exception:
                self._set_state("draining")
                raise
            if self._old_params is None:
                # first swap of this rollout: stash for rollback (a
                # re-delivered swap keeps the ORIGINAL stash — rolling
                # back twice must not "restore" the bad weights)
                self._old_params = old
            self._set_state("probing")
            return {"state": self.state, "swapped": True}
        if action == "rollback":
            if self._old_params is None:
                raise ValueError("nothing to roll back")
            self.engine.swap_weights(self._old_params)
            self._old_params = None
            self._set_state("probing")
            return {"state": self.state, "rolled_back": True}
        if action == "status":
            ready, state = self._readiness()
            return {
                "state": state, "ready": ready,
                "held_epoch": self.registry.held_epoch(self.name),
            }
        if action == "resign":
            threading.Thread(
                target=self.shutdown, daemon=True,
                name=f"tft-member-shutdown-{self.name}",
            ).start()
            return {"state": "draining", "resigning": True}
        raise ValueError(f"unknown lifecycle action {action!r}")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> Tuple[str, int]:
        """Start the ingress, register the membership lease, and begin
        heartbeating. Returns the bound ``(host, port)``."""
        host, port = self.server.start()
        meta = {
            "url": f"{host}:{port}",
            "pid": os.getpid(),
            "state": "ready",
            "tier": self.tier,
            "eos_id": getattr(self.engine, "eos_id", None),
            "max_seq_len": getattr(self.engine, "max_seq_len", 2048),
        }
        self.registry.on_lost = self._on_lease_lost
        self.registry.register(self.name, meta)
        return host, port

    def _on_lease_lost(self, key, epoch, cur) -> None:
        """The heartbeat sweep found our lease stolen: we were presumed
        dead and fenced. Stop admitting immediately — the router has
        already replayed our streams; anything we emit now lands in
        closed sockets."""
        if key != self.name:
            return
        with self._state_lock:
            self._state = "fenced"
        _flight.record(
            "membership", "lease_lost", member=self.name, epoch=epoch,
            holder=None if cur is None else cur.worker,
        )
        logger.warning(
            "membership: %s lost its lease at epoch %d (fenced by a "
            "router); admission stopped", self.name, epoch,
        )

    def wait_idle(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the engine has no queued or active work (True),
        or the timeout passes (False)."""
        deadline = time.monotonic() + (
            self.drain_timeout_s if timeout_s is None else timeout_s
        )
        while time.monotonic() < deadline:
            h = self.engine.health()
            if not h["queue_depth"] and not h["active_slots"]:
                return True
            time.sleep(0.02)
        return False

    def shutdown(self, timeout_s: Optional[float] = None) -> bool:
        """The graceful exit (SIGTERM / resign): stop admission, let
        in-flight streams finish (up to the drain timeout — leftovers
        fail on engine stop and the router replays them to survivors),
        export a final telemetry snapshot, release the membership and
        any job leases, stop the ingress. Idempotent. Returns whether
        the drain finished cleanly (no streams abandoned)."""
        if self._shutdown_done.is_set():
            return True
        self._set_state("draining")
        clean = self.wait_idle(timeout_s)
        try:
            from ..obs import export as _obs_export

            _obs_export.export_snapshot()
        except Exception:
            logger.warning(
                "membership: %s final telemetry export failed",
                self.name, exc_info=True,
            )
        try:
            self.registry.resign(self.name)
        except Exception:
            logger.warning(
                "membership: %s resign failed", self.name, exc_info=True
            )
        self.registry.stop()
        self._shutdown_done.set()
        try:
            self.server.stop()
        except Exception:
            logger.warning(
                "membership: %s server stop failed", self.name,
                exc_info=True,
            )
        try:
            if self.engine._thread is not None:
                self.engine.stop()
        except Exception:
            pass
        _flight.record(
            "membership", "shutdown", member=self.name, clean=clean,
        )
        return clean

    def install_sigterm(self) -> None:
        """Route SIGTERM to :meth:`shutdown` — the platform's
        drain-before-kill contract. Call from the main thread."""
        import signal as _signal

        def _handler(signum, frame):
            logger.warning(
                "membership: %s received SIGTERM; draining", self.name
            )
            self.shutdown()
            raise SystemExit(0)

        _signal.signal(_signal.SIGTERM, _handler)

    def __enter__(self) -> "MemberAgent":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


# -- router-side membership sync -------------------------------------------


class _MemberSync:
    """The fleet's registry-sync tick hook: reconcile the router's
    replica roster against the shared registry.

    - a fresh lease unknown to the roster joins as a
      :class:`RemoteEngine` replica;
    - an EXPIRED lease is fenced — tombstone in the registry (exactly
      once across routers, via the epoch race) AND
      :meth:`Fleet._fence` locally, so the member's streams replay to
      survivors exactly like an in-process replica death;
    - a tombstone (``fenced``/``resigned``) leaves the roster (fencing
      locally first unless it resigned after a clean drain);
    - metadata ``state`` transitions map to the router's administrative
      gates: ``draining`` → :meth:`Fleet.drain_replica`, back to
      ``ready`` → :meth:`Fleet.admit_replica` (probe-gated)."""

    def __init__(
        self,
        fleet: Fleet,
        registry: MemberRegistry,
        interval_s: float = 0.5,
        engine_factory: Optional[Callable[[str, dict], Any]] = None,
    ):
        self.fleet = fleet
        self.registry = registry
        self.interval_s = float(interval_s)
        self._engine_factory = engine_factory or self._default_engine
        self._last_sync = 0.0
        self._admitting: set = set()

    @staticmethod
    def _default_engine(name: str, meta: dict) -> RemoteEngine:
        eos = meta.get("eos_id")
        return RemoteEngine(
            name,
            str(meta.get("url", "")),
            eos_id=None if eos is None else int(eos),
            max_seq_len=int(meta.get("max_seq_len", 2048) or 2048),
        )

    def __call__(self) -> None:
        now = time.monotonic()
        if now - self._last_sync < self.interval_s:
            return
        self._last_sync = now
        try:
            views = self.registry.members()
        except Exception:
            logger.warning(
                "membership: registry scan failed; roster unchanged",
                exc_info=True,
            )
            return
        fleet = self.fleet
        roster = set(fleet.replica_names)
        seen: set = set()
        live = 0
        for view in views:
            name = view.key
            if name == ROUTER_LEASE_KEY:
                # the router-ELECTION lease (serve/router_ha.py) shares
                # the directory; it is not a member and must never be
                # fenced/joined as one
                continue
            seen.add(name)
            if view.terminal:
                if name in roster:
                    self._leave(name, resigned=view.state == "resigned")
                continue
            if view.expired:
                # presumed dead: fence FIRST in the registry (the
                # epoch race makes this exactly-once across routers),
                # then locally so its streams replay now
                self.registry.fence(name)
                if name in roster:
                    self._leave(name, resigned=False)
                continue
            live += 1
            state = str(view.meta.get("state", "ready"))
            if name not in roster:
                eng = self._engine_factory(name, view.meta)
                try:
                    # placements carry the fleet's election epoch as a
                    # fencing header once router HA activates; reading
                    # it live (not captured) tracks takeover/demotion
                    eng.router_epoch_fn = (
                        lambda: getattr(self.fleet, "router_epoch", None)
                    )
                except Exception:
                    pass  # duck-typed factory engine without the attr
                tier = str(view.meta.get("tier", "mixed") or "mixed")
                try:
                    fleet._add_replica(name, eng, tier=tier)
                except ValueError:
                    # raced another sync pass, or the member advertises
                    # a tier label this router does not know — join it
                    # untiered rather than strand its capacity
                    if name not in fleet.replica_names:
                        try:
                            fleet._add_replica(name, eng)
                        except ValueError:
                            continue
                if state != "ready":
                    fleet.drain_replica(name)
                continue
            try:
                # a member may re-role between heartbeats (operator
                # re-shaping the tiers); apply it like any other
                # metadata transition
                fleet.set_replica_tier(
                    name, str(view.meta.get("tier", "mixed") or "mixed")
                )
            except (KeyError, ValueError):
                pass
            rep_state = fleet.replica_state(name)
            if state == "draining" and rep_state == "active":
                fleet.drain_replica(name)
            elif state == "ready" and rep_state == "draining":
                # the member finished its drain cycle (e.g. SIGTERM
                # canceled, or an external orchestrator re-admitted
                # it): re-admit probe-gated, off the router tick — a
                # probe generation must not stall the failover drain
                if name not in self._admitting:
                    self._admitting.add(name)
                    threading.Thread(
                        target=self._admit_worker, args=(name,),
                        daemon=True,
                    ).start()
        # members the registry no longer lists at all (lease files
        # unlinked by a clean release) leave the roster too
        for name in roster - seen:
            self._leave(name, resigned=True)
        _m_members.set(float(live))

    def _admit_worker(self, name: str) -> None:
        try:
            self.fleet.admit_replica(name, probe=True)
        except Exception:
            logger.warning(
                "membership: re-admission of %s failed", name,
                exc_info=True,
            )
        finally:
            self._admitting.discard(name)

    def _leave(self, name: str, resigned: bool) -> None:
        try:
            rep = self.fleet._replica(name)
        except KeyError:
            return
        if not resigned:
            # death: drain the local relays so their streams hit the
            # failover queue before the replica object disappears
            self.fleet._fence(
                rep,
                EngineUnhealthyError(
                    f"member {name} fenced (lease expired or tombstoned)"
                ),
            )
        self.fleet._remove_replica(name)
        _flight.record(
            "membership", "leave", member=name, resigned=resigned,
        )


def connect_fleet(
    path: str,
    *,
    worker_id: Optional[str] = None,
    ttl_s: Optional[float] = None,
    sync_interval_s: float = 0.5,
    engine_factory: Optional[Callable[[str, dict], Any]] = None,
    **fleet_kwargs,
) -> Fleet:
    """Build a router over the member registry at ``path``: a
    :class:`~.fleet.Fleet` in remote-replica mode whose roster tracks
    the registry — members join as they register, expired heartbeats
    fence them (streams replay to survivors), tombstones leave.

    The returned fleet starts empty (members appear on the first
    watchdog tick after :meth:`~.fleet.Fleet.start`) and carries two
    extra attributes: ``registry`` (the router's
    :class:`MemberRegistry` view) and ``membership_sync`` (the tick
    hook, for tests to drive synchronously). ``auto_restart`` defaults
    OFF in this mode: a dead PROCESS cannot be restarted from here —
    member supervision belongs to the platform; a member that comes
    back re-registers and re-joins."""
    registry = MemberRegistry(
        path, worker_id=worker_id, ttl_s=ttl_s
    )
    fleet_kwargs.setdefault("auto_restart", False)
    fleet = Fleet(engines=[], **fleet_kwargs)
    sync = _MemberSync(
        fleet, registry,
        interval_s=sync_interval_s,
        engine_factory=engine_factory,
    )
    fleet._tick_hooks.append(sync)
    fleet.registry = registry
    fleet.membership_sync = sync
    return fleet


# -- rolling restart / weight swap -----------------------------------------


def _is_remote(engine) -> bool:
    return isinstance(engine, RemoteEngine)


def _drain_member(fleet: Fleet, name: str, drain_timeout_s: float) -> None:
    """Drain one member end to end: admission stops at the member's
    ingress (remote) and at the router, then in-flight streams get
    ``drain_timeout_s`` to finish; leftovers MIGRATE — the replica is
    fenced so its streams replay to survivors recompute-style."""
    rep = fleet._replica(name)
    if _is_remote(rep.engine):
        status, body = rep.engine.lifecycle("drain")
        if status != 200:
            raise RuntimeError(
                f"member {name} refused drain: HTTP {status}: "
                f"{body.get('error')}"
            )
    fleet.drain_replica(name)
    deadline = time.monotonic() + drain_timeout_s
    while time.monotonic() < deadline:
        h = rep.engine.health()
        if not h["queue_depth"] and not h["active_slots"]:
            return
        time.sleep(0.02)
    logger.warning(
        "membership: member %s drain timed out after %.1fs; migrating "
        "its in-flight streams to survivors", name, drain_timeout_s,
    )
    fleet._fence(
        rep,
        EngineUnhealthyError(
            f"member {name} drained past its timeout; streams migrate"
        ),
    )


def _admit_member(fleet: Fleet, name: str, probe: bool) -> bool:
    rep = fleet._replica(name)
    if _is_remote(rep.engine):
        status, body = rep.engine.lifecycle("admit")
        if status != 200:
            logger.warning(
                "membership: member %s refused admit: HTTP %s: %s",
                name, status, body.get("error"),
            )
            return False
        rep.engine.healthy = True
    return fleet.admit_replica(name, probe=probe)


def rolling_restart(
    fleet: Fleet,
    members: Optional[List[str]] = None,
    *,
    drain_timeout_s: float = 30.0,
) -> Dict[str, Any]:
    """Restart the fleet one member at a time with zero downtime: for
    each member, drain (admission stops; in-flight streams finish, or
    migrate to survivors past the timeout), restart the engine, then a
    **probe generation must pass** before re-admission. A member whose
    restart or probe fails halts the rollout (it stays out of
    placement; the rest of the fleet keeps serving) — re-run after
    fixing it. Returns ``{"outcome", "restarted", "failed"}``."""
    names = list(members if members is not None else fleet.replica_names)
    restarted: List[str] = []
    for name in names:
        rep = fleet._replica(name)
        try:
            _drain_member(fleet, name, drain_timeout_s)
            if _is_remote(rep.engine):
                rep.engine.restart()
            else:
                rep.engine.restart()
            ok = _admit_member(fleet, name, probe=True)
        except Exception as e:
            logger.warning(
                "membership: rolling restart halted at %s: %s",
                name, e, exc_info=True,
            )
            ok = False
        if not ok:
            _m_rollouts.inc(outcome="halted")
            _flight.record(
                "membership", "rollout",
                op="restart", outcome="halted", member=name,
            )
            return {
                "outcome": "halted",
                "restarted": restarted,
                "failed": name,
            }
        restarted.append(name)
    _m_rollouts.inc(outcome="ok")
    _flight.record(
        "membership", "rollout", op="restart", outcome="ok",
        members=len(restarted),
    )
    return {"outcome": "ok", "restarted": restarted, "failed": None}


def rolling_weight_swap(
    fleet: Fleet,
    checkpoint: str,
    *,
    drain_timeout_s: float = 30.0,
) -> Dict[str, Any]:
    """Hot-swap a new checkpoint across the fleet with zero downtime,
    one member at a time: drain → ``swap_weights`` (device_put +
    pointer flip; zero recompiles) → **probe generation** → re-admit.
    A probe failure on any member ROLLS BACK — that member and every
    member already swapped return to the old weights (mixed weights
    across replicas would break failover byte-identity) — and the
    rollout halts. Returns ``{"outcome", "swapped", "failed"}``;
    ``fleet.rollouts_total{outcome}`` counts it."""
    names = list(fleet.replica_names)
    swapped: List[str] = []
    stash: Dict[str, Any] = {}

    def swap_one(name: str) -> None:
        rep = fleet._replica(name)
        if _is_remote(rep.engine):
            status, body = rep.engine.lifecycle(
                "swap", checkpoint=str(checkpoint)
            )
            if status != 200:
                raise RuntimeError(
                    f"member {name} refused swap: HTTP {status}: "
                    f"{body.get('error')}"
                )
        else:
            stash[name] = rep.engine.swap_weights(load_params(checkpoint))

    def rollback_one(name: str) -> None:
        rep = fleet._replica(name)
        if _is_remote(rep.engine):
            status, body = rep.engine.lifecycle("rollback")
            if status != 200:
                # a member that cannot PROVE it restored the old weights
                # must stay out of placement — re-admitting it could mix
                # weights across replicas and break failover identity
                raise RuntimeError(
                    f"member {name} rollback failed: HTTP {status}: "
                    f"{body.get('error')}"
                )
        elif name in stash:
            rep.engine.swap_weights(stash.pop(name))

    for name in names:
        try:
            _drain_member(fleet, name, drain_timeout_s)
            swap_one(name)
            ok = fleet.probe_replica(name)
        except Exception as e:
            logger.warning(
                "membership: weight swap failed on %s: %s", name, e,
                exc_info=True,
            )
            ok = False
        if ok:
            ok = _admit_member(fleet, name, probe=False)
        if not ok:
            # roll the WHOLE rollout back: this member first, then
            # every member already carrying the new weights
            logger.warning(
                "membership: weight swap probe failed on %s; rolling "
                "back %d member(s) and halting the rollout",
                name, len(swapped) + 1,
            )
            for victim in [name] + list(reversed(swapped)):
                try:
                    if victim != name:
                        _drain_member(fleet, victim, drain_timeout_s)
                    rollback_one(victim)
                    _admit_member(fleet, victim, probe=True)
                except Exception:
                    logger.warning(
                        "membership: rollback of %s failed; it stays "
                        "out of placement", victim, exc_info=True,
                    )
            _m_rollouts.inc(outcome="rolled_back")
            _flight.record(
                "membership", "rollout",
                op="swap", outcome="rolled_back", member=name,
            )
            return {
                "outcome": "rolled_back",
                "swapped": [],
                "failed": name,
            }
        swapped.append(name)
    # the WHOLE rollout succeeded: tell every member to drop its
    # rollback stash (best-effort — an unreachable member just keeps a
    # harmless pre-rollout stash until its next rollout)
    for name in swapped:
        try:
            rep = fleet._replica(name)
            if _is_remote(rep.engine):
                rep.engine.lifecycle("commit")
            else:
                stash.pop(name, None)
        except Exception:
            logger.warning(
                "membership: commit of %s failed (stash lingers)",
                name, exc_info=True,
            )
    _m_rollouts.inc(outcome="ok")
    _flight.record(
        "membership", "rollout", op="swap", outcome="ok",
        members=len(swapped),
    )
    return {"outcome": "ok", "swapped": swapped, "failed": None}


# -- autoscaling -----------------------------------------------------------


class Autoscaler:
    """Scale decisions from the PR-12 signals, actuation injected.

    Watches three pressure signals — aggregate queue depth, KV pages in
    use (as a fraction of capacity), and the inter-token p99 from the
    time-series store (``serve.inter_token_seconds.p99``) — and calls
    the injected ``scale_up()`` / ``scale_down()`` callbacks (spawn a
    member process / drain one; the platform owns HOW). Guard rails:
    ``min_members``/``max_members`` bounds on the current roster size
    and a ``cooldown_s`` between actions so one burst cannot flap the
    fleet. ``signals_fn`` overrides the signal read for tests.

    Attach to a router with :meth:`attach` (it evaluates on the fleet's
    watchdog tick) or call :meth:`evaluate` from your own loop."""

    def __init__(
        self,
        fleet: Fleet,
        *,
        scale_up: Callable[[], Any],
        scale_down: Callable[[], Any],
        min_members: int = 1,
        max_members: int = 8,
        queue_high: int = 8,
        pages_frac_high: float = 0.85,
        itl_p99_high_s: float = 1.0,
        queue_low: int = 0,
        pages_frac_low: float = 0.25,
        cooldown_s: float = 30.0,
        signals_fn: Optional[Callable[[], Dict[str, float]]] = None,
    ):
        self.fleet = fleet
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.min_members = int(min_members)
        self.max_members = int(max_members)
        self.queue_high = int(queue_high)
        self.pages_frac_high = float(pages_frac_high)
        self.itl_p99_high_s = float(itl_p99_high_s)
        self.queue_low = int(queue_low)
        self.pages_frac_low = float(pages_frac_low)
        self.cooldown_s = float(cooldown_s)
        self._signals_fn = signals_fn
        self._last_action_t: float = -float("inf")
        self.decisions: List[Tuple[float, str, Dict[str, float]]] = []

    def signals(self) -> Dict[str, float]:
        """The current pressure read: fleet aggregates for queue/pages
        (synchronous truth) + the time-series store's inter-token p99
        (windowed; ``0.0`` while no samples exist)."""
        if self._signals_fn is not None:
            return dict(self._signals_fn())
        h = self.fleet.health()
        cap = float(h.get("pages_capacity") or 0)
        p99 = 0.0
        try:
            from ..obs import timeseries as _ts

            pt = _ts.store().latest("serve.inter_token_seconds.p99")
            if pt is not None:
                p99 = float(pt[1])
        except Exception:
            p99 = 0.0
        return {
            "queue_depth": float(h.get("queue_depth") or 0),
            "pages_frac": (
                float(h.get("pages_in_use") or 0) / cap if cap else 0.0
            ),
            "itl_p99_s": p99,
            "members": float(len(self.fleet.replica_names)),
        }

    def evaluate(self, now: Optional[float] = None) -> Optional[str]:
        """One scaling decision: ``"up"``, ``"down"``, or ``None``.
        Scale-up wins ties (pressure beats thrift); both respect the
        member bounds and the cooldown."""
        now = time.monotonic() if now is None else now
        if now - self._last_action_t < self.cooldown_s:
            return None
        s = self.signals()
        n = int(s.get("members", len(self.fleet.replica_names)))
        decision: Optional[str] = None
        if n < self.max_members and (
            s["queue_depth"] > self.queue_high
            or s["pages_frac"] > self.pages_frac_high
            or s["itl_p99_s"] > self.itl_p99_high_s
        ):
            decision = "up"
        elif n > self.min_members and (
            s["queue_depth"] <= self.queue_low
            and s["pages_frac"] < self.pages_frac_low
            and s["itl_p99_s"] < self.itl_p99_high_s / 2.0
        ):
            decision = "down"
        if decision is None:
            return None
        self._last_action_t = now
        self.decisions.append((now, decision, s))
        _m_scale_decisions.inc(direction=decision)
        _flight.record(
            "membership", "scale", direction=decision, **{
                k: round(v, 4) for k, v in s.items()
            },
        )
        logger.warning(
            "membership: autoscaler decided %s (queue=%.0f "
            "pages_frac=%.2f itl_p99=%.3fs members=%d)",
            decision, s["queue_depth"], s["pages_frac"],
            s["itl_p99_s"], n,
        )
        try:
            (self.scale_up if decision == "up" else self.scale_down)()
        except Exception:
            logger.warning(
                "membership: scale_%s callback failed", decision,
                exc_info=True,
            )
        return decision

    def attach(self, interval_s: float = 1.0) -> "Autoscaler":
        """Evaluate on the fleet's watchdog tick, rate-limited to
        ``interval_s``."""
        state = {"t": 0.0}

        def tick() -> None:
            now = time.monotonic()
            if now - state["t"] < interval_s:
                return
            state["t"] = now
            self.evaluate(now)

        self.fleet._tick_hooks.append(tick)
        return self


class LocalProcessProvisioner:
    """A REAL actuator behind :class:`Autoscaler`'s ``scale_up`` /
    ``scale_down`` callbacks: spawn and retire :class:`MemberAgent`
    subprocesses on this host (the single-host closing of ROADMAP item
    3's "real provisioner" remainder; a cloud provisioner swaps in the
    same two callbacks).

    ``script`` is the member's ``python -c`` source; it is launched as
    ``python -c <script> <registry_path> <member_name> [*extra_args]``
    and is expected to build an engine, construct a
    :class:`MemberAgent` on the shared ``path``, call
    :meth:`MemberAgent.install_sigterm`, start, and serve until
    signaled — retirement is a SIGTERM, so the member drains
    gracefully (stop admission, finish in-flight streams, resign the
    lease) rather than being fenced as a death.

    Bounded by ``max_procs`` (scale-up past it is a logged no-op —
    the autoscaler's own ``max_members``/``cooldown_s`` guard rails
    stay in charge of WHEN); scale-down only ever retires processes
    THIS provisioner spawned, newest first, so externally-managed
    members are untouchable from here."""

    def __init__(
        self,
        path: str,
        script: str,
        *,
        python: Optional[str] = None,
        base_name: str = "auto",
        max_procs: int = 8,
        extra_args: Tuple[str, ...] = (),
        env: Optional[Dict[str, str]] = None,
        term_grace_s: float = 10.0,
    ):
        self.path = str(path)
        self.script = script
        self.python = python or sys.executable
        self.base_name = str(base_name)
        self.max_procs = int(max_procs)
        self.extra_args = tuple(str(a) for a in extra_args)
        self.env = dict(env) if env is not None else None
        self.term_grace_s = float(term_grace_s)
        self._procs: "Dict[str, subprocess.Popen]" = {}
        self._order: List[str] = []  # spawn order; retire newest first
        self._seq = 0
        self._lock = threading.Lock()

    def reap(self) -> List[str]:
        """Forget exited processes; returns the names reaped."""
        gone = []
        with self._lock:
            for name, proc in list(self._procs.items()):
                if proc.poll() is not None:
                    gone.append(name)
                    del self._procs[name]
                    self._order.remove(name)
        return gone

    @property
    def alive(self) -> int:
        self.reap()
        with self._lock:
            return len(self._procs)

    def names(self) -> List[str]:
        self.reap()
        with self._lock:
            return list(self._order)

    def scale_up(self) -> Optional[str]:
        """Spawn one member subprocess; returns its name, or ``None``
        at the ``max_procs`` bound."""
        self.reap()
        with self._lock:
            if len(self._procs) >= self.max_procs:
                logger.warning(
                    "provisioner: scale_up refused at the max_procs "
                    "bound (%d)", self.max_procs,
                )
                return None
            self._seq += 1
            name = f"{self.base_name}-{self._seq}"
        env = None
        if self.env is not None:
            env = dict(os.environ)
            env.update(self.env)
        proc = subprocess.Popen(
            [self.python, "-c", self.script, self.path, name,
             *self.extra_args],
            env=env,
        )
        with self._lock:
            self._procs[name] = proc
            self._order.append(name)
        _flight.record(
            "membership", "provision", member=name, pid=proc.pid,
        )
        logger.warning(
            "provisioner: spawned member %s (pid %d)", name, proc.pid,
        )
        return name

    def scale_down(self) -> Optional[str]:
        """SIGTERM the newest member this provisioner owns (graceful
        drain + resign via :meth:`MemberAgent.install_sigterm`);
        returns its name, or ``None`` with nothing to retire."""
        self.reap()
        with self._lock:
            if not self._order:
                return None
            name = self._order[-1]
            proc = self._procs[name]
        try:
            proc.send_signal(signal.SIGTERM)
        except OSError:
            pass  # exited under us; the next reap forgets it
        _flight.record("membership", "retire", member=name, pid=proc.pid)
        logger.warning(
            "provisioner: retiring member %s (pid %d, SIGTERM)",
            name, proc.pid,
        )
        return name

    def autoscaler(self, fleet: Fleet, **kw: Any) -> Autoscaler:
        """Convenience: an :class:`Autoscaler` with this provisioner's
        callbacks bound (``max_members`` defaults to ``max_procs``)."""
        kw.setdefault("max_members", self.max_procs)
        return Autoscaler(
            fleet, scale_up=self.scale_up, scale_down=self.scale_down,
            **kw,
        )

    def stop(self) -> None:
        """Retire everything: SIGTERM all, wait out the grace period,
        SIGKILL leftovers."""
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.term_grace_s
        for proc in procs:
            rem = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(0.0, rem))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        self.reap()
