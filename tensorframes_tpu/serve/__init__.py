"""Serving-side generation: continuous batching over a paged KV cache.

The subsystem that turns the single-shot decode path
(:func:`~tensorframes_tpu.models.transformer_generate`) into a service:
requests with independent arrival times and lengths share one decode
batch and one static page pool, with at most three compiled step
programs for the whole lifetime (prefill + decode, plus the
prefill-chunk program when chunked prefill / prefix-cache resume is in
play) — five with speculative decoding on (a draft model proposes k
tokens per step from its own KV page group; one batched
``[max_slots, k + 1]`` verify program accepts by exact match against
the target's own sampled tokens, so streams stay byte-identical to
non-speculative decode). See ``docs/serving_llm.md``.

- :mod:`.kv_pages` — the paged KV cache (static pool + page tables,
  refcounted pages + the shared-prefix :class:`PrefixCache`)
- :mod:`.scheduler` — bounded admission, slots, preempt-and-requeue
- :mod:`.engine` — the compiled prefill/decode steps + streaming API
- :mod:`.fleet` — N engine replicas behind a health-gated router with
  least-loaded/session-affinity placement, fencing + background
  restart, and request replay on replica death
- :mod:`.membership` — the multi-host tier: lease-based membership in
  a shared directory, remote replicas over HTTP, host-death fencing,
  rolling restarts / hot weight swaps, autoscaling hooks
"""

from .engine import EngineUnhealthyError, GenerationEngine
from .fleet import Fleet, FleetHandle
from .membership import (
    Autoscaler,
    MemberAgent,
    MemberRegistry,
    RemoteEngine,
    connect_fleet,
    load_params,
    rolling_restart,
    rolling_weight_swap,
    save_params,
)
from .kv_pages import (
    PageGroup,
    PagePool,
    PrefixCache,
    SequencePages,
    pages_needed,
)
from .scheduler import GenerationHandle, GenRequest, QueueFullError, Scheduler

__all__ = [
    "Autoscaler",
    "EngineUnhealthyError",
    "Fleet",
    "FleetHandle",
    "GenerationEngine",
    "GenerationHandle",
    "GenRequest",
    "MemberAgent",
    "MemberRegistry",
    "PageGroup",
    "PagePool",
    "PrefixCache",
    "QueueFullError",
    "RemoteEngine",
    "Scheduler",
    "SequencePages",
    "connect_fleet",
    "load_params",
    "pages_needed",
    "rolling_restart",
    "rolling_weight_swap",
    "save_params",
]
