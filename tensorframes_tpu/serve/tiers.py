"""Live KV-page migration between serving replicas — the tier primitive.

Prompt ingestion (prefill) and token generation (decode) load move on
different curves, but a monolithic fleet makes every replica do both, so
a prefill burst stalls every concurrent stream's inter-token latency and
idle decode capacity cannot absorb it. This module is the primitive that
decouples them: one slot's COMPLETE decode state — request fields,
generated tokens, and the physical KV page rows (target model AND every
attached page group, e.g. the speculative draft's) — is serialized to
host memory (:func:`export_slot`), shipped through the streaming
transfer layer (``frame/transfer.py``: chunked, retried,
chaos-injectable at ``frame.h2d`` / ``frame.d2h``), and re-materialized
into a free slot on another replica (:func:`restore_slot`), where
generation continues **byte-identically**.

Why byte-identity holds: at a step boundary a slot's KV is valid for
positions ``[0, length - 2]`` and the newest generated token's KV write
is pending (the next decode writes it at ``length - 1``). The page
bytes plus ``prompt`` / ``generated`` / the sampling params therefore
fully determine the continuation — per-step sampling keys fold at
ABSOLUTE positions (``engine._sample_slot_tokens``), so greedy and
seeded streams alike continue exactly where they left off. Speculative
decoding keeps the property for free (exact-match acceptance never
changes emitted bytes; a draft group that cannot be restored just
resets ``draft_pos`` and re-ingests, degrading proposals, never
tokens). Heterogeneous tensor-parallel degrees work because pages are
exported at LOGICAL geometry — ``d2h`` gathers a sharded pool array
whole, and the import re-pins rows under the destination pool's own
KV-head sharding via ``place()``.

Two consumers (``serve/fleet.py``):

- **tier handoff** — a request prefills on a prefill-tier replica and
  migrates to a decode-tier replica at first token, so prefill bursts
  and decode streams stop contending for the same step loop;
- **decode rebalancing** — under pool pressure the scheduler offers its
  chosen preemption victim to ``Scheduler.on_pressure`` first: the
  fleet exports the victim's pages (freeing them synchronously, which
  is all ``grow`` needed) and re-imports them on the least-loaded
  decode replica, so the victim keeps its KV instead of paying a
  recompute-style preemption. Preemption stays the fallback — a failed
  import parks the record on the ordinary failover/replay path.

Chaos: ``tier.handoff`` fires inside both the export read and the
import write retry windows (reads are side-effect free; the write is
idempotent — re-setting the same rows), so a ``transient`` retries
invisibly and a ``fatal`` aborts the migration into the fallback
ladder. See docs/serving_llm.md "Disaggregated tiers".
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..frame.transfer import d2h as _d2h, h2d as _h2d, wire_dtype as _wire
from ..obs import span as _span
from ..obs.metrics import counter as _counter, histogram as _histogram
from ..utils import chaos as _chaos
from ..utils.failures import run_with_retries
from ..utils.logging import get_logger
from .kv_pages import SequencePages
from .scheduler import GenerationHandle, GenRequest, QueueFullError, _Active

__all__ = [
    "SlotSnapshot",
    "TIERS",
    "TierMigrationError",
    "export_slot",
    "restore_slot",
]

logger = get_logger("serve.tiers")

#: replica roles (``Fleet(tiers=...)`` / ``MemberAgent(tier=...)``):
#: ``prefill`` takes new requests and hands off at first token;
#: ``decode`` takes migrated streams (and new requests only when no
#: prefill capacity is healthy); ``mixed`` (the default) does both —
#: a fleet whose replicas are all ``mixed`` routes exactly like the
#: pre-tier router
TIERS = ("prefill", "decode", "mixed")

_m_migrations = _counter(
    "serve.kv_migrations_total",
    "Completed KV-page slot migrations by reason (handoff = prefill->"
    "decode tier transfer, rebalance = pool-pressure move, failed = "
    "aborted migrations that fell back to replay/preemption)",
    labels=("reason",),
)
_m_migration_s = _histogram(
    "serve.migration_seconds",
    "End-to-end wall of one slot migration: export (page d2h + detach) "
    "through restore (alloc + page write + slot attach)",
)


class TierMigrationError(RuntimeError):
    """A slot cannot migrate to this destination (geometry mismatch,
    unhealthy engine, infeasible length). Deliberately NOT transient:
    the caller falls back to replay or preemption, never retries the
    same doomed pairing."""


@dataclasses.dataclass
class SlotSnapshot:
    """One slot's complete migratable state, all host-side.

    ``k`` / ``v`` are ``[n_layers, n_pages, page_size, n_kv_heads,
    head_dim]`` rows gathered from the source pool in page-list order
    (logical geometry — TP shards are merged by the export gather);
    ``groups`` maps each page-group name (e.g. ``"draft"``) to its own
    ``(k, v)`` row pair. Request fields are carried verbatim so the
    destination's :class:`~.scheduler.GenRequest` continues the same
    deadline / seed / budget arithmetic."""

    request_id: int
    prompt: np.ndarray
    generated: List[int]
    emitted: int
    max_new_tokens: int
    temperature: float
    top_p: float
    seed: int
    eos_id: Optional[int]
    tenant: str
    priority: int
    deadline_t: Optional[float]
    submitted_at: float
    trace: Optional[object]
    page_size: int
    k: np.ndarray
    v: np.ndarray
    groups: Dict[str, Tuple[np.ndarray, np.ndarray]]
    draft_pos: int
    reason: str
    source: str
    started_t: float

    @property
    def n_pages(self) -> int:
        return int(self.k.shape[1])

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        for gk, gv in self.groups.values():
            n += gk.nbytes + gv.nbytes
        return n


def _find_slot(engine, request_id: int):
    for idx, act in enumerate(engine.scheduler.slots):
        if act is not None and act.req.request_id == request_id:
            return idx, act
    return None, None


def export_slot(engine, request_id: int, reason: str = "handoff"):
    """Serialize and DETACH one decode-phase slot from ``engine``.

    Under the engine's step lock (re-entrant, so the scheduler's
    ``on_pressure`` hook may call this from inside ``grow``): gather
    the slot's page rows to host through the transfer layer, then
    release the slot WITHOUT closing its handle — the pages return to
    the source pool immediately and the stream continues wherever the
    snapshot is restored. Returns ``None`` when the request is not in
    a migratable state (unknown id, still prefilling, pending
    copy-on-write clone) — the caller falls back to its ordinary
    ladder. Raises only on a non-transient transfer failure."""
    with engine._step_lock:
        idx, act = _find_slot(engine, request_id)
        if act is None:
            return None
        if not act.generated or act.cow_src is not None:
            # mid-prefill (chunked) or pre-clone: the cheap recompute
            # path (replay/preempt) beats moving half-built state
            return None
        t0 = time.monotonic()
        pool = engine.pool
        rows = np.asarray(act.seq.pages, np.int32)

        def fetch():
            _chaos.site("tier.handoff")
            payload = {
                "": (
                    _d2h(pool.k[:, rows], what="tier.kv"),
                    _d2h(pool.v[:, rows], what="tier.kv"),
                ),
            }
            for name, g in pool.groups.items():
                payload[name] = (
                    _d2h(g.k[:, rows], what=f"tier.kv.{name}"),
                    _d2h(g.v[:, rows], what=f"tier.kv.{name}"),
                )
            return payload

        with _span(
            "tier.export",
            request=int(request_id),
            pages=int(rows.size),
            reason=reason,
        ):
            payload = run_with_retries(fetch, what="tier.handoff")
        k, v = payload.pop("")
        req = act.req
        snap = SlotSnapshot(
            request_id=req.request_id,
            prompt=req.prompt,
            generated=list(act.generated),
            emitted=req.emitted,
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature,
            top_p=req.top_p,
            seed=req.seed,
            eos_id=req.eos_id,
            tenant=req.tenant,
            priority=req.priority,
            deadline_t=req.deadline_t,
            submitted_at=req.submitted_at,
            trace=req.trace,
            page_size=engine.page_size,
            k=k,
            v=v,
            groups=payload,
            draft_pos=act.draft_pos,
            reason=reason,
            source=engine.name,
            started_t=t0,
        )
        # pages back to the pool only AFTER the bytes are on the host;
        # the handle stays open — the restore side keeps streaming it
        engine.scheduler.detach(idx)
        return snap


def _check_compat(engine, snap: SlotSnapshot) -> None:
    pool = engine.pool
    if snap.page_size != engine.page_size:
        raise TierMigrationError(
            f"page_size mismatch: snapshot {snap.page_size} vs "
            f"engine {engine.name} {engine.page_size} — page rows are "
            f"position-layout-bound and cannot be re-tiled"
        )
    want = (
        pool.n_layers, snap.n_pages, pool.page_size,
        pool.n_kv_heads, pool.head_dim,
    )
    if tuple(snap.k.shape) != want or snap.k.dtype != pool.k.dtype:
        raise TierMigrationError(
            f"KV geometry mismatch: snapshot rows "
            f"{tuple(snap.k.shape)}/{snap.k.dtype} vs engine "
            f"{engine.name} {want}/{np.dtype(pool.k.dtype)}"
        )
    total = len(snap.prompt) + snap.max_new_tokens
    if total > engine.max_seq_len:
        raise TierMigrationError(
            f"request needs {total} positions at full length but engine "
            f"{engine.name} caps sequences at {engine.max_seq_len}"
        )


def _write_rows(holder, rows: np.ndarray, k_host, v_host) -> None:
    """Scatter host page rows into ``holder`` (the pool or one group)
    at indices ``rows`` — the eager ``_apply_cow`` idiom: plain device
    indexing re-pinned by ``place()``, zero step programs. The upload
    rides ``h2d`` (chunked/retried/counted) when the holder is
    unsharded and no wire cast is configured; sharded pools and active
    wire casts take the raw-host operand path instead, so the scatter
    itself re-shards under the holder's own placement and the bytes
    are never rounded."""
    use_h2d = (
        holder.sharding is None
        and _wire(k_host.dtype) == np.dtype(k_host.dtype)
    )
    k_src = _h2d(k_host, what="tier.kv") if use_h2d else k_host
    v_src = _h2d(v_host, what="tier.kv") if use_h2d else v_host
    holder.k = holder.place(holder.k.at[:, rows].set(k_src))
    holder.v = holder.place(holder.v.at[:, rows].set(v_src))


def restore_slot(engine, snap: SlotSnapshot, _handle_factory=None):
    """Re-materialize an exported slot on ``engine``; returns the new
    slot's :class:`~.scheduler.GenerationHandle` (or the relay handle
    ``_handle_factory`` builds — the fleet's stream-continuity hook,
    same contract as ``GenerationEngine.submit``).

    Raises :class:`TierMigrationError` on geometry/feasibility
    mismatch, :class:`~.scheduler.QueueFullError` when no slot is
    free, and :class:`~...utils.failures.PagePoolExhausted` when the
    pool cannot grant the page set — all three leave the engine
    untouched so the caller's fallback ladder (replay, preemption)
    still owns the request."""
    if not engine.healthy or engine._stop_wedged:
        raise TierMigrationError(
            f"engine {engine.name} is unhealthy; not importing a live slot"
        )
    with engine._step_lock:
        _check_compat(engine, snap)
        sched = engine.scheduler
        idx = next(
            (i for i, s in enumerate(sched.slots) if s is None), None
        )
        if idx is None:
            raise QueueFullError(
                f"engine {engine.name} has no free decode slot for a "
                f"migrated stream ({engine.max_slots} active)"
            )
        pool = engine.pool
        pages = pool.alloc(snap.n_pages)  # all-or-nothing
        rows = np.asarray(pages, np.int32)
        restored_groups: set = set()
        try:

            def write():
                _chaos.site("tier.handoff")
                _write_rows(pool, rows, snap.k, snap.v)
                for name, (gk, gv) in snap.groups.items():
                    g = pool.groups.get(name)
                    if g is None:
                        continue  # destination runs without this group
                    if (
                        tuple(gk.shape) != tuple(g.k[:, rows].shape)
                        or gk.dtype != g.k.dtype
                    ):
                        # e.g. a different draft model: leave the rows
                        # zeroed; draft_pos resets below and the draft
                        # re-ingests (proposals degrade, bytes do not)
                        continue
                    _write_rows(g, rows, gk, gv)
                    restored_groups.add(name)

            with _span(
                "tier.restore",
                request=int(snap.request_id),
                pages=int(rows.size),
                reason=snap.reason,
            ):
                run_with_retries(write, what="tier.handoff")
        except BaseException:
            pool.free(pages)
            raise
        with engine._submit_lock:
            engine._req_counter += 1
            rid = engine._req_counter
        handle = (
            GenerationHandle if _handle_factory is None else _handle_factory
        )(rid)
        req = GenRequest(
            request_id=rid,
            prompt=snap.prompt,
            max_new_tokens=snap.max_new_tokens,
            temperature=snap.temperature,
            top_p=snap.top_p,
            seed=snap.seed,
            eos_id=snap.eos_id,
            handle=handle,
            submitted_at=snap.submitted_at,
            emitted=snap.emitted,
            deadline_t=snap.deadline_t,
            trace=snap.trace,
            tenant=snap.tenant,
            priority=snap.priority,
        )
        seq = SequencePages(pool)
        seq.pages = pages
        act = _Active(req, seq, sched._admit_counter)
        sched._admit_counter += 1
        act.generated = list(snap.generated)
        # prefill is DONE by construction (export requires a generated
        # token); the slot joins the decode batch next step
        act.prefill_pos = len(snap.prompt)
        act.cached_tokens = 0
        act.cow_src = None
        # draft KV travelled with the pages iff the destination holds a
        # geometry-identical group; otherwise the draft re-ingests from
        # scratch — the bounded-stall catch-up discipline
        act.draft_pos = (
            snap.draft_pos if "draft" in restored_groups else 0
        )
        act.spec_k = -1  # re-seed from the destination's static k
        sched.slots[idx] = act
        _m_migrations.inc(reason=snap.reason)
        _m_migration_s.observe(time.monotonic() - snap.started_t)
        logger.info(
            "migrated request %s: %s -> %s (%d pages, %d tokens in, "
            "reason=%s)",
            snap.request_id, snap.source, engine.name, len(pages),
            len(snap.generated), snap.reason,
        )
        return handle
