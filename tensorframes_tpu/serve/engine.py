"""GenerationEngine: compiled prefill/decode steps over the paged cache.

The serving counterpart of
:func:`~tensorframes_tpu.models.transformer_generate`: where that
function compiles one scan program per (batch shape, decode structure),
this engine compiles at most THREE programs for a whole serving
lifetime —

- **prefill** ``[1, max_seq_len]``: one right-padded prompt through the
  batched causal pass (:func:`~tensorframes_tpu.models.transformer_prefill`),
  its per-layer k/v scattered into the sequence's pages, the first token
  sampled from the last real position's logits;
- **decode** ``[max_slots]``: one token per occupied slot through the
  shared per-token step (:func:`~tensorframes_tpu.models.transformer_step`)
  with attention delegated to the paged read —
  :func:`~tensorframes_tpu.ops.paged_attention` (gather reference) or
  :func:`~tensorframes_tpu.ops.ragged_paged_attention` (the fused
  Pallas kernel, ``attention_impl="fused"``);
- **prefill-chunk** ``[1, chunk]`` (dispatched only when chunked
  prefill or a shared-prefix cache hit needs it): one mid-prompt span
  through :func:`~tensorframes_tpu.models.transformer_prefill_chunk`,
  attending to the pages already written — long prompts prefill one
  chunk per step, interleaved with decode, and prefix-cache hits resume
  after the cached span;
- with SPECULATIVE DECODING on (``draft_params=``), two more — a
  **draft** program proposing up to k tokens per slot from the draft
  model's own KV page group, and a **verify** ``[max_slots, k + 1]``
  program (the mid-sequence sibling of the prefill chunk,
  :func:`~tensorframes_tpu.models.transformer_verify_chunk`) scoring
  every proposal against the target's paged KV in one dispatch, with
  exact-match acceptance keeping streams byte-identical to solo decode
  (the plain decode program stops dispatching; the budget becomes
  <= 5). See docs/serving_llm.md "Speculative decoding".

Every input shape is static (page tables are fixed-width, idle slots
point at the trash page), so slot turnover, ragged lengths, and
greedy/sampled mixes all reuse the same two executables — the
no-recompile property the ROADMAP's heavy-traffic target needs. Sampling
parameters (temperature / seed / top_p) are per-request TRACED inputs;
``top_k`` is engine-level static structure, as in ``generate``.

Requests stream through :class:`~.scheduler.Scheduler` (bounded
admission, continuous batching, preempt-and-requeue on page-pool
exhaustion); each :meth:`submit` returns a
:class:`~.scheduler.GenerationHandle` whose iterator yields tokens as
steps complete. Observability: queue depth / batch occupancy /
pages-in-use gauges, time-to-first-token and inter-token latency
histograms, all on the PR-1 registry (``docs/observability.md``).

**Supervision** (``docs/fault_tolerance.md``): step failures are
classified against the ``utils/failures.py`` taxonomy — transient
dispatch errors retry with bounded backoff inside the step, device OOM
recovers by ``defragment()`` + preempt-youngest (recompute-style, so
streams never replay or lose tokens), and anything fatal fails every
in-flight handle promptly with the real error and marks the engine
unhealthy (``submit`` sheds with :class:`EngineUnhealthyError`;
``GET /healthz`` reports it). :meth:`restart` rebuilds device state
from host-side scheduler progress — emitted bytes stay identical and
no step program recompiles. Per-request deadlines
(``submit(deadline=...)``) are swept every step; expired requests fail
with :class:`~tensorframes_tpu.utils.failures.DeadlineExceededError`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.transformer import (
    _kv_heads,
    filter_logits,
    transformer_prefill,
    transformer_prefill_chunk,
    transformer_step,
    transformer_verify_chunk,
)
from ..obs import (
    current_trace as _current_trace,
    flight as _flight,
    programs as _programs,
    requests as _obs_requests,
    span as _span,
    use_trace as _use_trace,
)
from ..obs.metrics import (
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
)
from ..utils import chaos as _chaos
from ..utils.config import get_config
from ..utils.failures import (
    DeadlineExceededError,
    PagePoolExhausted,
    TenantThrottledError,
    first_line as _first_line,
    is_oom,
    is_transient,
    run_with_retries,
)
from ..utils.logging import get_logger
from . import tenancy as _tenancy
from .kv_pages import PagePool, PrefixCache, pages_needed
from .scheduler import (
    GenerationHandle,
    GenRequest,
    QueueFullError,
    Scheduler,
    _Active,
)

__all__ = ["EngineUnhealthyError", "GenerationEngine"]

logger = get_logger("serve.engine")

_m_queue_depth = _gauge(
    "serve.queue_depth", "Generation requests waiting for a decode slot"
)
_m_active_slots = _gauge(
    "serve.active_slots",
    "Decode-batch occupancy (sequences currently holding a slot)",
)
_m_pages_in_use = _gauge(
    "serve.pages_in_use", "KV pages currently owned by live sequences"
)
_m_pages_capacity = _gauge(
    "serve.pages_capacity", "Total KV pages in the pool"
)
_m_ttft = _histogram(
    "serve.ttft_seconds",
    "Time to first token: submit to first emission (seconds)",
)
_m_itl = _histogram(
    "serve.inter_token_seconds",
    "Inter-token latency per stream: gap between emissions (seconds)",
)
_m_tokens = _counter(
    "serve.tokens_total", "Tokens emitted across all generation streams"
)
_m_requests = _counter(
    "serve.requests_total",
    "Generation requests by terminal status",
    labels=("status",),
)
_m_restarts = _counter(
    "serve.engine_restarts_total",
    "GenerationEngine.restart() recoveries (device state rebuilt from "
    "host-side scheduler progress)",
)
_m_deadline_expired = _counter(
    "serve.deadline_expired_total",
    "Requests evicted because their deadline passed (queued or "
    "mid-generation)",
)
_m_handles_failed = _counter(
    "serve.handles_failed_total",
    "Generation handles closed with an error, by classified reason",
    labels=("reason",),
)
_m_prefix_lookups = _counter(
    "serve.prefix_cache_lookups_total",
    "Admissions that consulted the shared-prefix KV cache",
)
_m_prefix_hits = _counter(
    "serve.prefix_cache_hits_total",
    "Admissions whose prompt prefix was served from cached KV pages "
    "(the prefill skipped the shared span)",
)
_m_prefix_tokens_saved = _counter(
    "serve.prefix_cache_tokens_saved_total",
    "Prompt positions whose prefill was skipped via cached KV pages",
)
_m_pages_shared = _gauge(
    "serve.kv_pages_shared",
    "KV pages currently named by more than one reference (prefix-cache "
    "dedup across sequences)",
)
_m_prefill_chunks = _counter(
    "serve.prefill_chunks_total",
    "Prefill chunks dispatched (chunked prefill and prefix-cache "
    "resume both count)",
)
_m_tp_degree = _gauge(
    "serve.tp_degree",
    "Tensor-parallel degree of the engine's step programs (chips per "
    "replica; 1 = solo single-chip serving), per engine",
    labels=("engine",),
)
_m_spec_proposed = _counter(
    "serve.spec_proposed_total",
    "Speculative draft tokens proposed to the verify pass "
    "(docs/serving_llm.md 'Speculative decoding')",
)
_m_spec_accepted = _counter(
    "serve.spec_accepted_total",
    "Speculative draft tokens accepted by exact match against the "
    "target's own sampled token (the byte-identity contract)",
)
_m_spec_accept_rate = _gauge(
    "serve.spec_acceptance_rate",
    "Cumulative speculative acceptance per engine: accepted / proposed "
    "draft tokens (the draft-length controller's signal; absent until "
    "the first proposal). Labeled like serve.tp_degree — fleets run "
    "several speculative engines in one process, and an unlabeled "
    "gauge would flap between replicas last-writer-wins",
    labels=("engine",),
)
_m_verify_s = _histogram(
    "serve.verify_seconds",
    "Wall seconds per batched multi-token verify dispatch (the "
    "[max_slots, k+1] step program)",
)
_m_collective_s = _counter(
    "serve.collective_seconds",
    "ESTIMATED wall seconds spent in cross-chip collectives by the "
    "tensor-parallel step programs (per-step estimate from a one-time "
    "micro-measurement of the step's gather pattern at engine init — "
    "the real gathers overlap compute inside the compiled step)",
)


_engine_seq_lock = threading.Lock()
_engine_seq = 0


def _next_engine_seq() -> int:
    global _engine_seq
    with _engine_seq_lock:
        _engine_seq += 1
        return _engine_seq


class EngineUnhealthyError(RuntimeError):
    """The engine is shedding load: a terminal stepping failure (or a
    wedged stop) marked it unhealthy, and submissions fail fast until
    :meth:`GenerationEngine.restart`. The HTTP endpoint maps this to
    503 + ``Retry-After`` (``interop/serving.py``)."""


def _fail_reason(e: BaseException) -> str:
    """Bounded reason label for ``serve.handles_failed_total``."""
    if isinstance(e, DeadlineExceededError):
        return "deadline"
    if is_oom(e):
        return "oom"
    if is_transient(e):
        return "transient_exhausted"
    return "fatal"


def _span_attend(state, ptabs, pos, pos_c, counts, ps, trash, mp,
                 max_len):
    """The shared ``[S, C]`` paged scatter+read attend of the
    speculative programs — the verify step and the draft's phase-1
    chunk use this ONE builder (the TP verify keeps its own body: head
    slicing and the context gather differ materially): scatter the
    whole span's k/v (positions past ``counts`` or the sequence bound
    land in the trash page), then read each position's visible history
    through the page table under the chunk family's mask. One
    implementation so the mask/scatter the byte-identity contract
    rides on cannot drift between the two programs. ``state`` is the
    caller's two-element ``[k_pool, v_pool]`` list, threaded through
    layer by layer."""
    import jax
    import jax.numpy as jnp

    from ..ops.attention import _NEG_BIG

    slots, c = pos.shape
    offs = jnp.arange(c)

    def attend(li, q, k, v):
        valid = (offs[None, :] < counts[:, None]) & (pos < max_len)
        page = jnp.where(
            valid,
            jnp.take_along_axis(ptabs, pos_c // ps, axis=1),
            trash,
        )
        off = pos_c % ps
        state[0] = state[0].at[li, page, off].set(k)
        state[1] = state[1].at[li, page, off].set(v)
        n_kv, hd = k.shape[2], k.shape[3]
        t = mp * ps
        kg = state[0][li][ptabs].reshape(slots, t, n_kv, hd)
        vg = state[1][li][ptabs].reshape(slots, t, n_kv, hd)
        scale = 1.0 / float(np.sqrt(hd))
        s = jnp.einsum("sckgd,stkd->sckgt", q, kg) * scale
        visible = jnp.arange(t)[None, None, :] <= pos_c[:, :, None]
        s = jnp.where(visible[:, :, None, None, :], s, _NEG_BIG)
        att = jnp.einsum(
            "sckgt,stkd->sckgd", jax.nn.softmax(s, axis=-1), vg
        )
        return att.reshape(slots, c, n_kv * q.shape[3] * hd)

    return attend


def _sample_slot_tokens(logits, positions, temps, seeds, top_ps, top_k):
    """THE per-row token rule, shared by the speculative draft and
    verify programs: greedy argmax, or seeded categorical after
    temperature + top-k/top-p filtering with the per-step key folded at
    the row's ABSOLUTE position — line-for-line the decode program's
    sampling (:meth:`GenerationEngine._decode_impl`), which is the
    byte-identity contract: a verify row at position ``p`` draws
    exactly the token solo decode would draw at ``p``. Traced inside
    the compiled steps. ``logits`` [N, V]; everything else [N]."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1)
    keys = jax.vmap(
        lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t)
    )(seeds, positions)
    scaled = logits / jnp.maximum(temps[:, None], 1e-6)
    filt = filter_logits(scaled, top_k=top_k, top_p=top_ps[:, None])
    sampled = jax.vmap(jax.random.categorical)(keys, filt)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


class GenerationEngine:
    """Continuous-batching generation over a :class:`PagePool`.

    >>> eng = GenerationEngine(lm, max_slots=8, page_size=16)
    >>> h = eng.submit(prompt_ids, max_new_tokens=64)
    >>> eng.start()              # background stepping (or drive .step())
    >>> for tok in h: ...        # stream
    >>> eng.stop()

    ``model`` is a :class:`~tensorframes_tpu.models.TransformerLM` or its
    params dict. ``max_seq_len`` bounds prompt + generation per request
    (default: the model's positional table). ``num_pages`` defaults to
    full-length pages for every slot (no preemption pressure); size it
    SMALLER to oversubscribe memory and lean on preempt-and-requeue.
    ``top_k`` is engine-static; temperature / ``top_p`` / seed are
    per-request.

    Perf knobs (``None`` falls back to the matching ``Config`` field;
    docs/serving_llm.md):

    - ``page_size``: KV page granularity. Default (``None``) is the
      measured-best mapping ``ops.paged_page_size_hint`` (one page IS
      the fused read's key tile) clamped to ``max_seq_len``, or the
      autotuner's ``serve.page_size`` winner when one is stored
      (docs/tuning.md); an explicit argument always wins, and
      ``/healthz`` reports the chosen size;

    - ``attention_impl``: ``"gather"`` (reference read,
      ``ops.paged_attention``) or ``"fused"`` (the ragged
      paged-attention Pallas kernel — decode bandwidth scales with live
      tokens in a ragged batch);
    - ``prefill_chunk_tokens``: > 0 prefills prompts longer than this in
      chunks of this size, one per step, interleaved with decode — a
      long prompt no longer stalls the whole batch for its full prefill;
    - ``prefix_cache``: share identical page-aligned prompt prefixes
      (system prompts, few-shot templates) as refcounted KV pages with
      copy-on-write on in-page divergence; repeat prefixes skip their
      prefill entirely;
    - ``draft_params``: a small DRAFT model of the same transformer
      family (``TransformerLM`` or params dict; same vocabulary and a
      positional table covering ``max_seq_len`` —
      :func:`~tensorframes_tpu.models.init_draft_transformer` derives
      one) turns on SPECULATIVE DECODING: each step the draft proposes
      up to ``draft_len`` tokens from its own KV page group in the
      pool, and ONE batched ``[max_slots, draft_len + 1]`` verify
      program scores every proposal against the target's paged KV.
      Acceptance is EXACT-MATCH against the target's own sampled token
      (greedy or seeded), so emitted streams stay byte-identical to
      non-speculative decode; rejected speculative KV rolls back by
      length bookkeeping alone. Adds two compiled step programs
      (draft + verify; the plain decode program never dispatches while
      speculation is on, so ``num_step_programs`` stays <= 5 — <= 3
      with speculation off). See docs/serving_llm.md "Speculative
      decoding";
    - ``draft_len``: the compiled STATIC draft length k (default:
      the autotuner's ``serve.draft_len`` winner, else 4). A per-slot
      adaptive controller shrinks the effective k on cold
      (low-acceptance) slots and grows it back on hot ones, bounded by
      this static k;
    - ``mesh``: a 1-D :class:`jax.sharding.Mesh` makes THIS replica
      span its chips (tensor parallelism, ``serve/tp.py``): the same
      three step programs compile as ``jit(shard_map(...))`` — weights
      sharded at rest and gathered bit-exactly inside the step, the KV
      pool and the paged attention walk sharded along KV heads — so
      decode streams stay byte-identical to solo at every TP degree
      while per-chip weight/KV memory scales ~1/N. ``num_pages``
      becomes the PER-CHIP page budget (the pool holds
      ``num_pages × N`` total — aggregate KV capacity scales with the
      mesh). Requires ``n_heads``/``n_kv_heads``/``d_ff`` divisible by
      the mesh size; dense (non-MoE) blocks only
      (docs/serving_llm.md "Tensor parallelism").

    A third compiled program (the ``[1, chunk]`` prefill-chunk step)
    exists only when chunked prefill or the prefix cache dispatches it:
    ``num_step_programs`` stays <= 2 with both off, <= 3 otherwise.
    Speculative decoding (``draft_params=``) adds the draft and verify
    programs — and retires the plain decode dispatch while it is on —
    so the budget becomes <= 5."""

    def __init__(
        self,
        model,
        *,
        max_slots: Optional[int] = None,
        page_size: Optional[int] = None,
        num_pages: Optional[int] = None,
        max_seq_len: Optional[int] = None,
        queue_capacity: int = 64,
        top_k: int = 0,
        eos_id: Optional[int] = None,
        moe_top_k: int = 1,
        attention_impl: Optional[str] = None,
        prefill_chunk_tokens: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        draft_params=None,
        draft_len: Optional[int] = None,
        name: Optional[str] = None,
        mesh=None,
    ):
        import jax

        params = getattr(model, "params", model)
        n_heads = params["n_heads"]
        d_model = int(np.shape(params["embed"])[1])
        hd = d_model // n_heads
        n_kv = _kv_heads(params["blocks"][0], d_model, n_heads)
        model_max = int(np.shape(params["pos"])[0])
        self.max_seq_len = int(max_seq_len or model_max)
        if self.max_seq_len > model_max:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"positional table ({model_max})"
            )
        # dtype only — never np.asarray the embed table (that would
        # d2h-copy the whole embedding just to read one attribute)
        kv_dtype = np.dtype(getattr(params["embed"], "dtype", np.float32))
        #: the ``serve.page_slots`` winner for this model signature when
        #: one is stored (None otherwise) — pool GEOMETRY: decode slots
        #: × pages per slot. Cached-mode-safe like every init-time knob:
        #: consulted only where the caller passed no explicit value
        #: (slot count and pool size change scheduling, never streams —
        #: the serve-suite byte-identity).
        self._tuned_geometry = self._tuned_page_slots(kv_dtype, hd)
        if max_slots is None:
            max_slots = 8
            if self._tuned_geometry is not None:
                max_slots = max(
                    1, int(self._tuned_geometry.get("slots", 8))
                )
        self.max_slots = int(max_slots)
        #: tensor parallelism (docs/serving_llm.md "Tensor parallelism",
        #: serve/tp.py): a 1-D jax Mesh makes THIS replica span its
        #: chips — weights sharded at rest, the KV pool and paged
        #: attention sharded along KV heads, decode streams
        #: byte-identical to solo at every degree
        self.mesh = mesh
        self.tp_degree = 1
        self._tp_axis: Optional[str] = None
        if mesh is not None:
            from .tp import validate_tp_mesh

            blk0 = params["blocks"][0]
            d_ff = (
                int(np.shape(blk0["up"])[1]) if "up" in blk0 else 0
            )
            self._tp_axis = validate_tp_mesh(mesh, n_heads, n_kv, d_ff)
            self.tp_degree = int(mesh.devices.size)
        if page_size is None:
            # the measured-best default (ISSUE 13 satellite): one page IS
            # the fused read's key tile, so the flash sweep's block_k —
            # ``paged_page_size_hint`` — is the default, clamped to the
            # sequence bound; the autotuner's ``serve.page_size`` winner
            # (tuned by tune_serve_knobs / bench.py autotune) overrides
            # the hint. An EXPLICIT argument wins over both and is
            # taken verbatim (no clamp — callers pinning a page size
            # keep exactly the pool layout they asked for).
            # /healthz reports whatever was chosen.
            page_size = self._default_page_size(kv_dtype, hd)
        self.page_size = max(1, int(page_size))
        self._max_pages = pages_needed(self.max_seq_len, self.page_size)
        if num_pages is None:
            pps = self._max_pages
            if self._tuned_geometry is not None:
                # the tuned pool geometry may oversubscribe (fewer pages
                # per slot than full coverage — preempt-and-requeue is
                # the relief valve), never undercut feasibility: the
                # pool always holds at least one full-length request.
                # Like an explicit ``num_pages``, a tuned budget is a
                # PER-CHIP quantity, so it scales by the TP degree —
                # only the untuned full-coverage default (which can
                # never preempt) skips the multiply.
                pps = max(
                    1,
                    min(
                        int(
                            self._tuned_geometry.get(
                                "pages_per_slot", pps
                            )
                        ),
                        self._max_pages,
                    ),
                )
                num_pages = max(
                    self._max_pages,
                    self.max_slots * pps * self.tp_degree,
                )
            else:
                num_pages = self.max_slots * pps
        elif self.tp_degree > 1:
            # ``num_pages`` is the PER-CHIP page budget: a page spans
            # the mesh's shards (1/N of its solo bytes per chip), so a
            # fixed per-chip HBM budget holds N× the pages — aggregate
            # KV capacity scales with the TP degree, which is what lets
            # a workload that exhausts TP=1 admission serve
            # preemption-free at TP=2 (``serve.pages_capacity`` reports
            # the scaled total)
            num_pages = int(num_pages) * self.tp_degree
        kv_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            from .tp import tp_kv_specs

            kv_sharding = NamedSharding(mesh, tp_kv_specs(self._tp_axis))
        self.pool = PagePool(
            n_layers=len(params["blocks"]),
            n_kv_heads=n_kv,
            head_dim=hd,
            num_pages=num_pages,
            page_size=self.page_size,
            sharding=kv_sharding,
        )
        cfg = get_config()
        if attention_impl is None:
            attention_impl = cfg.serve_attention_impl
        if attention_impl not in ("gather", "fused"):
            raise ValueError(
                f"attention_impl must be 'gather' or 'fused'; got "
                f"{attention_impl!r}"
            )
        self.attention_impl = attention_impl
        if prefill_chunk_tokens is None:
            prefill_chunk_tokens = cfg.serve_prefill_chunk_tokens
            if prefill_chunk_tokens == 0:
                # neither argument nor config asked for chunking: take
                # the autotuner's winner when one is stored (cache-only
                # at init — the measured search for the serving knobs
                # lives in tune.tune_serve_knobs; chunking never changes
                # emitted tokens, the serve-suite byte-identity)
                prefill_chunk_tokens = self._tuned_prefill_chunk(
                    kv_dtype, hd
                )
        if prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0; got "
                f"{prefill_chunk_tokens}"
            )
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        #: the chunk program's STATIC width: the chunk size when chunked
        #: prefill is on, else the full prompt row (the prefix-cache
        #: resume path then runs as one "chunk" mid-sequence)
        self._chunk_c = self.prefill_chunk_tokens or self.max_seq_len
        if prefix_cache is None:
            prefix_cache = cfg.serve_prefix_cache
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.pool) if prefix_cache else None
        )
        self.scheduler = Scheduler(
            self.pool, self.max_slots, queue_capacity, self.max_seq_len,
            prefix_cache=self.prefix_cache,
        )
        self.top_k = int(top_k)
        self.eos_id = eos_id
        self._d_model = d_model
        # -- speculative decoding: the draft model's config + KV page
        # group (docs/serving_llm.md "Speculative decoding") -----------
        #: compiled static draft length k (0 = speculation off)
        self.draft_len = 0
        self._draft_dev = None
        self._draft_group = None
        self._draft_d_model = 0
        #: cumulative host-side speculation stats (health()/statusz)
        self._spec_proposed = 0
        self._spec_accepted = 0
        if draft_params is not None:
            dp = getattr(draft_params, "params", draft_params)
            d_vocab = int(np.shape(dp["embed"])[0])
            vocab = int(np.shape(params["embed"])[0])
            if d_vocab != vocab:
                raise ValueError(
                    f"draft model vocabulary ({d_vocab}) must match the "
                    f"target's ({vocab}): proposals are target token ids"
                )
            if int(np.shape(dp["pos"])[0]) < self.max_seq_len:
                raise ValueError(
                    f"draft model's positional table "
                    f"({int(np.shape(dp['pos'])[0])}) is shorter than "
                    f"max_seq_len ({self.max_seq_len})"
                )
            if draft_len is None:
                draft_len = self._tuned_draft_len(kv_dtype, hd)
            if int(draft_len) < 1:
                raise ValueError(
                    f"draft_len must be >= 1 with a draft model; got "
                    f"{draft_len} (omit draft_params to disable "
                    f"speculation)"
                )
            self.draft_len = min(int(draft_len), self.max_seq_len - 1)
            d_heads = dp["n_heads"]
            self._draft_d_model = int(np.shape(dp["embed"])[1])
            d_hd = self._draft_d_model // d_heads
            d_n_kv = _kv_heads(
                dp["blocks"][0], self._draft_d_model, d_heads
            )
            # the draft's own KV page group: parallel page arrays in the
            # SAME pool index space (one page list covers both models —
            # alloc/free/defrag/prefix-sharing stay single-sourced).
            # Replicated even under a TP mesh: the draft is small and
            # its proposals never touch emitted bytes, so sharding it
            # buys nothing the verify contract needs.
            self._draft_group = self.pool.add_group(
                "draft",
                n_layers=len(dp["blocks"]),
                n_kv_heads=d_n_kv,
                head_dim=d_hd,
                dtype=np.dtype(
                    getattr(dp["embed"], "dtype", np.float32)
                ),
            )
            self._draft_host = {
                k: v for k, v in dp.items() if k != "n_heads"
            }
            self._draft_n_heads = d_heads
        # weights enter the compiled steps as an ARGUMENT (swap-safe, like
        # TransformerLM.generate); one device copy held for the lifetime.
        # Under tensor parallelism the copy is SHARDED AT REST per
        # transformer_tp_specs (qkv/up on output columns, proj/down on
        # hidden rows — per-chip weight HBM scales ~1/N); the step
        # programs gather shards back to bit-exact full weights inside
        # the mesh (serve/tp.py).
        self._host_params = params
        host = {k: v for k, v in params.items() if k != "n_heads"}
        self._tp_param_specs = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            from ..models.transformer import transformer_tp_specs

            self._tp_param_specs = transformer_tp_specs(
                host, self._tp_axis
            )
            self._params_dev = jax.device_put(
                host,
                jax.tree.map(
                    lambda s: NamedSharding(mesh, s),
                    self._tp_param_specs,
                    is_leaf=lambda x: not isinstance(x, (dict, list)),
                ),
            )
        else:
            self._params_dev = jax.device_put(host)
        #: display name for telemetry — the fleet passes its replica
        #: names so the cost registry and /statusz attribute each step
        #: program to its replica; the sequence keeps registry KEYS
        #: unique even when two fleets reuse a replica name
        seq = _next_engine_seq()
        self.name = name if name is not None else f"eng{seq}"
        # donation halves pool traffic on real chips; CPU jax warns and
        # ignores it, so only request it where it works
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        # each step program registers in the per-program cost registry
        # (obs/programs.py): compile wall-time + FLOP/byte estimates at
        # first dispatch, invocation count + cumulative dispatch time
        # after. sync=True is semantics-neutral here — every dispatch
        # site already block_until_ready()s inside its retry window, so
        # the wrapper's sync just moves the wait inside the timing.
        mmeta = dict(
            max_slots=self.max_slots, page_size=self.page_size,
            max_seq_len=self.max_seq_len, d_model=d_model,
            attention_impl=self.attention_impl,
            tp_degree=self.tp_degree,
        )
        if mesh is not None:
            # the SAME three step programs, as jit(shard_map(...)) over
            # the mesh (serve/tp.py): identical call signatures, shapes,
            # and — the serving contract — identical emitted bytes
            from . import tp as _tp

            ax = self._tp_axis
            prefill_fn = _tp.tp_prefill_impl(
                self, mesh, ax, n_heads, moe_top_k
            )
            decode_fn = _tp.tp_decode_impl(
                self, mesh, ax, n_heads, moe_top_k
            )
            chunk_fn = _tp.tp_prefill_chunk_impl(
                self, mesh, ax, n_heads, moe_top_k
            )
            verify_fn = (
                _tp.tp_verify_impl(self, mesh, ax, n_heads, moe_top_k)
                if self.draft_len
                else None
            )
        else:
            prefill_fn = self._prefill_impl(n_heads, moe_top_k)
            decode_fn = self._decode_impl(n_heads, moe_top_k)
            chunk_fn = self._prefill_chunk_impl(n_heads, moe_top_k)
            verify_fn = (
                self._verify_impl(n_heads, moe_top_k)
                if self.draft_len
                else None
            )
        self._prefill_jit = _programs.instrument(
            jax.jit(prefill_fn, donate_argnums=donate),
            key=f"serve.{seq}:prefill",
            name=f"serve.prefill[{self.name}]",
            kind="serve.step", sync=True, **mmeta,
        )
        self._decode_jit = _programs.instrument(
            jax.jit(decode_fn, donate_argnums=donate),
            key=f"serve.{seq}:decode",
            name=f"serve.decode[{self.name}]",
            kind="serve.step", sync=True, **mmeta,
        )
        # built unconditionally (a jit wrapper is free until dispatched);
        # it only dispatches — and only then counts a program — when
        # chunked prefill or a prefix-cache resume needs it
        self._prefill_chunk_jit = _programs.instrument(
            jax.jit(chunk_fn, donate_argnums=donate),
            key=f"serve.{seq}:prefill_chunk",
            name=f"serve.prefill_chunk[{self.name}]",
            kind="serve.step", sync=True, **mmeta,
        )
        self._verify_jit = self._draft_jit = None
        if self.draft_len:
            # the two speculative programs (draft + verify). The DRAFT
            # model runs replicated (plain jit) even under a mesh — its
            # proposals steer how many positions the verify covers,
            # never their values — while the VERIFY program shards on
            # KV heads exactly like decode (serve/tp.py).
            self._draft_dev = jax.device_put(self._draft_host)
            del self._draft_host
            self._verify_jit = _programs.instrument(
                jax.jit(verify_fn, donate_argnums=donate),
                key=f"serve.{seq}:verify",
                name=f"serve.verify[{self.name}]",
                kind="serve.step", sync=True,
                draft_len=self.draft_len, **mmeta,
            )
            self._draft_jit = _programs.instrument(
                jax.jit(
                    self._draft_impl(self._draft_n_heads, moe_top_k),
                    donate_argnums=donate,
                ),
                key=f"serve.{seq}:draft",
                name=f"serve.draft[{self.name}]",
                kind="serve.step", sync=True,
                draft_len=self.draft_len, **mmeta,
            )
        #: distinct (name, abstract input signature) pairs dispatched —
        #: jit keys compiles on exactly this, so its length IS the number
        #: of compiled step programs
        self.program_signatures: set = set()
        self._req_counter = 0
        self._submit_lock = threading.Lock()
        self._step_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: False after a terminal stepping failure (supervisor fail-fast)
        #: or a wedged stop; submit sheds until restart()
        self.healthy = True
        #: stop() observed the stepping thread outliving its join window
        self._stop_wedged = False
        #: consecutive decode steps lost to device OOM — bounds the
        #: defragment + preempt-youngest recovery loop
        self._consecutive_ooms = 0
        #: monotonic time the last step COMPLETED (the /healthz watchdog:
        #: a large age with work queued means the stepping path is wedged)
        self._last_step_t = time.monotonic()
        #: a fault queued by :meth:`inject_fault` — consumed (and raised)
        #: at the START of the next step, so an externally-injected
        #: replica kill lands at a step boundary instead of racing a
        #: step in progress
        self._poison: Optional[BaseException] = None
        _m_pages_capacity.set(float(num_pages))
        _m_tp_degree.set(float(self.tp_degree), engine=self.name)
        #: estimated collective wall per dispatched step (0 solo): a
        #: one-time micro-measurement of the step's gather pattern,
        #: charged to serve.collective_seconds per dispatch
        self._collective_step_s = 0.0
        self._collective_bytes_per_step = 0.0
        if mesh is not None and self.tp_degree > 1:
            from .tp import estimate_collective_seconds

            (
                self._collective_step_s,
                self._collective_bytes_per_step,
            ) = estimate_collective_seconds(self, mesh, self._tp_axis)
        # per-request cost attribution (obs/requests.py): observe every
        # finishing slot while it still holds its pages
        self.scheduler.on_request_done = self._account_request

    # -- tuned serving knobs ----------------------------------------------

    def _default_page_size(self, kv_dtype, head_dim: int) -> int:
        """Default page size when the caller passed none: the
        measured-best key-tile mapping (``paged_page_size_hint``, the
        flash sweep's block_k clamped to ``max_seq_len``), overridden by
        the autotuner's ``serve.page_size`` winner for this model
        signature when one is in the store."""
        from ..ops.attention import paged_page_size_hint

        hint = max(
            1,
            min(
                int(paged_page_size_hint(kv_dtype, head_dim)),
                self.max_seq_len,
            ),
        )
        try:
            from .. import tune

            if tune.mode() == "off":
                return hint
            win = tune.lookup(
                "serve.page_size",
                tune.serve_signature(kv_dtype, head_dim, self.max_seq_len),
                {"page_size": hint},
            )
            # defaulted path: clamp the winner like the hint — a store
            # row from a longer-sequence world must not oversize pages
            return max(
                1, min(int(win.get("page_size", hint)), self.max_seq_len)
            )
        except Exception:
            return hint

    def _tuned_page_slots(self, kv_dtype, head_dim: int):
        """The autotuner's ``serve.page_slots`` winner — pool geometry
        (decode slots × pages per slot) — for this model signature, or
        None when nothing is stored. Cache-only at init, like the other
        serving knobs (the measured search lives in
        ``tune.tune_serve_knobs``)."""
        try:
            from .. import tune

            if tune.mode() == "off":
                return None
            win = tune.lookup(
                "serve.page_slots",
                tune.serve_signature(
                    kv_dtype, head_dim, self.max_seq_len
                ),
                {},
            )
            return win or None
        except Exception:
            return None

    def _tuned_draft_len(self, kv_dtype, head_dim: int) -> int:
        """Default static draft length k when ``draft_params`` is given
        with no explicit ``draft_len``: the autotuner's
        ``serve.draft_len`` winner for this model signature (the
        measured search lives in ``tune.tune_serve_knobs``, driven by
        the acceptance-rate and verify-wall series), else 4 — cache-only
        at init like the other serving knobs."""
        try:
            from .. import tune

            if tune.mode() == "off":
                return 4
            win = tune.lookup(
                "serve.draft_len",
                tune.serve_signature(kv_dtype, head_dim, self.max_seq_len),
                {"k": 4},
            )
            return max(1, min(int(win.get("k", 4)), self.max_seq_len - 1))
        except Exception:
            return 4

    def _tuned_prefill_chunk(self, kv_dtype, head_dim: int) -> int:
        """The autotuner's ``serve.prefill_chunk`` winner (0 — whole
        prompts in one pass — when nothing is stored)."""
        try:
            from .. import tune

            if tune.mode() == "off":
                return 0
            win = tune.lookup(
                "serve.prefill_chunk",
                tune.serve_signature(kv_dtype, head_dim, self.max_seq_len),
                {"tokens": 0},
            )
            return max(0, min(int(win.get("tokens", 0)), self.max_seq_len))
        except Exception:
            return 0

    # -- compiled step builders -------------------------------------------

    def _prefill_impl(self, n_heads: int, moe_top_k: int):
        import jax
        import jax.numpy as jnp

        ps = self.page_size
        trash = self.pool.trash_page
        top_k = self.top_k

        def prefill(p, kp, vp, prompt, length, ptab, temp, seed, top_p):
            full = {**p, "n_heads": n_heads}
            logits, kc, vc = transformer_prefill(
                full, prompt, moe_top_k=moe_top_k
            )
            # [L, 1, n_kv, Pmax, hd] -> [L, Pmax, n_kv, hd]; positions
            # past the real prompt scatter into the trash page
            k_all = kc[:, 0].transpose(0, 2, 1, 3)
            v_all = vc[:, 0].transpose(0, 2, 1, 3)
            pos = jnp.arange(prompt.shape[1])
            page = jnp.where(pos < length, ptab[pos // ps], trash)
            off = pos % ps
            kp = kp.at[:, page, off].set(k_all)
            vp = vp.at[:, page, off].set(v_all)
            last = logits[0, length - 1]
            greedy = jnp.argmax(last, axis=-1)
            # sampled path mirrors generate: per-step key folded at the
            # emitting position, filter_logits truncation, categorical
            key = jax.random.fold_in(jax.random.PRNGKey(seed), length - 1)
            scaled = last[None] / jnp.maximum(
                jnp.asarray(temp, jnp.float32), 1e-6
            )
            filt = filter_logits(scaled, top_k=top_k, top_p=top_p)
            sampled = jax.random.categorical(key, filt, axis=-1)[0]
            tok = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
            return kp, vp, tok

        return prefill

    def _prefill_chunk_impl(self, n_heads: int, moe_top_k: int):
        """The third compiled step: one ``[1, C]`` span of a prompt at
        positions ``start .. start + C``, attending to the pages already
        written (earlier chunks, or a shared-prefix cache hit) plus
        itself causally. The per-position math is
        :func:`transformer_prefill_chunk`'s block walk — byte-identical
        k/v and logits to the one-pass prefill — and the sampled token
        mirrors the full program's (folded at the LAST prompt position),
        so only the final chunk's token is consumed."""
        import jax
        import jax.numpy as jnp

        from ..ops.attention import _NEG_BIG

        ps = self.page_size
        trash = self.pool.trash_page
        top_k = self.top_k
        mp = self._max_pages
        max_len = self.max_seq_len

        def chunk_step(
            p, kp, vp, chunk, start, valid, total_len, ptab, temp, seed,
            top_p,
        ):
            full = {**p, "n_heads": n_heads}
            c = chunk.shape[1]
            offs = jnp.arange(c)
            pos = start + offs  # absolute positions; tail is padding
            pos_clipped = jnp.minimum(pos, max_len - 1)
            state = [kp, vp]

            def attend(li, q, k, v):
                # scatter this chunk's k/v into its pages (padding rows
                # land in the trash page), then read the whole visible
                # history through the page table under the causal mask
                page = jnp.where(offs < valid, ptab[pos_clipped // ps], trash)
                off = pos_clipped % ps
                state[0] = state[0].at[li, page, off].set(k[0])
                state[1] = state[1].at[li, page, off].set(v[0])
                n_kv, hd = k.shape[2], k.shape[3]
                t = mp * ps
                kg = state[0][li][ptab].reshape(t, n_kv, hd)
                vg = state[1][li][ptab].reshape(t, n_kv, hd)
                scale = 1.0 / float(np.sqrt(hd))
                s = jnp.einsum("ckgd,tkd->ckgt", q[0], kg) * scale
                visible = jnp.arange(t)[None, :] <= pos[:, None]
                # the shared mask fill: byte-identity between chunked
                # and one-pass prefill depends on every paged/dense
                # read masking with the same value
                s = jnp.where(visible[:, None, None, :], s, _NEG_BIG)
                att = jnp.einsum(
                    "ckgt,tkd->ckgd", jax.nn.softmax(s, axis=-1), vg
                )
                return att.reshape(1, c, n_kv * q.shape[3] * hd)

            logits = transformer_prefill_chunk(
                full, chunk, pos_clipped, attend, moe_top_k=moe_top_k
            )
            # the final chunk's last REAL position seeds generation,
            # exactly as the one-pass prefill samples it (key folded at
            # the absolute last prompt position)
            last = logits[0, valid - 1]
            greedy = jnp.argmax(last, axis=-1)
            key = jax.random.fold_in(
                jax.random.PRNGKey(seed), total_len - 1
            )
            scaled = last[None] / jnp.maximum(
                jnp.asarray(temp, jnp.float32), 1e-6
            )
            filt = filter_logits(scaled, top_k=top_k, top_p=top_p)
            sampled = jax.random.categorical(key, filt, axis=-1)[0]
            tok = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
            return state[0], state[1], tok

        return chunk_step

    def _decode_impl(self, n_heads: int, moe_top_k: int):
        import jax
        import jax.numpy as jnp

        from ..ops import paged_attention, ragged_paged_attention

        ps = self.page_size
        d_model = self._d_model
        top_k = self.top_k
        fused = self.attention_impl == "fused"

        def decode(p, kp, vp, toks, positions, ptabs, temps, seeds, top_ps):
            full = {**p, "n_heads": n_heads}
            slots = toks.shape[0]
            state = [kp, vp]

            def attend(li, q, k, v):
                # write this token's k/v into its page, then read the
                # whole visible history through the page table — via the
                # materialized gather (reference) or the fused ragged
                # kernel (bandwidth scales with live tokens)
                page = ptabs[jnp.arange(slots), positions // ps]
                off = positions % ps
                state[0] = state[0].at[li, page, off].set(k)
                state[1] = state[1].at[li, page, off].set(v)
                read = ragged_paged_attention if fused else paged_attention
                ctx = read(
                    q, state[0][li], state[1][li], ptabs, positions + 1
                )
                return ctx.reshape(slots, d_model)

            logits = transformer_step(
                full, toks, positions, attend, moe_top_k=moe_top_k
            )
            greedy = jnp.argmax(logits, axis=-1)
            keys = jax.vmap(
                lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t)
            )(seeds, positions)
            scaled = logits / jnp.maximum(temps[:, None], 1e-6)
            filt = filter_logits(scaled, top_k=top_k, top_p=top_ps[:, None])
            sampled = jax.vmap(jax.random.categorical)(keys, filt)
            nxt = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
            return state[0], state[1], nxt

        return decode

    def _verify_impl(self, n_heads: int, moe_top_k: int):
        """The VERIFY step — the engine's fourth compiled program, the
        speculative-decoding tentpole: ``[max_slots, k + 1]`` tokens
        (each slot's pending token followed by its draft proposals) run
        the TARGET model's mid-sequence chunk walk
        (:func:`transformer_verify_chunk`) in ONE dispatch, scattering
        target k/v for every position and sampling the target's token
        at each with the per-step key folded at that ABSOLUTE position
        — exactly the decode program's rule, which is what keeps
        speculative streams byte-identical to solo decode (greedy and
        seeded). Positions past a slot's ``n_valid`` (adaptive k < the
        static k, idle slots) scatter into the trash page and their
        samples are ignored."""
        import jax.numpy as jnp

        ps = self.page_size
        trash = self.pool.trash_page
        top_k = self.top_k
        mp = self._max_pages
        max_len = self.max_seq_len
        c = self.draft_len + 1

        def verify(
            p, kp, vp, toks, starts, n_valid, ptabs, temps, seeds, top_ps
        ):
            full = {**p, "n_heads": n_heads}
            slots = toks.shape[0]
            pos = starts[:, None] + jnp.arange(c)[None, :]  # [S, C]
            pos_c = jnp.clip(pos, 0, max_len - 1)
            state = [kp, vp]
            # the shared span attend: scatter the whole verify span's
            # k/v (padding and out-of-range positions land in the trash
            # page), then read each position's visible history through
            # the page table — the prefill-chunk read, batched over
            # slots
            attend = _span_attend(
                state, ptabs, pos, pos_c, n_valid, ps, trash, mp,
                max_len,
            )
            logits = transformer_verify_chunk(
                full, toks, pos_c, attend, moe_top_k=moe_top_k
            )  # [S, C, V]
            vocab = logits.shape[-1]
            u = _sample_slot_tokens(
                logits.reshape(slots * c, vocab),
                pos_c.reshape(-1),
                jnp.repeat(temps, c),
                jnp.repeat(seeds, c),
                jnp.repeat(top_ps, c),
                top_k,
            ).reshape(slots, c)
            return state[0], state[1], u

        return verify

    def _draft_impl(self, n_heads: int, moe_top_k: int):
        """The DRAFT step — one dispatch per engine step proposes up to
        k tokens per slot from the draft model's own KV page group:

        - phase 1 (chunk): the ``[max_slots, k + 1]`` context window —
          tokens the draft has not ingested yet, teacher-forced —
          runs the draft's chunk walk, writing draft k/v; the LAST
          context token's logits seed proposal 1 (sampled with the
          target's exact rule at that absolute position, so a correct
          draft's proposal matches the target's token bit-for-bit);
        - phase 2 (scan, k - 1 iterations): single-token draft steps
          extend the proposals, each writing its draft k/v and sampling
          the next.

        The same program also serves CATCH-UP (a freshly prefilled
        prompt, a preemption replay): the host feeds ONE lag window per
        engine step through phase 1 — the slot decodes plainly until
        the backlog drains, bounding the stall like chunked prefill —
        and uses proposals only once the window reaches the newest
        token. Proposals never touch emitted bytes — the verify
        program's target tokens do — so the draft runs replicated even
        under a TP mesh."""
        import jax
        import jax.numpy as jnp

        from ..ops import paged_attention

        ps = self.page_size
        trash = self.pool.trash_page
        top_k = self.top_k
        mp = self._max_pages
        max_len = self.max_seq_len
        k_static = self.draft_len
        w = k_static + 1
        d_model = self._draft_d_model

        def draft(
            p, kp, vp, ctx, starts, n_ctx, ptabs, temps, seeds, top_ps
        ):
            full = {**p, "n_heads": n_heads}
            slots = ctx.shape[0]
            pos = starts[:, None] + jnp.arange(w)[None, :]
            pos_c = jnp.clip(pos, 0, max_len - 1)
            state = [kp, vp]
            attend = _span_attend(
                state, ptabs, pos, pos_c, n_ctx, ps, trash, mp, max_len
            )
            logits = transformer_verify_chunk(
                full, ctx, pos_c, attend, moe_top_k=moe_top_k
            )  # [S, W, V]
            last_pos = starts + n_ctx - 1
            last = jnp.take_along_axis(
                logits, (n_ctx - 1)[:, None, None], axis=1
            )[:, 0]  # [S, V]
            t1 = _sample_slot_tokens(
                last,
                jnp.clip(last_pos, 0, max_len - 1),
                temps, seeds, top_ps, top_k,
            )
            if k_static == 1:
                return state[0], state[1], t1[:, None]

            def scan_body(carry, _):
                dk, dv, tok, posn = carry
                posn_c = jnp.clip(posn, 0, max_len - 1)
                inner = [dk, dv]

                def attend_step(li, q, k, v):
                    page = jnp.where(
                        posn < max_len,
                        ptabs[jnp.arange(slots), posn_c // ps],
                        trash,
                    )
                    off = posn_c % ps
                    inner[0] = inner[0].at[li, page, off].set(k)
                    inner[1] = inner[1].at[li, page, off].set(v)
                    read = paged_attention(
                        q, inner[0][li], inner[1][li], ptabs, posn_c + 1
                    )
                    return read.reshape(slots, d_model)

                step_logits = transformer_step(
                    full, tok, posn_c, attend_step, moe_top_k=moe_top_k
                )
                nxt = _sample_slot_tokens(
                    step_logits, posn_c, temps, seeds, top_ps, top_k
                )
                return (inner[0], inner[1], nxt, posn + 1), nxt

            # proposal t_i sits at absolute position last_pos + i; the
            # scan walks t_1 .. t_{k-1} through the draft (writing their
            # draft k/v — correct whenever the proposal is accepted) and
            # emits t_2 .. t_k
            (dk, dv, _, _), rest = jax.lax.scan(
                scan_body,
                (state[0], state[1], t1, last_pos + 1),
                None,
                length=k_static - 1,
            )
            props = jnp.concatenate([t1[:, None], rest.T], axis=1)
            return dk, dv, props

        return draft

    def _charge_collectives(self) -> None:
        """One step program dispatched: charge its estimated collective
        wall (no-op solo)."""
        if self._collective_step_s:
            _m_collective_s.inc(self._collective_step_s)

    def _record_program(self, name: str, *args) -> None:
        sig: List = [name]
        for a in args:
            if isinstance(a, dict):
                sig.append("params")
            else:
                arr = np.asarray(a) if np.isscalar(a) else a
                sig.append((tuple(arr.shape), str(arr.dtype)))
        self.program_signatures.add(tuple(sig))

    @property
    def num_step_programs(self) -> int:
        """Distinct compiled step programs dispatched so far (jit keys on
        the abstract input signature; static shapes keep this at <= 3:
        one prefill + one decode, plus the prefill-chunk program when
        chunked prefill / prefix-cache resume dispatches it — and <= 5
        with speculative decoding on, which adds the draft and verify
        programs while the plain decode program stops dispatching)."""
        return len(self.program_signatures)

    # -- submission --------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        block: bool = True,
        timeout: Optional[float] = None,
        deadline: Optional[float] = None,
        trace=None,
        tenant: str = "",
        _handle_factory=None,
    ) -> GenerationHandle:
        """Queue one generation request; returns its streaming handle.
        Raises ``ValueError`` for requests that could never be scheduled,
        :class:`~.scheduler.QueueFullError` when the bounded queue is
        full and ``block=False``, and :class:`EngineUnhealthyError` when
        the engine is shedding after a terminal failure (restart() to
        recover). ``deadline`` is a per-request budget in SECONDS from
        now: the step sweep evicts the request — queued or
        mid-generation — once it passes, and the handle raises
        :class:`~tensorframes_tpu.utils.failures.DeadlineExceededError`.

        ``trace`` attaches a
        :class:`~tensorframes_tpu.obs.TraceContext` the request's
        engine-side spans join (default: the submitting thread's
        current trace, so an HTTP ``traceparent`` flows through without
        every caller threading it explicitly).

        ``tenant`` keys the request's cost-attribution record
        (``obs/requests.py``; empty = unattributed) — the fleet fills
        it from the session id when the client names no tenant.

        ``_handle_factory`` (private) lets the fleet router
        (``serve/fleet.py``) substitute its relay handle —
        ``factory(request_id) -> GenerationHandle`` — so emissions and
        the terminal close forward to the fleet-level stream."""
        prompt = np.asarray(prompt, np.int32).ravel()
        if prompt.size < 1:
            _m_requests.inc(status="rejected")
            raise ValueError("prompt needs at least one token")
        if max_new_tokens < 1:
            _m_requests.inc(status="rejected")
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        if deadline is not None and deadline <= 0:
            _m_requests.inc(status="rejected")
            raise ValueError(
                f"deadline must be positive seconds from now; got {deadline}"
            )
        if not self.healthy or self._stop_wedged:
            # shed instead of queueing work a broken engine will never
            # run — the caller gets the fast 503, not a hung handle
            _m_requests.inc(status="rejected")
            raise EngineUnhealthyError(
                "engine is unhealthy after a terminal stepping failure "
                "or a wedged stop; restart() it (or recycle the process) "
                "before submitting"
            )
        if _handle_factory is None and _tenancy.enabled():
            # the QoS admission gate (quota / rate / SLO shed → 429).
            # Only at the FRONT door: the fleet router charged its
            # fleet-wide check already, so the relay path
            # (_handle_factory set) must not bill the tenant twice —
            # and preemption requeues / failover replays never come
            # back through submit at all
            active, queued = self.scheduler.tenant_counts()
            key = str(tenant or "")
            try:
                _tenancy.admit_request(
                    key, int(max_new_tokens),
                    active.get(key, 0), queued.get(key, 0),
                )
            except TenantThrottledError:
                _m_requests.inc(status="rejected")
                raise
        with self._submit_lock:
            self._req_counter += 1
            rid = self._req_counter
        handle = (
            GenerationHandle if _handle_factory is None else _handle_factory
        )(rid)
        req = GenRequest(
            request_id=rid,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            eos_id=self.eos_id if eos_id is None else eos_id,
            handle=handle,
            deadline_t=(
                None if deadline is None else time.monotonic() + deadline
            ),
            trace=trace if trace is not None else _current_trace(),
            tenant=str(tenant or ""),
            priority=_tenancy.priority_of(str(tenant or "")),
        )
        try:
            self.scheduler.submit(req, block=block, timeout=timeout)
        except (ValueError, QueueFullError):
            # both are terminal rejections from the caller's view —
            # infeasible shape and queue backpressure alike must keep
            # completed + failed + rejected == submissions
            _m_requests.inc(status="rejected")
            raise
        _m_queue_depth.set(float(self.scheduler.queue_depth))
        return handle

    # -- stepping ----------------------------------------------------------

    def step(self) -> bool:
        """One scheduler iteration: sweep expired deadlines, admit +
        prefill newcomers, grow pages (preempting on exhaustion), one
        decode step for the batch. Returns whether work remains.

        Failure classification (the supervisor's contract,
        ``docs/fault_tolerance.md``): transient dispatch errors retry
        with bounded backoff INSIDE the step (``run_with_retries`` on
        the compiled-step calls); device OOM mid-decode recovers by
        ``defragment()`` + preempt-youngest without failing anyone;
        whatever still escapes fails the affected requests' handles and
        re-raises for the caller (the background loop then fails the
        rest and marks the engine unhealthy)."""
        with self._step_lock:
            try:
                return self._step_locked()
            finally:
                # the /healthz watchdog: age of the last step COMPLETION
                # (normal, recovered, or failed — a wedged device call is
                # the thing this must expose, and that never reaches here)
                self._last_step_t = time.monotonic()

    def _step_locked(self) -> bool:
        poison = self._poison
        if poison is not None:
            # an injected hard fault (inject_fault): raise BEFORE touching
            # the batch so every token already emitted stays consistent —
            # the supervisor then fails all in-flight handles promptly
            self._poison = None
            raise poison
        expired = self.scheduler.expire(time.monotonic())
        if expired:
            _m_deadline_expired.inc(expired)
            _m_handles_failed.inc(expired, reason="deadline")
            _m_requests.inc(expired, status="failed")
        prefill_err: Optional[BaseException] = None
        stepped: set = set()
        for idx, act in self.scheduler.admit():
            stepped.add(idx)
            err = self._try_prefill(idx, act, first=True)
            if err is not None and prefill_err is None:
                prefill_err = err
        # slots admitted in EARLIER steps still mid-prompt (chunked
        # prefill) advance one chunk per step, interleaved with the
        # decode batch below — the bounded-stall property
        for idx, act in self.scheduler.active:
            if (
                idx in stepped
                or act.generated
                or self.scheduler.slots[idx] is not act
            ):
                continue
            err = self._try_prefill(idx, act, first=False)
            if err is not None and prefill_err is None:
                prefill_err = err
        if prefill_err is not None:
            # every surviving slot is prefilled; propagate now, before
            # decode, so synchronous drivers see the device error
            self._refresh_gauges()
            raise prefill_err
        batch = self.scheduler.active
        if batch:
            ready: List[Tuple[int, _Active]] = []
            for idx, act in batch:
                if self.scheduler.slots[idx] is not act:
                    continue  # preempted as a victim already
                if not act.generated:
                    continue  # still prefilling in chunks
                if self.scheduler.grow(idx):
                    ready.append((idx, act))
            # growth for a later slot may have evicted an earlier one
            ready = [
                (i, a) for i, a in ready if self.scheduler.slots[i] is a
            ]
            if ready:
                try:
                    if self.draft_len:
                        self._spec_batch(ready)
                    else:
                        self._decode_batch(ready)
                    self._consecutive_ooms = 0
                except Exception as e:
                    if is_oom(e) and self._recover_oom():
                        self._refresh_gauges()
                        return True
                    for i, _ in ready:
                        if self.scheduler.slots[i] is not None:
                            self.scheduler.finish(i, error=e)
                            _m_requests.inc(status="failed")
                            _m_handles_failed.inc(reason=_fail_reason(e))
                    raise
        self._refresh_gauges()
        return self.scheduler.has_work()

    def _note_oom(self) -> bool:
        """One more consecutive OOM recovery attempt; False once the
        bounded budget (``max_slots + 1`` without a completed decode) is
        spent — shrinking cannot help, treat the OOM as fatal."""
        self._consecutive_ooms += 1
        return self._consecutive_ooms <= self.max_slots + 1

    def _recover_oom(self) -> bool:
        """Device OOM mid-decode: the batch died BEFORE its emission loop
        (no tokens were streamed), so the step is safe to redo. Compact
        the pool and shed the youngest sequence (recompute-style requeue
        — its stream never notices), then let the next step retry with a
        smaller batch. Bounded via :meth:`_note_oom`."""
        if not self._note_oom():
            return False
        logger.warning(
            "decode step hit device OOM (%d consecutive); defragmenting "
            "and preempting the youngest sequence",
            self._consecutive_ooms,
        )
        self._defragment_locked()
        victim = self.scheduler._victim_slot(exclude=-1)
        if victim is not None:
            self.scheduler.preempt(victim)
        return True

    def _try_prefill(
        self, idx: int, act: _Active, first: bool
    ) -> Optional[BaseException]:
        """One prefill advance (full prompt, or one chunk) under the
        step's failure contract: device OOM degrades to defragment +
        requeue-self (nothing emitted yet — recompute-style), anything
        else fails THIS request only so later slots still step (an
        abort mid-loop would leave them with no prefill, poisoning the
        decode batch). Returns the non-OOM error, if any, for the caller
        to re-raise once every slot has been serviced."""
        try:
            if first:
                self._prefill_one(idx, act)
            else:
                self._advance_prefill(idx, act)
            return None
        except Exception as e:
            if is_oom(e) and self._note_oom():
                logger.warning(
                    "prefill hit device OOM (%d consecutive); "
                    "defragmenting and requeueing request %d",
                    self._consecutive_ooms,
                    act.req.request_id,
                )
                self._defragment_locked()
                self.scheduler.preempt(idx)
                return None
            self.scheduler.finish(idx, error=e)
            _m_requests.inc(status="failed")
            _m_handles_failed.inc(reason=_fail_reason(e))
            return e

    def _defragment_locked(self) -> Dict[int, int]:
        """Pool compaction with every live page list renumbered — the
        sequences', the prefix cache's (cached prefixes survive), AND
        any slot's pending copy-on-write donor page. The cow reference
        is held as a bare index on ``_Active``, not a list the pool can
        rewrite in place, so it is wrapped here and written back: a
        defragment between admission and ``_apply_cow`` (an earlier
        slot's prefill OOM) would otherwise leave a stale donor index —
        the later clone would copy whatever page landed there (silent KV
        corruption) and free the wrong page's reference."""
        acts = [a for _, a in self.scheduler.active]
        cow_lists = [[a.cow_src] for a in acts if a.cow_src is not None]
        page_lists: List[List[int]] = list(cow_lists)
        if self.prefix_cache is not None:
            page_lists.extend(self.prefix_cache.entry_page_lists())
        remap = self.pool.defragment(
            [a.seq for a in acts], page_lists=page_lists
        )
        it = iter(cow_lists)
        for a in acts:
            if a.cow_src is not None:
                a.cow_src = next(it)[0]
        return remap

    def _prefill_one(self, idx: int, act: _Active) -> None:
        """First prefill service for a newly admitted slot: route to the
        one-pass program, or to the chunk program when the prompt
        exceeds the chunk size or a prefix-cache hit starts mid-prompt."""
        req = act.req
        plen = len(req.prompt)
        timings = req.handle.timings
        if "queue_wait_s" not in timings:
            # first admission only (preemption/replay requeues keep the
            # original submitted_at, and setdefault keeps the first wait)
            timings["queue_wait_s"] = time.monotonic() - req.submitted_at
        if self.prefix_cache is not None:
            _m_prefix_lookups.inc()
            if act.cached_tokens > 0:
                _m_prefix_hits.inc()
                _m_prefix_tokens_saved.inc(act.cached_tokens)
                # cost attribution: tokens this request never prefilled
                # (accumulates across preemption re-admissions)
                timings["prefix_cached_tokens"] = (
                    timings.get("prefix_cached_tokens", 0)
                    + act.cached_tokens
                )
        if self.draft_len and act.cached_tokens > 0:
            # shared prefix pages carry the donor's DRAFT-KV rows too
            # (same page indices in the draft group), so the draft skips
            # the cached span exactly like the target prefill does; a
            # donor that never caught up leaves zeroed rows — proposals
            # degrade, the verify pass still decides every byte
            act.draft_pos = act.cached_tokens
        chunking = self.prefill_chunk_tokens > 0
        if act.cached_tokens > 0 or (
            chunking and plen > self.prefill_chunk_tokens
        ):
            self._apply_cow(act)
            act.prefill_pos = act.cached_tokens
            self._advance_prefill(idx, act)
            return
        self._prefill_full(idx, act)

    def _apply_cow(self, act: _Active) -> None:
        """Copy-on-write for a cached prefix that ends INSIDE a donor
        page: clone the donor's page row into this sequence's private
        page, then drop the temporary donor reference. Positions up to
        ``cached_tokens`` are then valid; the chunk prefill overwrites
        from the divergence point on. Plain device indexing, like
        ``defragment()`` — not a step program."""
        if act.cow_src is None:
            return
        src = act.cow_src
        dst = act.seq.pages[act.cached_tokens // self.page_size]
        pool = self.pool
        pool.k = pool.place(pool.k.at[:, dst].set(pool.k[:, src]))
        pool.v = pool.place(pool.v.at[:, dst].set(pool.v[:, src]))
        for g in pool.groups.values():
            # the donor's draft-KV rows ride the same page indices: the
            # clone must carry them too, or the sharer's draft would
            # propose from a zeroed page (correctness is unaffected —
            # verify decides — but the acceptance rate would crater)
            g.k = g.place(g.k.at[:, dst].set(g.k[:, src]))
            g.v = g.place(g.v.at[:, dst].set(g.v[:, src]))
        act.cow_src = None
        pool.free([src])

    def _register_prefix(self, act: _Active) -> None:
        """A finished prefill publishes its prompt's complete pages for
        future identical prefixes to share."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(
                act.req.prompt, act.seq.pages,
                priority=act.req.priority,
            )

    def _advance_prefill(self, idx: int, act: _Active) -> None:
        """Dispatch ONE prefill chunk (the third compiled program); on
        the final chunk, sample and emit the first token and register
        the prompt's pages in the prefix cache."""
        req = act.req
        plen = len(req.prompt)
        start = act.prefill_pos
        c = self._chunk_c
        valid = min(c, plen - start)
        chunk_row = np.zeros((1, c), np.int32)
        chunk_row[0, :valid] = req.prompt[start : start + valid]
        ptab = act.seq.table(self._max_pages)
        args = (
            chunk_row,
            np.int32(start),
            np.int32(valid),
            np.int32(plen),
            ptab,
            np.float32(req.temperature),
            np.int32(req.seed),
            np.float32(req.top_p),
        )
        pool = self.pool
        self._record_program(
            "prefill_chunk", self._params_dev, pool.k, *args
        )

        def dispatch():
            import jax

            _chaos.site("serve.prefill_chunk")
            return jax.block_until_ready(
                self._prefill_chunk_jit(
                    self._params_dev, pool.k, pool.v, *args
                )
            )

        t0 = time.perf_counter()
        with _use_trace(req.trace), _span(
            "serve.prefill_chunk",
            request=req.request_id,
            start=start,
            tokens=valid,
        ):
            pool.k, pool.v, tok = run_with_retries(
                dispatch,
                what=f"serve.prefill_chunk request {req.request_id}",
            )
        self._charge_collectives()
        timings = req.handle.timings
        timings["prefill_s"] = (
            timings.get("prefill_s", 0.0) + time.perf_counter() - t0
        )
        timings["prefill_chunks"] = timings.get("prefill_chunks", 0) + 1
        self._charge_flops(timings, self._prefill_chunk_jit)
        act.prefill_pos = start + valid
        _m_prefill_chunks.inc()
        if act.prefill_pos >= plen:
            self._register_prefix(act)
            self._emit(idx, act, int(tok))

    def _prefill_full(self, idx: int, act: _Active) -> None:
        req = act.req
        plen = len(req.prompt)
        prompt_row = np.zeros((1, self.max_seq_len), np.int32)
        prompt_row[0, :plen] = req.prompt
        ptab = act.seq.table(self._max_pages)
        args = (
            prompt_row,
            np.int32(plen),
            ptab,
            np.float32(req.temperature),
            np.int32(req.seed),
            np.float32(req.top_p),
        )
        pool = self.pool
        self._record_program("prefill", self._params_dev, pool.k, *args)

        # dispatch inside a retry window, SYNCED inside it (jax dispatch
        # is async; failures.py's coverage rule): the compiled call is
        # functional and pool arrays are reassigned only on success, so a
        # transient failure retries with an identical result. On TPU the
        # step donates pool.k/v — a mid-execution failure there consumes
        # the donated buffers, the retry fails non-transiently, and the
        # supervisor escalates to fail-fast + restart() instead.
        def dispatch():
            import jax

            _chaos.site("serve.prefill")
            return jax.block_until_ready(
                self._prefill_jit(self._params_dev, pool.k, pool.v, *args)
            )

        t0 = time.perf_counter()
        with _use_trace(req.trace), _span(
            "serve.prefill", request=req.request_id, prompt_len=plen
        ):
            pool.k, pool.v, tok = run_with_retries(
                dispatch, what=f"serve.prefill request {req.request_id}"
            )
        self._charge_collectives()
        timings = req.handle.timings
        timings["prefill_s"] = (
            timings.get("prefill_s", 0.0) + time.perf_counter() - t0
        )
        self._charge_flops(timings, self._prefill_jit)
        act.prefill_pos = plen
        self._register_prefix(act)
        self._emit(idx, act, int(tok))

    def _decode_batch(self, ready: List[Tuple[int, _Active]]) -> None:
        s = self.max_slots
        toks = np.zeros(s, np.int32)
        positions = np.zeros(s, np.int32)
        ptabs = np.full(
            (s, self._max_pages), self.pool.trash_page, np.int32
        )
        temps = np.zeros(s, np.float32)
        seeds = np.zeros(s, np.int32)
        top_ps = np.ones(s, np.float32)
        for idx, act in ready:
            toks[idx] = act.generated[-1]
            positions[idx] = act.length - 1  # this token's write position
            ptabs[idx] = act.seq.table(self._max_pages)
            temps[idx] = act.req.temperature
            seeds[idx] = act.req.seed
            top_ps[idx] = act.req.top_p
        args = (toks, positions, ptabs, temps, seeds, top_ps)
        pool = self.pool
        self._record_program("decode", self._params_dev, pool.k, *args)

        # synced inside the retry window, like prefill (the host loop
        # needs ``nxt`` before the next step anyway, so the sync costs
        # no pipelining); same donation caveat as prefill on TPU
        def dispatch():
            import jax

            _chaos.site("serve.decode_step")
            return jax.block_until_ready(
                self._decode_jit(self._params_dev, pool.k, pool.v, *args)
            )

        with _span("serve.decode_step", occupancy=len(ready)):
            pool.k, pool.v, nxt = run_with_retries(
                dispatch, what="serve.decode_step"
            )
        self._charge_collectives()
        nxt = np.asarray(nxt)
        share = 1.0 / max(1, len(ready))
        for idx, act in ready:
            self._charge_flops(
                act.req.handle.timings, self._decode_jit, share
            )
            self._emit(idx, act, int(nxt[idx]))

    # -- speculative decoding ---------------------------------------------

    def _spec_slot_k(self, act: _Active) -> int:
        """This step's EFFECTIVE draft length for one slot: the per-slot
        adaptive k (seeded from the compiled static k), clamped so the
        verify span never outruns the sequence bound or the request's
        remaining budget, then clamped to the pages actually granted —
        speculation degrades to a shorter k under pool pressure, it
        never preempts live work for lookahead room."""
        if act.spec_k < 0:
            act.spec_k = self.draft_len
        k = min(
            act.spec_k,
            self.draft_len,
            act.remaining - 1,
            self.max_seq_len - act.length,
        )
        k = max(0, k)
        if k > 1:
            # QoS: low-priority slots surrender speculative page
            # appetite first under pool pressure (identity when the
            # plane is off). Acceptance is exact-match, so a shorter
            # k never changes emitted bytes.
            k = _tenancy.clamp_spec_k(
                k, act.req.priority,
                self.pool.pages_free, self.pool.num_pages,
            )
        if k > 0:
            try:
                act.seq.ensure(act.length + k)
            except PagePoolExhausted:
                k = max(0, act.seq.capacity - act.length)
        return k

    def _draft_advance(self, ready: List[Tuple[int, _Active]]):
        """ONE draft dispatch per engine step: each slot ingests its
        next ``k + 1``-token window of un-ingested tokens (positions
        ``draft_pos .. length - 1``, teacher-forced) through phase 1.
        Slots whose window reaches the newest token are CAUGHT UP —
        their proposals are live this step; slots still lagging (a
        fresh long prefill, a preemption replay) advance one window per
        step and decode plainly meanwhile, exactly the bounded-stall
        discipline chunked prefill established: catch-up never turns
        one engine step into O(prompt / k) back-to-back dispatches that
        would spike every concurrent stream's inter-token latency.
        Returns ``({slot: [k] proposals}, caught_up_slots)``."""
        s = self.max_slots
        w = self.draft_len + 1
        g = self._draft_group
        mp = self._max_pages
        trash = self.pool.trash_page
        ctx = np.zeros((s, w), np.int32)
        starts = np.zeros(s, np.int32)
        n_ctx = np.ones(s, np.int32)
        ptabs = np.full((s, mp), trash, np.int32)
        temps = np.zeros(s, np.float32)
        seeds = np.zeros(s, np.int32)
        top_ps = np.ones(s, np.float32)
        caught_up: set = set()
        for idx, act in ready:
            l = act.length
            if act.draft_pos >= l:
                # caught up: re-ingest the newest token (rewrites
                # identical draft k/v) so phase 1 seeds proposals
                # from its logits
                act.draft_pos = l - 1
            lag = l - act.draft_pos
            n = min(lag, w)
            if lag <= w:
                caught_up.add(idx)
            start = act.draft_pos
            # slice just the window (positions start .. start+n-1) out
            # of prompt/generated — materializing the whole sequence
            # here would put O(length) host copies per slot on every
            # step's inter-token critical path
            end = start + n
            plen = len(act.req.prompt)
            window: List[np.ndarray] = []
            if start < plen:
                window.append(act.req.prompt[start : min(end, plen)])
            if end > plen:
                window.append(
                    np.asarray(
                        act.generated[max(0, start - plen) : end - plen],
                        np.int32,
                    )
                )
            ctx[idx, :n] = (
                window[0]
                if len(window) == 1
                else np.concatenate(window)
            )
            starts[idx] = start
            n_ctx[idx] = n
            ptabs[idx] = act.seq.table(mp)
            temps[idx] = act.req.temperature
            seeds[idx] = act.req.seed
            top_ps[idx] = act.req.top_p
        args = (ctx, starts, n_ctx, ptabs, temps, seeds, top_ps)
        self._record_program("draft", self._draft_dev, g.k, *args)

        def dispatch():
            import jax

            return jax.block_until_ready(
                self._draft_jit(self._draft_dev, g.k, g.v, *args)
            )

        with _span("serve.draft", occupancy=len(ready)):
            g.k, g.v, out = run_with_retries(
                dispatch, what="serve.draft"
            )
        # no _charge_collectives: the draft program is replicated —
        # it runs no cross-chip gathers even under a TP mesh
        for idx, act in ready:
            act.draft_pos = int(starts[idx]) + int(n_ctx[idx])
        props = np.asarray(out)
        return {idx: props[idx] for idx, _ in ready}, caught_up

    def _spec_batch(self, ready: List[Tuple[int, _Active]]) -> None:
        """One SPECULATIVE step for the decode batch: draft proposals,
        one batched ``[max_slots, k + 1]`` verify dispatch, exact-match
        acceptance. Every emitted token is the TARGET's own sampled
        token (the verify program applies the decode rule at each
        absolute position), so streams stay byte-identical to solo
        non-speculative decode; the draft only decides how many
        positions one dispatch covers. Rejected speculative KV rolls
        back via length bookkeeping alone — positions past the accepted
        length are never read before the next step overwrites them."""
        s = self.max_slots
        kmax = self.draft_len
        c = kmax + 1
        t_draft0 = time.perf_counter()
        k_eff = {idx: self._spec_slot_k(act) for idx, act in ready}
        proposals, caught_up = self._draft_advance(ready)
        for idx, _ in ready:
            if idx not in caught_up:
                # the draft is still windowing this slot's backlog
                # (long prefill, preemption replay): decode plainly
                # this step — its proposals are mid-catch-up garbage
                k_eff[idx] = 0
        draft_wall = time.perf_counter() - t_draft0
        toks = np.zeros((s, c), np.int32)
        starts = np.zeros(s, np.int32)
        n_valid = np.ones(s, np.int32)
        ptabs = np.full(
            (s, self._max_pages), self.pool.trash_page, np.int32
        )
        temps = np.zeros(s, np.float32)
        seeds = np.zeros(s, np.int32)
        top_ps = np.ones(s, np.float32)
        for idx, act in ready:
            k = k_eff[idx]
            toks[idx, 0] = act.generated[-1]
            toks[idx, 1 : 1 + k] = proposals[idx][:k]
            starts[idx] = act.length - 1  # the pending token's position
            n_valid[idx] = k + 1
            ptabs[idx] = act.seq.table(self._max_pages)
            temps[idx] = act.req.temperature
            seeds[idx] = act.req.seed
            top_ps[idx] = act.req.top_p
        args = (toks, starts, n_valid, ptabs, temps, seeds, top_ps)
        pool = self.pool
        self._record_program("verify", self._params_dev, pool.k, *args)

        def dispatch():
            import jax

            _chaos.site("serve.verify")
            return jax.block_until_ready(
                self._verify_jit(self._params_dev, pool.k, pool.v, *args)
            )

        t0 = time.perf_counter()
        with _span("serve.verify", occupancy=len(ready)):
            pool.k, pool.v, u = run_with_retries(
                dispatch, what="serve.verify"
            )
        verify_wall = time.perf_counter() - t0
        _m_verify_s.observe(verify_wall)
        self._charge_collectives()
        u = np.asarray(u)
        t_roll0 = time.perf_counter()
        for idx, act in ready:
            k = k_eff[idx]
            target = u[idx]
            prop = proposals[idx]
            accept = 0
            while accept < k and int(prop[accept]) == int(target[accept]):
                accept += 1
            l0 = act.length
            if idx in caught_up:
                # draft KV stands for the accepted proposals the scan
                # wrote (t_1 .. t_{k-1}); everything past that rolls
                # back by this counter alone. Lagging slots keep the
                # window progress _draft_advance recorded instead.
                act.draft_pos = l0 + min(accept, kmax - 1)
            if act.spec_k < 0:
                act.spec_k = kmax
            if k > 0 and accept == k:
                act.spec_k = min(kmax, act.spec_k + 1)  # hot: grow
            elif k > 0 and accept * 2 < k:
                act.spec_k = max(1, act.spec_k - 1)  # cold: shrink
            self._spec_proposed += k
            self._spec_accepted += accept
            if k:
                _m_spec_proposed.inc(k)
            if accept:
                _m_spec_accepted.inc(accept)
            timings = act.req.handle.timings
            timings["draft_s"] = (
                timings.get("draft_s", 0.0) + draft_wall
            )
            timings["verify_s"] = (
                timings.get("verify_s", 0.0) + verify_wall
            )
            timings["spec_proposed"] = (
                timings.get("spec_proposed", 0) + k
            )
            timings["spec_accepted"] = (
                timings.get("spec_accepted", 0) + accept
            )
            timings["spec_rolled_back"] = (
                timings.get("spec_rolled_back", 0) + (k - accept)
            )
            spec_share = 1.0 / max(1, len(ready))
            self._charge_flops(timings, self._draft_jit, spec_share)
            self._charge_flops(timings, self._verify_jit, spec_share)
            # emit the target's tokens: the accepted run plus the
            # correction/bonus token — u[accept] is what solo decode
            # would have emitted at that position either way
            for j in range(accept + 1):
                self._emit(idx, act, int(target[j]))
                if self.scheduler.slots[idx] is not act:
                    break  # EOS or budget mid-burst: the rest is moot
        roll_wall = time.perf_counter() - t_roll0
        for idx, act in ready:
            if self.scheduler.slots[idx] is act:
                t = act.req.handle.timings
                t["rollback_s"] = t.get("rollback_s", 0.0) + roll_wall
        if self._spec_proposed:
            _m_spec_accept_rate.set(
                self._spec_accepted / self._spec_proposed,
                engine=self.name,
            )

    def _emit(self, idx: int, act: _Active, tok: int) -> None:
        now = time.monotonic()
        act.generated.append(tok)
        act.req.handle._emit(tok)
        _m_tokens.inc()
        if act.req.emitted == 0 and len(act.generated) == 1:
            _m_ttft.observe(now - act.req.submitted_at)
        elif act.last_emit_t is not None:
            _m_itl.observe(now - act.last_emit_t)
        if act.last_emit_t is not None:
            t = act.req.handle.timings
            t["decode_s"] = t.get("decode_s", 0.0) + now - act.last_emit_t
        act.last_emit_t = now
        eos = act.req.eos_id
        if (eos is not None and tok == eos) or act.remaining <= 0:
            self.scheduler.finish(idx)
            _m_requests.inc(status="completed")

    @staticmethod
    def _charge_flops(timings: dict, prog, share: float = 1.0) -> None:
        """Accumulate one dispatch's estimated FLOPs into a request's
        cost ledger: ``share`` of the program's ``ProgramRecord`` FLOP
        estimate (batched dispatches apportion equally over the
        requests the batch served). Silently zero until the program's
        first-dispatch cost estimate lands, and under ``TFT_OBS=0``."""
        rec = getattr(prog, "record", None)
        flops = getattr(rec, "flops", None) if rec is not None else None
        if flops:
            timings["est_flops"] = (
                timings.get("est_flops", 0.0) + float(flops) * share
            )

    def _account_request(self, act: _Active, error) -> None:
        """Scheduler finish hook: the request's terminal cost record
        (``obs/requests.py``), taken while the slot still holds its
        pages so holdings are countable. ``timings`` gets the same keys
        so the HTTP response echoes them."""
        req = act.req
        t = req.handle.timings
        t["tokens"] = req.emitted + len(act.generated)
        t["kv_pages"] = max(int(t.get("kv_pages", 0)), len(act.seq.pages))
        if req.tenant:
            t["tenant"] = req.tenant
        _obs_requests.record_request(
            request_id=req.request_id,
            engine=self.name,
            tenant=req.tenant,
            status="failed" if error is not None else "completed",
            tokens=t["tokens"],
            kv_pages=t["kv_pages"],
            prefix_cached_tokens=int(t.get("prefix_cached_tokens", 0)),
            spec_proposed=int(t.get("spec_proposed", 0)),
            spec_accepted=int(t.get("spec_accepted", 0)),
            est_flops=float(t.get("est_flops", 0.0)),
            queue_wait_s=t.get("queue_wait_s"),
            prefill_s=t.get("prefill_s"),
            decode_s=t.get("decode_s"),
        )

    def _refresh_gauges(self) -> None:
        _m_queue_depth.set(float(self.scheduler.queue_depth))
        _m_active_slots.set(
            float(sum(s is not None for s in self.scheduler.slots))
        )
        _m_pages_in_use.set(float(self.pool.pages_in_use))
        _m_pages_shared.set(float(self.pool.pages_shared))
        if _tenancy.enabled():
            _tenancy.update_active_gauge(self.scheduler.slots)

    def run_until_idle(self) -> None:
        """Drive :meth:`step` until queue and slots are empty (the
        synchronous mode — tests and batch jobs)."""
        while self.step():
            pass

    def defragment(self):
        """Compact live KV pages to the lowest pool indices between steps
        (page tables are rebuilt from the sequences every step, so the
        renumbering is transparent to in-flight generation). Returns the
        ``old -> new`` page remap (prefix-cache entries and pending
        copy-on-write donors are renumbered too). See
        :meth:`PagePool.defragment`."""
        with self._step_lock:
            return self._defragment_locked()

    # -- live slot migration (serve/tiers.py) ------------------------------

    def detach_slot(self, request_id: int, reason: str = "handoff"):
        """Serialize and remove one decode-phase slot for live
        migration: the slot's page rows (target + every page group)
        come back as a host :class:`~.tiers.SlotSnapshot`, its pages
        return to this pool, and its handle stays OPEN — the stream
        continues wherever :meth:`attach_slot` lands the snapshot.
        Returns ``None`` when the request is not currently migratable
        (unknown, queued, still prefilling). See ``serve/tiers.py``."""
        from . import tiers as _tiers

        return _tiers.export_slot(self, request_id, reason=reason)

    def attach_slot(self, snap, _handle_factory=None):
        """Adopt a migrated slot: allocate its page set, write the
        snapshot's rows (eager indexing like ``_apply_cow`` — zero new
        step programs), and seat it directly in decode phase. Returns
        the new handle (``_handle_factory`` substitutes the fleet's
        relay, exactly like :meth:`submit`). Raises
        :class:`~.tiers.TierMigrationError` /
        :class:`~.scheduler.QueueFullError` /
        :class:`~..utils.failures.PagePoolExhausted` with the engine
        untouched — the caller's fallback still owns the request."""
        from . import tiers as _tiers

        return _tiers.restore_slot(self, snap, _handle_factory=_handle_factory)

    # -- supervision -------------------------------------------------------

    def inject_fault(self, error: BaseException) -> None:
        """Queue a hard fault for the NEXT step: the stepping loop raises
        it at the step boundary and the supervisor fails every in-flight
        handle with it. This is how an external supervisor (the fleet
        router, ``serve/fleet.py``) kills a replica without racing a
        step in progress — calling :meth:`_fail_inflight` from another
        thread would contend with the step lock and could let the doomed
        engine keep emitting (or, after device-state corruption, emit
        WRONG bytes) until the contender wins. ``healthy`` flips now so
        ``submit`` sheds immediately; the drain lands within one step."""
        self.healthy = False
        self._poison = error
        with self.scheduler._lock:
            self.scheduler._lock.notify_all()  # wake an idle stepping loop

    def _fail_inflight(self, error: BaseException) -> None:
        """The fail-fast path: close EVERY in-flight handle (active slots
        and the whole admission queue) with the real error, NOW, and mark
        the engine unhealthy until :meth:`restart`. A consumer must see
        a doomed stream's failure within a step — never hang to its
        timeout against an engine that will not produce another token."""
        self.healthy = False
        reason = _fail_reason(error)
        with self._step_lock:
            n = self.scheduler.fail_all(error)
        if n:
            _m_requests.inc(n, status="failed")
            _m_handles_failed.inc(n, reason=reason)
        self._refresh_gauges()
        # the flight recorder's moment: every consumer has its error, so
        # snapshotting here cannot delay anyone — dump the black box
        _flight.record(
            "serve", "engine_fatal", reason=reason,
            error=f"{type(error).__name__}: {_first_line(error)}",
            handles_failed=n,
        )
        _flight.dump_bundle(
            "engine_fatal",
            health=self.health(),
            series_prefix="serve.",
            extra={
                "error_type": type(error).__name__,
                "error": str(error)[:2000],
                "handles_failed": n,
            },
        )

    def restart(self) -> "GenerationEngine":
        """Rebuild device state from host-side scheduler progress after a
        crash (lost pool arrays, a fatal step error). Every active
        sequence is preempted — its progress folds into its prompt, so
        re-admission re-prefills prompt + emitted tokens and the stream's
        emitted bytes stay identical — the page pool is re-zeroed, and
        the engine is marked healthy again. The compiled step programs
        survive (every shape is unchanged), so recovery adds zero
        recompiles: ``num_step_programs`` stays within its budget
        (<= 2, or <= 3 with chunked prefill / the prefix cache)."""
        if self._stop_wedged:
            # the old stepping thread never exited; flipping healthy here
            # would accept work nothing can step (start() still refuses
            # while _thread is set). stop() again to retry the join.
            raise RuntimeError(
                "cannot restart a wedged engine: the stepping thread "
                "never exited its stop join — stop() again to retry, or "
                "recycle the process"
            )
        with self._step_lock:
            # youngest-first so the OLDEST request ends up at the queue
            # front (each preempt requeues at the front) — re-admission
            # preserves the oldest-first service order
            for idx, _ in reversed(self.scheduler.active):
                self.scheduler.preempt(idx)
            self.pool.reset()
            if self.prefix_cache is not None:
                # the cached k/v died with the device state; reset()
                # already rebuilt the free list, so drop host entries
                # WITHOUT releasing pages
                self.prefix_cache.clear(free_pages=False)
            self._consecutive_ooms = 0
            self._poison = None  # a queued kill is moot on rebuilt state
            self.healthy = True
            self._last_step_t = time.monotonic()
        _m_restarts.inc()
        _flight.record(
            "serve", "engine_restart",
            requeued=self.scheduler.queue_depth,
        )
        _flight.dump_bundle(
            "engine_restart",
            health=self.health(),
            series_prefix="serve.",
            extra={"requeued": self.scheduler.queue_depth},
        )
        with self.scheduler._lock:
            self.scheduler._lock.notify_all()  # wake the stepping thread
        logger.warning(
            "engine restarted: device state rebuilt, %d request(s) "
            "requeued for recompute",
            self.scheduler.queue_depth,
        )
        return self

    def swap_weights(self, model) -> Dict[str, object]:
        """Hot weight swap: replace the served checkpoint in place.

        Weights enter every compiled step program as an ARGUMENT (the
        swap-safe design noted at construction), so swapping is one
        ``device_put`` plus a pointer flip under the step lock — **zero
        recompiles** (shapes and dtypes are validated identical, so the
        jit caches all hit) and zero dropped streams (in-flight
        sequences simply decode their next token under the new
        weights; the step between old and new is a clean boundary
        because the lock excludes a half-dispatched step).

        ``model`` is a :class:`~tensorframes_tpu.models.TransformerLM`
        or its raw params dict. A checkpoint whose tree structure,
        shapes, dtypes, or head count differ raises ``ValueError``
        *before* anything is touched — the rollout machinery
        (``serve/membership.py``) treats that exactly like a failed
        probe: roll back, halt the rollout. Returns the PREVIOUS params
        dict so callers can roll back with a second ``swap_weights``.
        Under tensor parallelism the new copy is sharded at rest with
        the same specs as the original (structure equality makes them
        reusable)."""
        import jax

        params = getattr(model, "params", model)
        if not isinstance(params, dict) or "blocks" not in params:
            raise ValueError(
                "swap_weights expects a TransformerLM or its params "
                f"dict; got {type(params).__name__}"
            )
        old = self._host_params
        if int(params.get("n_heads", 0)) != int(old.get("n_heads", 0)):
            raise ValueError(
                f"swap_weights: head count mismatch (served "
                f"{old.get('n_heads')}, checkpoint {params.get('n_heads')})"
            )
        new_host = {k: v for k, v in params.items() if k != "n_heads"}
        old_host = {k: v for k, v in old.items() if k != "n_heads"}

        def _sig(tree):
            return jax.tree.map(
                lambda a: (tuple(a.shape), str(np.dtype(a.dtype))), tree
            )

        if jax.tree.structure(new_host) != jax.tree.structure(old_host):
            raise ValueError(
                "swap_weights: checkpoint tree structure differs from "
                "the served weights — same architecture required for a "
                "hot swap"
            )
        if _sig(new_host) != _sig(old_host):
            raise ValueError(
                "swap_weights: checkpoint shapes/dtypes differ from the "
                "served weights — same shapes required (a shape change "
                "would recompile every step program; restart instead)"
            )
        if self.mesh is not None:
            from jax.sharding import NamedSharding

            dev = jax.device_put(
                new_host,
                jax.tree.map(
                    lambda s: NamedSharding(self.mesh, s),
                    self._tp_param_specs,
                    is_leaf=lambda x: not isinstance(x, (dict, list)),
                ),
            )
        else:
            dev = jax.device_put(new_host)
        with self._step_lock:
            self._params_dev = dev
            self._host_params = params
        _flight.record("serve", "weight_swap", engine=self.name)
        logger.info(
            "engine %s: weights hot-swapped (zero recompiles)", self.name
        )
        return old

    def health(self) -> Dict[str, object]:
        """Liveness snapshot for ``GET /healthz``: the last-step watchdog
        age, queue/batch/pool occupancy, and the unhealthy flags the
        supervisor and :meth:`stop` raise."""
        thread = self._thread
        return {
            "healthy": bool(self.healthy and not self._stop_wedged),
            "last_step_age_s": round(
                time.monotonic() - self._last_step_t, 3
            ),
            "queue_depth": self.scheduler.queue_depth,
            "active_slots": sum(
                s is not None for s in self.scheduler.slots
            ),
            "pages_in_use": self.pool.pages_in_use,
            "pages_capacity": self.pool.num_pages,
            "pages_shared": self.pool.pages_shared,
            # the CHOSEN perf knobs (page size may come from the
            # measured-best hint or a tuned winner — ISSUE 13): the
            # probe shows what this engine actually runs with
            "page_size": self.page_size,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            # tensor parallelism (serve/tp.py): degree 1 = solo. Under
            # TP each page spans the shards, so "per shard" pages equal
            # the pool's logical counts while the BYTES per chip are
            # the pool's divided by the degree — the capacity-scaling
            # view operators size HBM with (ISSUE 14)
            "tp_degree": self.tp_degree,
            "tp": (
                None
                if self.mesh is None
                else {
                    "degree": self.tp_degree,
                    "axis": self._tp_axis,
                    "pages_capacity": self.pool.num_pages,
                    "pages_in_use_per_shard": self.pool.pages_in_use,
                    "kv_bytes_per_shard": int(
                        (self.pool.k.nbytes + self.pool.v.nbytes)
                        // max(1, self.tp_degree)
                    ),
                    "collective_seconds_per_step_est": round(
                        self._collective_step_s, 6
                    ),
                    "collective_bytes_per_step_est": int(
                        self._collective_bytes_per_step
                    ),
                }
            ),
            "prefix_cache": (
                self.prefix_cache.stats()
                if self.prefix_cache is not None
                else None
            ),
            # speculative decoding (docs/serving_llm.md): None with no
            # draft model; the acceptance rate is the draft-length
            # controller's signal and the tuning cookbook's first read
            "speculative": (
                None
                if not self.draft_len
                else {
                    "draft_len": self.draft_len,
                    "proposed": self._spec_proposed,
                    "accepted": self._spec_accepted,
                    "acceptance_rate": round(
                        self._spec_accepted
                        / max(1, self._spec_proposed),
                        4,
                    ),
                }
            ),
            "stepping_thread_alive": (
                thread.is_alive() if thread is not None else None
            ),
            "stop_wedged": self._stop_wedged,
        }

    # -- background serving ------------------------------------------------

    def start(self) -> "GenerationEngine":
        """Step in a daemon thread until :meth:`stop` — the serving mode
        (pair with the scoring server's generate endpoint)."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._supervised_loop, daemon=True
        )
        self._thread.start()
        return self

    def _supervised_loop(self) -> None:
        """The serving loop under supervision. Recoverable failures never
        reach here (transient retries and OOM recovery live inside
        :meth:`step`); whatever does escape is terminal for the in-flight
        work, so every handle is failed promptly with the real error and
        the engine flips unhealthy (submit sheds, ``/healthz`` goes red)
        until :meth:`restart`. The loop itself keeps running either way —
        it never dies silently with streams still attached."""
        try:
            while not self._stop.is_set():
                try:
                    worked = self.step()
                except Exception as e:
                    # split, not splitlines: str(e) may be empty (bare
                    # asserts), and "".splitlines()[0] would kill the
                    # loop this handler exists to keep alive
                    logger.error(
                        "generation step failed terminally (%s); failing "
                        "all in-flight requests and marking the engine "
                        "unhealthy — restart() to recover",
                        f"{type(e).__name__}: "
                        + str(e).split("\n", 1)[0][:200],
                    )
                    self._fail_inflight(e)
                    worked = False
                if not worked:
                    with self.scheduler._lock:
                        if not self.scheduler._waiting:
                            self.scheduler._lock.wait(0.02)
        except BaseException as e:  # the supervisor must never die silently
            if not self._stop.is_set():
                logger.error("stepping thread died", exc_info=True)
                self._fail_inflight(e)
            raise

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        with self.scheduler._lock:
            self.scheduler._lock.notify_all()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # pretending the stop worked would hand the caller a zombie
            # stepping thread; surface it loudly, shed new work, and keep
            # the thread reference so a later stop() can retry the join
            logger.warning(
                "stepping thread did not stop within 10s (wedged device "
                "call?); engine marked unhealthy — stop() again to retry"
            )
            self._stop_wedged = True
            self.healthy = False
            return
        self._stop_wedged = False
        self._thread = None
        # anything still in flight will never get another step: fail the
        # handles now instead of stranding their consumers
        with self._step_lock:
            n = self.scheduler.fail_all(
                RuntimeError("engine stopped with the request in flight")
            )
        if n:
            _m_requests.inc(n, status="failed")
            _m_handles_failed.inc(n, reason="shutdown")
            logger.warning(
                "engine stopped with %d request(s) in flight; their "
                "handles were failed",
                n,
            )
            self._refresh_gauges()

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- convenience -------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        **kw,
    ) -> List[np.ndarray]:
        """Submit every prompt, run to completion, return each request's
        generated tokens (prompt excluded). Synchronous when no
        background thread is running."""
        handles = [self.submit(p, max_new_tokens, **kw) for p in prompts]
        if self._thread is None:
            self.run_until_idle()
        timeout = get_config().serve_result_timeout_s
        return [h.result(timeout=timeout) for h in handles]
