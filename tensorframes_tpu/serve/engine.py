"""GenerationEngine: compiled prefill/decode steps over the paged cache.

The serving counterpart of
:func:`~tensorframes_tpu.models.transformer_generate`: where that
function compiles one scan program per (batch shape, decode structure),
this engine compiles exactly TWO programs for a whole serving lifetime —

- **prefill** ``[1, max_seq_len]``: one right-padded prompt through the
  batched causal pass (:func:`~tensorframes_tpu.models.transformer_prefill`),
  its per-layer k/v scattered into the sequence's pages, the first token
  sampled from the last real position's logits;
- **decode** ``[max_slots]``: one token per occupied slot through the
  shared per-token step (:func:`~tensorframes_tpu.models.transformer_step`)
  with attention delegated to the paged read
  (:func:`~tensorframes_tpu.ops.paged_attention`).

Every input shape is static (page tables are fixed-width, idle slots
point at the trash page), so slot turnover, ragged lengths, and
greedy/sampled mixes all reuse the same two executables — the
no-recompile property the ROADMAP's heavy-traffic target needs. Sampling
parameters (temperature / seed / top_p) are per-request TRACED inputs;
``top_k`` is engine-level static structure, as in ``generate``.

Requests stream through :class:`~.scheduler.Scheduler` (bounded
admission, continuous batching, preempt-and-requeue on page-pool
exhaustion); each :meth:`submit` returns a
:class:`~.scheduler.GenerationHandle` whose iterator yields tokens as
steps complete. Observability: queue depth / batch occupancy /
pages-in-use gauges, time-to-first-token and inter-token latency
histograms, all on the PR-1 registry (``docs/observability.md``).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..models.transformer import (
    _kv_heads,
    filter_logits,
    transformer_prefill,
    transformer_step,
)
from ..obs import span as _span
from ..obs.metrics import (
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
)
from ..utils.logging import get_logger
from .kv_pages import PagePool, pages_needed
from .scheduler import (
    GenerationHandle,
    GenRequest,
    QueueFullError,
    Scheduler,
    _Active,
)

__all__ = ["GenerationEngine"]

logger = get_logger("serve.engine")

_m_queue_depth = _gauge(
    "serve.queue_depth", "Generation requests waiting for a decode slot"
)
_m_active_slots = _gauge(
    "serve.active_slots",
    "Decode-batch occupancy (sequences currently holding a slot)",
)
_m_pages_in_use = _gauge(
    "serve.pages_in_use", "KV pages currently owned by live sequences"
)
_m_pages_capacity = _gauge(
    "serve.pages_capacity", "Total KV pages in the pool"
)
_m_ttft = _histogram(
    "serve.ttft_seconds",
    "Time to first token: submit to first emission (seconds)",
)
_m_itl = _histogram(
    "serve.inter_token_seconds",
    "Inter-token latency per stream: gap between emissions (seconds)",
)
_m_tokens = _counter(
    "serve.tokens_total", "Tokens emitted across all generation streams"
)
_m_requests = _counter(
    "serve.requests_total",
    "Generation requests by terminal status",
    labels=("status",),
)


class GenerationEngine:
    """Continuous-batching generation over a :class:`PagePool`.

    >>> eng = GenerationEngine(lm, max_slots=8, page_size=16)
    >>> h = eng.submit(prompt_ids, max_new_tokens=64)
    >>> eng.start()              # background stepping (or drive .step())
    >>> for tok in h: ...        # stream
    >>> eng.stop()

    ``model`` is a :class:`~tensorframes_tpu.models.TransformerLM` or its
    params dict. ``max_seq_len`` bounds prompt + generation per request
    (default: the model's positional table). ``num_pages`` defaults to
    full-length pages for every slot (no preemption pressure); size it
    SMALLER to oversubscribe memory and lean on preempt-and-requeue.
    ``top_k`` is engine-static; temperature / ``top_p`` / seed are
    per-request."""

    def __init__(
        self,
        model,
        *,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: Optional[int] = None,
        max_seq_len: Optional[int] = None,
        queue_capacity: int = 64,
        top_k: int = 0,
        eos_id: Optional[int] = None,
        moe_top_k: int = 1,
    ):
        import jax

        params = getattr(model, "params", model)
        n_heads = params["n_heads"]
        d_model = int(np.shape(params["embed"])[1])
        hd = d_model // n_heads
        n_kv = _kv_heads(params["blocks"][0], d_model, n_heads)
        model_max = int(np.shape(params["pos"])[0])
        self.max_seq_len = int(max_seq_len or model_max)
        if self.max_seq_len > model_max:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"positional table ({model_max})"
            )
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self._max_pages = pages_needed(self.max_seq_len, self.page_size)
        if num_pages is None:
            num_pages = self.max_slots * self._max_pages
        self.pool = PagePool(
            n_layers=len(params["blocks"]),
            n_kv_heads=n_kv,
            head_dim=hd,
            num_pages=num_pages,
            page_size=self.page_size,
        )
        self.scheduler = Scheduler(
            self.pool, self.max_slots, queue_capacity, self.max_seq_len
        )
        self.top_k = int(top_k)
        self.eos_id = eos_id
        self._d_model = d_model
        # weights enter the compiled steps as an ARGUMENT (swap-safe, like
        # TransformerLM.generate); one device copy held for the lifetime
        self._host_params = params
        self._params_dev = jax.device_put(
            {k: v for k, v in params.items() if k != "n_heads"}
        )
        # donation halves pool traffic on real chips; CPU jax warns and
        # ignores it, so only request it where it works
        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        self._prefill_jit = jax.jit(
            self._prefill_impl(n_heads, moe_top_k), donate_argnums=donate
        )
        self._decode_jit = jax.jit(
            self._decode_impl(n_heads, moe_top_k), donate_argnums=donate
        )
        #: distinct (name, abstract input signature) pairs dispatched —
        #: jit keys compiles on exactly this, so its length IS the number
        #: of compiled step programs
        self.program_signatures: set = set()
        self._req_counter = 0
        self._submit_lock = threading.Lock()
        self._step_lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        _m_pages_capacity.set(float(num_pages))

    # -- compiled step builders -------------------------------------------

    def _prefill_impl(self, n_heads: int, moe_top_k: int):
        import jax
        import jax.numpy as jnp

        ps = self.page_size
        trash = self.pool.trash_page
        top_k = self.top_k

        def prefill(p, kp, vp, prompt, length, ptab, temp, seed, top_p):
            full = {**p, "n_heads": n_heads}
            logits, kc, vc = transformer_prefill(
                full, prompt, moe_top_k=moe_top_k
            )
            # [L, 1, n_kv, Pmax, hd] -> [L, Pmax, n_kv, hd]; positions
            # past the real prompt scatter into the trash page
            k_all = kc[:, 0].transpose(0, 2, 1, 3)
            v_all = vc[:, 0].transpose(0, 2, 1, 3)
            pos = jnp.arange(prompt.shape[1])
            page = jnp.where(pos < length, ptab[pos // ps], trash)
            off = pos % ps
            kp = kp.at[:, page, off].set(k_all)
            vp = vp.at[:, page, off].set(v_all)
            last = logits[0, length - 1]
            greedy = jnp.argmax(last, axis=-1)
            # sampled path mirrors generate: per-step key folded at the
            # emitting position, filter_logits truncation, categorical
            key = jax.random.fold_in(jax.random.PRNGKey(seed), length - 1)
            scaled = last[None] / jnp.maximum(
                jnp.asarray(temp, jnp.float32), 1e-6
            )
            filt = filter_logits(scaled, top_k=top_k, top_p=top_p)
            sampled = jax.random.categorical(key, filt, axis=-1)[0]
            tok = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
            return kp, vp, tok

        return prefill

    def _decode_impl(self, n_heads: int, moe_top_k: int):
        import jax
        import jax.numpy as jnp

        from ..ops import paged_attention

        ps = self.page_size
        d_model = self._d_model
        top_k = self.top_k

        def decode(p, kp, vp, toks, positions, ptabs, temps, seeds, top_ps):
            full = {**p, "n_heads": n_heads}
            slots = toks.shape[0]
            state = [kp, vp]

            def attend(li, q, k, v):
                # write this token's k/v into its page, then read the
                # whole visible history through the page table
                page = ptabs[jnp.arange(slots), positions // ps]
                off = positions % ps
                state[0] = state[0].at[li, page, off].set(k)
                state[1] = state[1].at[li, page, off].set(v)
                ctx = paged_attention(
                    q, state[0][li], state[1][li], ptabs, positions + 1
                )
                return ctx.reshape(slots, d_model)

            logits = transformer_step(
                full, toks, positions, attend, moe_top_k=moe_top_k
            )
            greedy = jnp.argmax(logits, axis=-1)
            keys = jax.vmap(
                lambda s, t: jax.random.fold_in(jax.random.PRNGKey(s), t)
            )(seeds, positions)
            scaled = logits / jnp.maximum(temps[:, None], 1e-6)
            filt = filter_logits(scaled, top_k=top_k, top_p=top_ps[:, None])
            sampled = jax.vmap(jax.random.categorical)(keys, filt)
            nxt = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
            return state[0], state[1], nxt

        return decode

    def _record_program(self, name: str, *args) -> None:
        sig: List = [name]
        for a in args:
            if isinstance(a, dict):
                sig.append("params")
            else:
                arr = np.asarray(a) if np.isscalar(a) else a
                sig.append((tuple(arr.shape), str(arr.dtype)))
        self.program_signatures.add(tuple(sig))

    @property
    def num_step_programs(self) -> int:
        """Distinct compiled step programs dispatched so far (jit keys on
        the abstract input signature; static shapes keep this at <= 2:
        one prefill + one decode)."""
        return len(self.program_signatures)

    # -- submission --------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = 0.0,
        top_p: float = 1.0,
        seed: int = 0,
        eos_id: Optional[int] = None,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> GenerationHandle:
        """Queue one generation request; returns its streaming handle.
        Raises ``ValueError`` for requests that could never be scheduled
        and :class:`~.scheduler.QueueFullError` when the bounded queue is
        full and ``block=False``."""
        prompt = np.asarray(prompt, np.int32).ravel()
        if prompt.size < 1:
            _m_requests.inc(status="rejected")
            raise ValueError("prompt needs at least one token")
        if max_new_tokens < 1:
            _m_requests.inc(status="rejected")
            raise ValueError(
                f"max_new_tokens must be >= 1; got {max_new_tokens}"
            )
        with self._submit_lock:
            self._req_counter += 1
            rid = self._req_counter
        handle = GenerationHandle(rid)
        req = GenRequest(
            request_id=rid,
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_p=float(top_p),
            seed=int(seed),
            eos_id=self.eos_id if eos_id is None else eos_id,
            handle=handle,
        )
        try:
            self.scheduler.submit(req, block=block, timeout=timeout)
        except (ValueError, QueueFullError):
            # both are terminal rejections from the caller's view —
            # infeasible shape and queue backpressure alike must keep
            # completed + failed + rejected == submissions
            _m_requests.inc(status="rejected")
            raise
        _m_queue_depth.set(float(self.scheduler.queue_depth))
        return handle

    # -- stepping ----------------------------------------------------------

    def step(self) -> bool:
        """One scheduler iteration: admit + prefill newcomers, grow pages
        (preempting on exhaustion), one decode step for the batch.
        Returns whether work remains. Exceptions from the device fail the
        affected requests' handles and re-raise."""
        with self._step_lock:
            prefill_err: Optional[BaseException] = None
            for idx, act in self.scheduler.admit():
                try:
                    self._prefill_one(idx, act)
                except Exception as e:
                    # fail THIS request only and keep admitting: aborting
                    # mid-loop would leave later-admitted slots with no
                    # prefill (empty ``generated``), poisoning the next
                    # decode batch
                    self.scheduler.finish(idx, error=e)
                    _m_requests.inc(status="failed")
                    if prefill_err is None:
                        prefill_err = e
            if prefill_err is not None:
                # every surviving slot is prefilled; propagate now, before
                # decode, so synchronous drivers see the device error
                self._refresh_gauges()
                raise prefill_err
            batch = self.scheduler.active
            if batch:
                ready: List[Tuple[int, _Active]] = []
                for idx, act in batch:
                    if self.scheduler.slots[idx] is not act:
                        continue  # preempted as a victim already
                    if self.scheduler.grow(idx):
                        ready.append((idx, act))
                # growth for a later slot may have evicted an earlier one
                ready = [
                    (i, a) for i, a in ready if self.scheduler.slots[i] is a
                ]
                if ready:
                    try:
                        self._decode_batch(ready)
                    except Exception as e:
                        for i, _ in ready:
                            if self.scheduler.slots[i] is not None:
                                self.scheduler.finish(i, error=e)
                                _m_requests.inc(status="failed")
                        raise
            self._refresh_gauges()
            return self.scheduler.has_work()

    def _prefill_one(self, idx: int, act: _Active) -> None:
        req = act.req
        plen = len(req.prompt)
        prompt_row = np.zeros((1, self.max_seq_len), np.int32)
        prompt_row[0, :plen] = req.prompt
        ptab = act.seq.table(self._max_pages)
        args = (
            prompt_row,
            np.int32(plen),
            ptab,
            np.float32(req.temperature),
            np.int32(req.seed),
            np.float32(req.top_p),
        )
        pool = self.pool
        self._record_program("prefill", self._params_dev, pool.k, *args)
        with _span("serve.prefill", request=req.request_id, prompt_len=plen):
            pool.k, pool.v, tok = self._prefill_jit(
                self._params_dev, pool.k, pool.v, *args
            )
        self._emit(idx, act, int(tok))

    def _decode_batch(self, ready: List[Tuple[int, _Active]]) -> None:
        s = self.max_slots
        toks = np.zeros(s, np.int32)
        positions = np.zeros(s, np.int32)
        ptabs = np.full(
            (s, self._max_pages), self.pool.trash_page, np.int32
        )
        temps = np.zeros(s, np.float32)
        seeds = np.zeros(s, np.int32)
        top_ps = np.ones(s, np.float32)
        for idx, act in ready:
            toks[idx] = act.generated[-1]
            positions[idx] = act.length - 1  # this token's write position
            ptabs[idx] = act.seq.table(self._max_pages)
            temps[idx] = act.req.temperature
            seeds[idx] = act.req.seed
            top_ps[idx] = act.req.top_p
        args = (toks, positions, ptabs, temps, seeds, top_ps)
        pool = self.pool
        self._record_program("decode", self._params_dev, pool.k, *args)
        with _span("serve.decode_step", occupancy=len(ready)):
            pool.k, pool.v, nxt = self._decode_jit(
                self._params_dev, pool.k, pool.v, *args
            )
        nxt = np.asarray(nxt)
        for idx, act in ready:
            self._emit(idx, act, int(nxt[idx]))

    def _emit(self, idx: int, act: _Active, tok: int) -> None:
        now = time.monotonic()
        act.generated.append(tok)
        act.req.handle._emit(tok)
        _m_tokens.inc()
        if act.req.emitted == 0 and len(act.generated) == 1:
            _m_ttft.observe(now - act.req.submitted_at)
        elif act.last_emit_t is not None:
            _m_itl.observe(now - act.last_emit_t)
        act.last_emit_t = now
        eos = act.req.eos_id
        if (eos is not None and tok == eos) or act.remaining <= 0:
            self.scheduler.finish(idx)
            _m_requests.inc(status="completed")

    def _refresh_gauges(self) -> None:
        _m_queue_depth.set(float(self.scheduler.queue_depth))
        _m_active_slots.set(
            float(sum(s is not None for s in self.scheduler.slots))
        )
        _m_pages_in_use.set(float(self.pool.pages_in_use))

    def run_until_idle(self) -> None:
        """Drive :meth:`step` until queue and slots are empty (the
        synchronous mode — tests and batch jobs)."""
        while self.step():
            pass

    def defragment(self):
        """Compact live KV pages to the lowest pool indices between steps
        (page tables are rebuilt from the sequences every step, so the
        renumbering is transparent to in-flight generation). Returns the
        ``old -> new`` page remap. See :meth:`PagePool.defragment`."""
        with self._step_lock:
            return self.pool.defragment(
                [a.seq for _, a in self.scheduler.active]
            )

    # -- background serving ------------------------------------------------

    def start(self) -> "GenerationEngine":
        """Step in a daemon thread until :meth:`stop` — the serving mode
        (pair with the scoring server's generate endpoint)."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    worked = self.step()
                except Exception:
                    logger.warning(
                        "generation step failed", exc_info=True
                    )
                    worked = True  # the failed batch was cleared; go on
                if not worked:
                    with self.scheduler._lock:
                        if not self.scheduler._waiting:
                            self.scheduler._lock.wait(0.02)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        with self.scheduler._lock:
            self.scheduler._lock.notify_all()
        self._thread.join(timeout=10)
        self._thread = None

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- convenience -------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        **kw,
    ) -> List[np.ndarray]:
        """Submit every prompt, run to completion, return each request's
        generated tokens (prompt excluded). Synchronous when no
        background thread is running."""
        handles = [self.submit(p, max_new_tokens, **kw) for p in prompts]
        if self._thread is None:
            self.run_until_idle()
        return [h.result(timeout=300) for h in handles]
