"""Router high availability: durable request WAL + fenced standby takeover.

PR 18 (``serve/membership.py``) made *members* survive kill -9 with
byte-identical failover replay; this module does the same for the
ROUTER, the serving plane's last single point of failure. Three pieces,
all built on primitives the repo already has:

- **Request WAL** (:class:`RequestWAL`) — an append-only per-request
  journal in the shared fleet directory (the same directory the
  membership leases live in, the same append-only + torn-tail-tolerant
  discipline as ``engine/jobs.py``'s BlockLedger). It records each
  request's ADMISSION (prompt, sampling params, tenant, session,
  traceparent, and the client-supplied idempotent ``request_id``) and a
  delivered-token WATERMARK. Every write happens off the relay path —
  a per-request pump thread feeds an in-process tracker entry, and one
  background writer thread batches journal appends — so the token hot
  loop stays ~free; the whole plane is additionally gated zero-cost-off
  by ``Config.router_wal`` (the tenancy/chaos module-global pattern).

- **Resumable streams** — the tracker entry is what
  ``interop/serving.py`` streams from when a ``request_id`` is
  supplied: a duplicate submit dedupes against it instead of
  double-generating, and a disconnected client reconnects with
  ``request_id`` + ``from=<offset>`` to get the already-delivered
  prefix replayed followed by the live tail, byte-identical to the
  uninterrupted stream.

- **Fenced standby takeover** (:class:`RouterHA`) — routers elect an
  active via an epoch-fenced lease on the shared directory (exactly
  the ``MemberRegistry`` fencing pattern, key :data:`ROUTER_LEASE_KEY`
  — filtered out of member scans). A standby detects lease expiry,
  wins epoch+1, rebuilds in-flight state from the WAL, and resubmits
  unfinished requests recompute-style through
  ``Fleet.submit(_resume_tokens=...)``: the delivered watermark folds
  into the prompt and per-step sampling keys fold at their absolute
  positions, so resumed streams are byte-identical. Members learn the
  current router epoch from the same lease file and reject a zombie
  router's stale-epoch placements
  (:class:`~tensorframes_tpu.utils.failures.StaleRouterEpochError`).
  A router that LOSES the lease deliberately keeps its stale
  ``fleet.router_epoch`` — its late placements must carry the
  superseded epoch so the rejection fires.

Chaos sites: ``fleet.router_wal`` (journal flush — ``transient``
retries invisibly, ``latency`` lags the watermark, which only means a
takeover replays a little more, still byte-identical) and
``fleet.router_heartbeat`` (the election tick — ``latency`` past the
TTL is the takeover drill). See docs/fault_tolerance.md "Router HA".
"""

from __future__ import annotations

import json
import os
import queue
import re
import socket
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..obs import flight as _flight
from ..obs.metrics import counter as _counter
from ..utils import chaos as _chaos
from ..utils.config import get_config, register_on_change
from ..utils.failures import first_line as _first_line, run_with_retries
from ..utils.leases import LeaseStore
from ..utils.logging import get_logger

__all__ = [
    "ROUTER_LEASE_KEY",
    "RequestWAL",
    "RouterHA",
    "attach_router_ha",
    "enabled",
    "router_epoch_from",
]

logger = get_logger("serve.router_ha")

#: the router-election lease's key in the shared directory — a RESERVED
#: name the membership sync skips, so the election lease is never
#: mistaken for a serving member
ROUTER_LEASE_KEY = "router"

_m_takeovers = _counter(
    "fleet.router_takeovers_total",
    "Router activations at epoch > 0: a standby (or restarted router) "
    "won the election lease past a previous incarnation and rebuilt "
    "in-flight state from the request WAL",
)
_m_wal_records = _counter(
    "fleet.wal_records_total",
    "Records appended to the router's request WAL, by event "
    "(admit / tok / done / err)",
    labels=("event",),
)

# -- the zero-cost-off gate (the tenancy/chaos module-global pattern) ------

_ON = False


def _refresh() -> None:
    global _ON
    _ON = bool(get_config().router_wal)


register_on_change(_refresh)


def enabled() -> bool:
    """Whether the durable request plane is on (``Config.router_wal``)."""
    return _ON


#: ledger filename per router incarnation: the election epoch makes the
#: name unique, so two incarnations can never interleave appends in one
#: file and a torn tail is always the LAST line of exactly one file
_LEDGER_RE = re.compile(r"^wal\.e(\d+)\.jsonl$")

#: tracker-entry table bound: beyond this many entries the oldest
#: COMPLETED entries are forgotten (dedupe/resume of a long-finished
#: request degrades to a fresh admission — an optimization bound, not a
#: correctness one; the journal itself keeps every record)
_MAX_ENTRIES = 8192


class _WalEntry:
    """One tracked request: the in-process twin of its WAL records —
    what resumable streams are served from. ``tokens`` grows under
    ``cond``; ``done``/``error`` settle exactly once."""

    __slots__ = (
        "rid", "record", "tokens", "done", "error", "cond", "handle",
        "created_t",
    )

    def __init__(self, rid: str, record: Dict[str, Any]):
        self.rid = rid
        self.record = record
        self.tokens: List[int] = []
        self.done = False
        self.error: Optional[Tuple[str, str]] = None  # (kind, message)
        self.cond = threading.Condition()
        self.handle = None
        self.created_t = time.monotonic()

    def wait(
        self, cursor: int, timeout_s: float
    ) -> Optional[Tuple[List[int], bool, Optional[Tuple[str, str]]]]:
        """Block until tokens beyond ``cursor`` exist or the entry is
        terminal; returns ``(new_tokens, done, error)`` or ``None`` on
        timeout."""
        deadline = time.monotonic() + timeout_s
        with self.cond:
            while len(self.tokens) <= cursor and not self.done:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return None
                self.cond.wait(rem)
            return list(self.tokens[cursor:]), self.done, self.error


class RequestWAL:
    """The append-only per-request journal plus its in-process tracker.

    One JSONL ledger per router incarnation
    (``<path>/wal/wal.e<epoch>.jsonl``), records::

        {"e": "admit", "rid", "rec": {prompt, max_new, temperature,
         top_p, seed, eos_id, session, tenant, trace, deadline_s}}
        {"e": "tok",   "rid", "off": <absolute offset>, "t": [ids]}
        {"e": "done",  "rid", "n": <tokens total>}
        {"e": "err",   "rid", "kind", "msg"}

    Appends ride a background writer thread (batched, fsynced, chaos
    site ``fleet.router_wal`` inside a transient-retry window) so the
    relay hot loop never touches the disk. Because replay is
    byte-identical, token records from DIFFERENT router epochs agree
    wherever their offsets overlap — recovery merges ledgers by setting
    tokens at absolute offsets, and duplicates are harmless. A torn
    last line (the crash artifact append-only files allow) is skipped
    on load, exactly the BlockLedger discipline."""

    def __init__(self, path: str, router_id: str):
        self.dir = os.path.join(path, "wal")
        self.router_id = router_id
        self._entries: "OrderedDict[str, _WalEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._writer: Optional[threading.Thread] = None
        self._file = None
        self._ledger: Optional[str] = None
        self.epoch: Optional[int] = None
        self.records_written = 0

    # -- lifecycle ---------------------------------------------------------

    def open(self, epoch: int) -> None:
        """Start journaling into this incarnation's ledger."""
        os.makedirs(self.dir, exist_ok=True)
        self.epoch = int(epoch)
        if self._file is not None:
            # re-activation at a later epoch: appends go to the NEW
            # incarnation's ledger from here on
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        self._ledger = os.path.join(
            self.dir, f"wal.e{int(epoch):06d}.jsonl"
        )
        if self._writer is None or not self._writer.is_alive():
            self._stop.clear()
            self._writer = threading.Thread(
                target=self._write_loop,
                name=f"tft-router-wal-{self.router_id}",
                daemon=True,
            )
            self._writer.start()

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)  # wake the writer for its final drain
        w = self._writer
        if w is not None:
            w.join(timeout=5.0)
        self._writer = None
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None

    # -- the tracker -------------------------------------------------------

    def lookup(self, rid: str) -> Optional[_WalEntry]:
        with self._lock:
            return self._entries.get(str(rid))

    def admit(
        self, rid: str, record: Dict[str, Any]
    ) -> Tuple[_WalEntry, bool]:
        """Check-and-create for one request id: returns ``(entry,
        created)``. ``created=False`` means a duplicate submit (or a
        reconnect) — the caller serves from the existing entry instead
        of generating again."""
        rid = str(rid)
        with self._lock:
            cur = self._entries.get(rid)
            if cur is not None:
                return cur, False
            entry = _WalEntry(rid, dict(record))
            self._entries[rid] = entry
            self._evict_done_locked()
        self._append({"e": "admit", "rid": rid, "rec": dict(record)})
        return entry, True

    def admit_recovered(
        self,
        rid: str,
        record: Dict[str, Any],
        tokens: List[int],
        done: bool,
        error: Optional[Tuple[str, str]],
    ) -> _WalEntry:
        """Rebuild one request's entry from recovered ledgers, and
        snapshot it into THIS incarnation's ledger so each epoch's file
        is self-contained (old files become garbage-collectable once a
        takeover has re-journaled them)."""
        rid = str(rid)
        entry = _WalEntry(rid, dict(record))
        entry.tokens = [int(t) for t in tokens]
        entry.done = bool(done)
        entry.error = error
        with self._lock:
            self._entries[rid] = entry
            self._evict_done_locked()
        self._append({"e": "admit", "rid": rid, "rec": dict(record)})
        if entry.tokens:
            self._append(
                {"e": "tok", "rid": rid, "off": 0, "t": list(entry.tokens)}
            )
        if error is not None:
            self._append(
                {"e": "err", "rid": rid, "kind": error[0], "msg": error[1]}
            )
        elif done:
            self._append({"e": "done", "rid": rid, "n": len(entry.tokens)})
        return entry

    def _evict_done_locked(self) -> None:
        while len(self._entries) > _MAX_ENTRIES:
            victim = None
            for key, e in self._entries.items():
                if e.done:
                    victim = key
                    break
            if victim is None:
                return  # every entry is live; never evict one mid-stream
            del self._entries[victim]

    def bind(self, entry: _WalEntry, handle) -> None:
        """Attach a live engine handle to the entry and start its pump:
        a daemon thread draining the handle's token queue into the
        tracker (and the journal). The pump OWNS the handle's queue —
        the serving layer streams from the entry, never the queue."""
        entry.handle = handle
        threading.Thread(
            target=self._pump,
            args=(entry, handle),
            name=f"tft-router-wal-pump-{entry.rid}",
            daemon=True,
        ).start()

    def fail(self, rid: str, exc: BaseException) -> None:
        """Settle an entry with an error without a live handle (e.g. a
        takeover resubmission the fleet refused)."""
        entry = self.lookup(rid)
        if entry is None:
            return
        self._settle(entry, (type(exc).__name__, _first_line(exc)))

    def forget(self, rid: str, exc: BaseException) -> None:
        """Drop a REFUSED admission (429/503/400 before any token):
        journals the refusal so a takeover never resubmits work the
        admission gate rejected, then frees the id — a client retry
        with the same ``request_id`` re-admits fresh instead of
        deduping against a dead entry."""
        rid = str(rid)
        with self._lock:
            self._entries.pop(rid, None)
        self._append(
            {
                "e": "err", "rid": rid,
                "kind": type(exc).__name__, "msg": _first_line(exc),
            }
        )

    def _pump(self, entry: _WalEntry, handle) -> None:
        timeout_s = get_config().serve_result_timeout_s
        while True:
            try:
                item = handle._q.get(timeout=timeout_s)
            except queue.Empty:
                self._settle(
                    entry,
                    ("TimeoutError", f"no emission within {timeout_s}s"),
                )
                return
            if item is handle._DONE:
                err = handle.error
                self._settle(
                    entry,
                    None
                    if err is None
                    else (type(err).__name__, _first_line(err)),
                )
                return
            with entry.cond:
                off = len(entry.tokens)
                entry.tokens.append(int(item))
                entry.cond.notify_all()
            self._append(
                {"e": "tok", "rid": entry.rid, "off": off, "t": [int(item)]}
            )

    def _settle(
        self, entry: _WalEntry, error: Optional[Tuple[str, str]]
    ) -> None:
        with entry.cond:
            if entry.done:
                return
            entry.done = True
            entry.error = error
            entry.cond.notify_all()
        if error is None:
            self._append(
                {"e": "done", "rid": entry.rid, "n": len(entry.tokens)}
            )
        else:
            self._append(
                {
                    "e": "err", "rid": entry.rid,
                    "kind": error[0], "msg": error[1],
                }
            )

    # -- the journal -------------------------------------------------------

    def _append(self, rec: Dict[str, Any]) -> None:
        self._q.put(rec)

    def _write_loop(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [] if item is None else [item]
            while True:  # drain whatever accumulated behind it
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is not None:
                    batch.append(nxt)
            if batch:
                try:
                    run_with_retries(
                        lambda: self._flush(batch), what="fleet.router_wal"
                    )
                except Exception:
                    # durability degraded (disk gone, fatal chaos) —
                    # never let the journal take serving down with it;
                    # a takeover simply replays more from the prompt
                    logger.warning(
                        "router_ha: WAL flush failed; %d record(s) "
                        "dropped", len(batch), exc_info=True,
                    )
            if self._stop.is_set() and self._q.empty():
                return

    def _flush(self, batch: List[Dict[str, Any]]) -> None:
        _chaos.site("fleet.router_wal")
        if self._file is None:
            self._file = open(self._ledger, "ab")
        payload = b"".join(
            json.dumps(rec, separators=(",", ":")).encode("utf-8") + b"\n"
            for rec in batch
        )
        self._file.write(payload)
        self._file.flush()
        os.fsync(self._file.fileno())
        self.records_written += len(batch)
        for rec in batch:
            _m_wal_records.inc(event=str(rec.get("e", "?")))

    # -- recovery ----------------------------------------------------------

    def recover(self) -> Dict[str, Dict[str, Any]]:
        """Merge every PREVIOUS incarnation's ledger into per-request
        state: ``{rid: {record, tokens, done, error}}``. Token records
        are applied at their absolute offsets — overlapping records
        from different epochs are identical by the byte-identity
        guarantee, so duplicates are no-ops and the merged watermark is
        the max across ledgers. Undecodable lines (the torn tail a
        kill -9 mid-append leaves) are skipped."""
        state: Dict[str, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return state
        ledgers = []
        for name in names:
            m = _LEDGER_RE.match(name)
            if m is None:
                continue
            epoch = int(m.group(1))
            if self.epoch is not None and epoch >= self.epoch:
                continue  # our own (or a future) ledger, not history
            ledgers.append((epoch, os.path.join(self.dir, name)))
        for _, path in sorted(ledgers):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError:
                continue
            for line in raw.splitlines():
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line.decode("utf-8"))
                except ValueError:
                    continue  # torn tail / crash artifact
                if not isinstance(rec, dict):
                    continue
                rid = str(rec.get("rid", ""))
                ev = rec.get("e")
                if ev == "admit":
                    cur = state.get(rid)
                    if cur is None or cur["done"]:
                        # a re-admission AFTER a settled outcome is a
                        # client retry of a refused/failed id (forget()
                        # freed it): the retry's lifecycle replaces the
                        # stale one instead of merging into it
                        state[rid] = {
                            "record": dict(rec.get("rec") or {}),
                            "tokens": [], "done": False, "error": None,
                        }
                    continue
                st = state.get(rid)
                if st is None:
                    continue  # records for an admission we never saw
                if ev == "tok":
                    toks = st["tokens"]
                    off = int(rec.get("off", 0))
                    for i, t in enumerate(rec.get("t") or []):
                        pos = off + i
                        if pos < len(toks):
                            continue  # overlap: identical by replay
                        if pos == len(toks):
                            toks.append(int(t))
                        else:
                            break  # a gap — trust only the contiguous prefix
                elif ev == "done":
                    st["done"] = True
                elif ev == "err":
                    st["done"] = True
                    st["error"] = (
                        str(rec.get("kind", "RuntimeError")),
                        str(rec.get("msg", "")),
                    )
        return state

    def statusz_view(self) -> Dict[str, Any]:
        with self._lock:
            entries = len(self._entries)
            live = sum(1 for e in self._entries.values() if not e.done)
        return {
            "dir": self.dir,
            "epoch": self.epoch,
            "entries": entries,
            "live": live,
            "records_written": self.records_written,
            "queue_depth": self._q.qsize(),
        }


def router_epoch_from(
    store: LeaseStore, cache_s: float = 0.25
) -> "Any":
    """Build a cached ``() -> Optional[int]`` reading the router
    election lease's current epoch from ``store``'s directory — the
    member-side half of zombie-router fencing (``interop/serving.py``
    compares it against the placement's ``x-router-epoch`` header).
    Cached for ``cache_s`` so the per-request cost is a clock read, and
    degrades to ``None`` (no fencing) when the lease is unreadable —
    a broken shared filesystem must not reject live traffic."""
    state = {"t": -1e9, "epoch": None}
    lock = threading.Lock()

    def current() -> Optional[int]:
        now = time.monotonic()
        with lock:
            if now - state["t"] < cache_s:
                return state["epoch"]
            state["t"] = now
        try:
            view = store._scan(ROUTER_LEASE_KEY)
            epoch = None if view is None else int(view.epoch)
        except Exception:
            epoch = None
        with lock:
            state["epoch"] = epoch
        return epoch

    return current


class RouterHA:
    """One router process's election + takeover state machine.

    Rides the fleet's watchdog tick (:meth:`tick` is a tick hook): the
    ACTIVE router holds the election lease (key
    :data:`ROUTER_LEASE_KEY`) with the lease store's own heartbeat
    renewing it; a STANDBY polls ``acquire()`` — which only succeeds
    once the active's lease has EXPIRED — and wins at epoch+1. Winning
    at epoch > 0 is a takeover: the WAL's previous-incarnation ledgers
    are merged, finished requests become servable (resume of a
    completed stream replays from the journal), and unfinished ones are
    resubmitted through ``Fleet.submit(_resume_tokens=...)`` — the
    delivered watermark folds into the prompt, so the stream continues
    byte-identically from the next undelivered position.

    Losing the lease (the heartbeat's ``on_lost``) demotes to a FENCED
    zombie: admission stops (serving answers 503 while
    :attr:`active` is False), and ``fleet.router_epoch`` deliberately
    keeps the superseded epoch so any in-flight placement is rejected
    member-side."""

    def __init__(
        self,
        fleet,
        path: str,
        *,
        name: Optional[str] = None,
        ttl_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
    ):
        cfg = get_config()
        self.fleet = fleet
        self.name = name or (
            f"router-{socket.gethostname()}-{os.getpid()}"
        )
        ttl = float(
            cfg.router_lease_ttl_s if ttl_s is None else ttl_s
        )
        self.store = LeaseStore(
            path,
            worker_id=self.name,
            ttl_s=ttl,
            heartbeat_s=0.0 if heartbeat_s is None else float(heartbeat_s),
        )
        self.store.on_lost = self._on_lease_lost
        self.wal = RequestWAL(path, router_id=self.name)
        self.active = False
        self.fenced = False
        self.epoch: Optional[int] = None
        self.resumed_requests = 0
        self._interval = max(0.05, ttl / 3.0)
        self._last_tick = -1e9
        self._lock = threading.Lock()
        self._taking_over = False

    # -- election ----------------------------------------------------------

    def tick(self) -> None:
        """The election heartbeat, run on the fleet watchdog tick
        (rate-limited to a third of the TTL). The ACTIVE router's lease
        renewal rides the store's own heartbeat thread; this tick only
        campaigns while standby/fenced."""
        now = time.monotonic()
        if now - self._last_tick < self._interval:
            return
        self._last_tick = now
        _chaos.site("fleet.router_heartbeat")
        with self._lock:
            if self.active or self._taking_over:
                return
        epoch = self.store.acquire(
            ROUTER_LEASE_KEY, meta={"router": self.name}
        )
        if epoch is None:
            return
        with self._lock:
            self._taking_over = True
        # recovery + resubmission off the watchdog thread: a takeover
        # that waits on queue room must not stall health polling or the
        # failover drain that the resubmissions themselves depend on
        threading.Thread(
            target=self._become_active,
            args=(int(epoch),),
            name=f"tft-router-takeover-{self.name}",
            daemon=True,
        ).start()

    def _become_active(self, epoch: int) -> None:
        try:
            self.epoch = epoch
            self.fleet.router_epoch = epoch
            self.wal.open(epoch)
            recovered = self.wal.recover() if epoch > 0 else {}
            if epoch > 0:
                _m_takeovers.inc()
                _flight.record(
                    "router_ha", "takeover", router=self.name,
                    epoch=epoch, recovered=len(recovered),
                )
                logger.warning(
                    "router_ha: %s won the router lease at epoch %d "
                    "(takeover; %d journaled request(s) to rebuild)",
                    self.name, epoch, len(recovered),
                )
            else:
                logger.warning(
                    "router_ha: %s won the router lease at epoch 0 "
                    "(first activation)", self.name,
                )
            for rid, st in recovered.items():
                self._rebuild_one(rid, st)
        finally:
            with self._lock:
                self._taking_over = False
                # a lease lost DURING takeover leaves us fenced, not
                # active — the winner of epoch+2 owns these requests now
                if not self.fenced:
                    self.active = True

    def _rebuild_one(self, rid: str, st: Dict[str, Any]) -> None:
        record = st["record"]
        entry = self.wal.admit_recovered(
            rid, record, st["tokens"], st["done"], st["error"]
        )
        if entry.done:
            return  # servable for resume; nothing to regenerate
        try:
            kwargs: Dict[str, Any] = dict(
                temperature=float(record.get("temperature", 0.0)),
                top_p=float(record.get("top_p", 1.0)),
                seed=int(record.get("seed", 0)),
                block=True,
                timeout=10.0,
            )
            if record.get("eos_id") is not None:
                kwargs["eos_id"] = int(record["eos_id"])
            if record.get("session"):
                kwargs["session"] = str(record["session"])
            if record.get("tenant") is not None:
                kwargs["tenant"] = str(record["tenant"])
            handle = self.fleet.submit(
                [int(t) for t in record.get("prompt") or []],
                int(record.get("max_new", 1)),
                _resume_tokens=list(entry.tokens),
                **kwargs,
            )
        except Exception as e:
            logger.warning(
                "router_ha: takeover resubmission of %r failed: %s",
                rid, _first_line(e),
            )
            self.wal.fail(rid, e)
            return
        self.resumed_requests += 1
        # binding also covers the instantly-complete resume (the prefix
        # already covered the budget): _finish put the DONE sentinel in
        # the handle's queue, so the pump settles the entry right away
        self.wal.bind(entry, handle)
        _flight.record(
            "router_ha", "resume", router=self.name, rid=rid,
            delivered=len(entry.tokens),
        )

    def _on_lease_lost(self, key: str, epoch: int, cur) -> None:
        if key != ROUTER_LEASE_KEY:
            return
        with self._lock:
            self.active = False
            self.fenced = True
        # fleet.router_epoch stays at the superseded value ON PURPOSE:
        # any placement this zombie still makes carries the stale epoch
        # and is rejected member-side (StaleRouterEpochError)
        _flight.record(
            "router_ha", "lease_lost", router=self.name, epoch=epoch,
            holder=None if cur is None else cur.worker,
        )
        logger.warning(
            "router_ha: %s lost the router lease at epoch %d (fenced; "
            "admission stopped — a standby is taking over)",
            self.name, epoch,
        )

    # -- introspection / lifecycle ----------------------------------------

    def statusz_view(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "active": self.active,
            "fenced": self.fenced,
            "epoch": self.epoch,
            "lease_ttl_s": self.store.ttl_s,
            "resumed_requests": self.resumed_requests,
            "wal_enabled": enabled(),
            "wal": self.wal.statusz_view(),
        }

    def stop(self) -> None:
        """Stop journaling and heartbeating WITHOUT unlinking the
        election lease: the epoch lineage must survive this process —
        a successor acquires epoch+1 after the TTL, and unlinking would
        reset epochs to 0 (breaking zombie fencing forever after)."""
        self.wal.stop()
        self.store.stop(unlink_held=False)


def attach_router_ha(
    fleet,
    path: str,
    *,
    name: Optional[str] = None,
    ttl_s: Optional[float] = None,
) -> RouterHA:
    """Wire router HA onto a fleet router (usually one built by
    :func:`~tensorframes_tpu.serve.membership.connect_fleet` over the
    same ``path``): creates the :class:`RouterHA` state machine,
    exposes it (and its WAL tracker) to the serving layer as
    ``fleet.router_ha`` / ``fleet.wal``, and registers the election
    tick on the fleet watchdog. Requires ``Config.router_wal=True`` to
    actually journal/dedupe/resume — attached-but-gated-off, the
    serving path stays byte-identical to the pre-HA stack."""
    ha = RouterHA(fleet, path, name=name, ttl_s=ttl_s)
    fleet.router_ha = ha
    fleet.wal = ha.wal
    fleet._tick_hooks.append(ha.tick)
    return ha
